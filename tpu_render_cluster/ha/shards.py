"""Shard router: one control-plane front end over N master shards.

The single master ceilings out (NORTHSTAR.md: 3,233 assignments/s at 160
workers) because one event loop serializes every dispatch RPC, result
event, and scheduler tick. Sharding splits the control plane
horizontally: N independent ``master serve`` processes (shards), each
owning a SLICE of the worker pool (workers connect to their shard's
worker port directly — the router never touches the render-traffic
path), with this router as the single submission endpoint.

The router speaks the same JSON-lines protocol as ``sched/control.py``
(so ``python -m tpu_render_cluster.sched.submit`` and shell scripts work
unchanged against it) and routes:

- ``submit`` — stable-hashes the job name (crc32, deterministic across
  processes and runs) onto a shard and forwards; the returned job id is
  prefixed ``s<shard>/`` so later ops route without a lookup table;
- ``status``/``cancel`` with a ``s<shard>/job-NNNN`` id — routed to the
  owning shard (the prefix is stripped before forwarding);
- ``status`` (global), ``alerts``, ``drain``, ``ping`` — fanned out to
  every shard and aggregated under ``shards``.

CLI::

    python -m tpu_render_cluster.ha.shards --controlPort 9900 \\
        --shards 127.0.0.1:9902,127.0.0.1:9912

Shard health is the operator's concern (each shard exposes its own
``/healthz``); a shard that is down answers requests routed to it with
``ok: false`` and an explanatory error instead of taking the router down.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import zlib
from typing import Any

from tpu_render_cluster.obs import MetricsRegistry, get_registry
from tpu_render_cluster.sched.control import MAX_LINE_BYTES, control_request

logger = logging.getLogger(__name__)


def shard_for_job_name(job_name: str, shard_count: int) -> int:
    """Deterministic job->shard placement (crc32: stable across Python
    processes, unlike ``hash``, so a resubmitted or re-routed status
    query lands on the same shard every time)."""
    if shard_count <= 0:
        raise ValueError("shard_count must be positive")
    return zlib.crc32(job_name.encode("utf-8")) % shard_count


def split_routed_job_id(job_id: str) -> tuple[int, str] | None:
    """``"s2/job-0007"`` -> ``(2, "job-0007")``; None when unprefixed."""
    if not job_id.startswith("s"):
        return None
    head, sep, rest = job_id.partition("/")
    if not sep or not rest:
        return None
    try:
        return int(head[1:]), rest
    except ValueError:
        return None


class ShardRouter:
    """Routing logic over a list of shard control endpoints."""

    def __init__(
        self,
        shards: list[tuple[str, int]],
        *,
        timeout: float = 30.0,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        if not shards:
            raise ValueError("ShardRouter needs at least one shard")
        self.shards = shards
        self.timeout = timeout
        self.metrics = metrics if metrics is not None else get_registry()
        self._requests = self.metrics.counter(
            "ha_router_requests_total",
            "Control requests through the shard router, by op and shard "
            "('all' for fan-outs)",
            labels=("op", "shard"),
        )
        self._routed_jobs = self.metrics.counter(
            "ha_router_jobs_routed_total",
            "Submissions hashed onto each shard",
            labels=("shard",),
        )

    def shard_for(self, job_name: str) -> int:
        return shard_for_job_name(job_name, len(self.shards))

    async def _forward(
        self, shard: int, request: dict[str, Any]
    ) -> dict[str, Any]:
        host, port = self.shards[shard]
        try:
            return await control_request(
                host, port, request, timeout=self.timeout
            )
        except (OSError, ValueError, ConnectionError, asyncio.TimeoutError) as e:
            return {
                "ok": False,
                "error": f"shard {shard} ({host}:{port}) unreachable: {e}",
                "shard": shard,
            }

    async def _fan_out(self, request: dict[str, Any]) -> list[dict[str, Any]]:
        return list(
            await asyncio.gather(
                *(self._forward(i, request) for i in range(len(self.shards)))
            )
        )

    async def handle_request(self, request: dict[str, Any]) -> dict[str, Any]:
        op = request.get("op")
        if op == "submit":
            spec = request.get("spec") or {}
            job_name = ((spec.get("job") or {}).get("job_name"))
            if not isinstance(job_name, str) or not job_name:
                return {"ok": False, "error": "submit spec has no job_name"}
            shard = self.shard_for(job_name)
            self._requests.inc(op="submit", shard=str(shard))
            self._routed_jobs.inc(shard=str(shard))
            response = await self._forward(shard, request)
            if response.get("ok") and isinstance(response.get("job_id"), str):
                # Prefix the shard so every later op routes statelessly.
                response = {
                    **response,
                    "job_id": f"s{shard}/{response['job_id']}",
                    "shard": shard,
                }
            return response
        if op in ("status", "cancel") and isinstance(request.get("job_id"), str):
            routed = split_routed_job_id(request["job_id"])
            if routed is None:
                return {
                    "ok": False,
                    "error": f"job_id {request['job_id']!r} is not shard-"
                    "routed (expected 's<shard>/job-NNNN')",
                }
            shard, inner_id = routed
            if not 0 <= shard < len(self.shards):
                return {"ok": False, "error": f"unknown shard in job_id: {shard}"}
            self._requests.inc(op=str(op), shard=str(shard))
            return await self._forward(shard, {**request, "job_id": inner_id})
        if op in ("status", "alerts", "drain", "ping"):
            # Global fan-out, aggregated per shard.
            self._requests.inc(op=str(op), shard="all")
            responses = await self._fan_out(request)
            return {
                "ok": all(r.get("ok") for r in responses),
                "shards": {str(i): r for i, r in enumerate(responses)},
            }
        return {"ok": False, "error": f"unknown op: {op!r}"}


class ShardRouterServer:
    """JSON-lines TCP front end over a ``ShardRouter`` (the shard-side
    twin of ``sched/control.py``'s ``ControlServer``)."""

    def __init__(
        self, router: ShardRouter, host: str = "127.0.0.1", port: int = 0
    ) -> None:
        self.router = router
        self.host = host
        self.port = port
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=MAX_LINE_BYTES
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info(
            "Shard router listening on %s:%d over %d shard(s)",
            self.host,
            self.port,
            len(self.router.shards),
        )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("Shard router close timed out.")

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    request = json.loads(line)
                    if not isinstance(request, dict):
                        raise ValueError("request must be a JSON object")
                except (json.JSONDecodeError, ValueError) as e:
                    response: dict[str, Any] = {
                        "ok": False,
                        "error": f"bad request: {e}",
                    }
                else:
                    response = await self.router.handle_request(request)
                writer.write(json.dumps(response).encode("utf-8") + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 - one client must not kill routing
            logger.warning("Router connection from %s failed: %s", peer, e)
        finally:
            writer.close()


def parse_shard_list(text: str) -> list[tuple[str, int]]:
    """``"h1:9902,h2:9902"`` -> ``[("h1", 9902), ("h2", 9902)]``."""
    shards: list[tuple[str, int]] = []
    for chunk in text.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        host, sep, port = chunk.rpartition(":")
        if not sep:
            raise ValueError(f"shard {chunk!r} is not host:port")
        shards.append((host, int(port)))
    if not shards:
        raise ValueError("no shards given")
    return shards


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trc-shard-router",
        description="JSON-lines control front end hashing jobs across "
        "master shards",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--controlPort", dest="control_port", type=int, default=9900
    )
    parser.add_argument(
        "--shards",
        required=True,
        help="Comma-separated host:port control endpoints, one per master "
        "shard (the `master serve --controlPort` addresses).",
    )
    parser.add_argument("--timeout", type=float, default=30.0)
    return parser


async def serve(args: argparse.Namespace) -> int:
    router = ShardRouter(
        parse_shard_list(args.shards), timeout=args.timeout
    )
    server = ShardRouterServer(router, args.host, args.control_port)
    await server.start()
    print(
        f"Shard router on {args.host}:{server.port} over "
        f"{len(router.shards)} shard(s): "
        + ", ".join(f"s{i}={h}:{p}" for i, (h, p) in enumerate(router.shards))
    )
    try:
        await asyncio.Event().wait()  # serve until interrupted
    finally:
        await server.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return asyncio.run(serve(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
