"""Shard router: one control-plane front end over N master shards.

The single master ceilings out (NORTHSTAR.md: 3,233 assignments/s at 160
workers) because one event loop serializes every dispatch RPC, result
event, and scheduler tick. Sharding splits the control plane
horizontally: N independent ``master serve`` processes (shards), each
owning a SLICE of the worker pool (workers connect to their shard's
worker port directly — the router never touches the render-traffic
path), with this router as the single submission endpoint.

The router speaks the same JSON-lines protocol as ``sched/control.py``
(so ``python -m tpu_render_cluster.sched.submit`` and shell scripts work
unchanged against it) and routes:

- ``submit`` — stable-hashes the job name (crc32, deterministic across
  processes and runs) onto a shard and forwards; the returned job id is
  prefixed ``s<shard>/`` so later ops route without a lookup table;
- ``status``/``cancel`` with a ``s<shard>/job-NNNN`` id — routed to the
  owning shard (the prefix is stripped before forwarding);
- ``status`` (global), ``alerts``, ``drain``, ``ping`` — fanned out to
  every shard and aggregated under ``shards``; a dead shard degrades to
  ABSENCE from the merge (plus ``ha_router_scrape_failures_total``),
  exactly like the federated ``/metrics`` view;
- ``route_worker`` — where should a worker (re)connect? Answers with the
  least-backlogged live shard's WORKER endpoint (``--shardWorkers``);
  workers whose shard died re-home through this.

With ``--followers`` the router also runs the ``PromotionMonitor``:
shards are liveness-probed, and one that stays unreachable past
``TRC_HA_REPL_PROMOTE_TIMEOUT`` has its most-caught-up ledger follower
(ha/replicate.py) promoted to primary — epoch-fenced against the old
primary's revival — with the shard slot re-pointed at the promoted
process. With ``--rebalance`` (or ``TRC_REBALANCE=1``) the router runs
the hot->cold worker rebalancer (sched/rebalance.py) over the same
control plane.

Federated telemetry (``TelemetryFederation``): with ``--telemetryPort``
and ``--shardTelemetry`` the router additionally serves ``/metrics`` and
``/history`` that fan out to every shard's telemetry endpoints and
re-serve the merged result with each series tagged ``shard="<i>"`` — one
scrape sees the whole replicated control plane (per-shard ledger append
histograms, failover MTTR, queue depths) without N scrape targets. The
federated ``/metrics`` re-emits scraped samples through the 0.0.4 parser
(``parse_prometheus`` -> ``render_sample_line``; HELP/TYPE of remote
series are not retained — untyped samples are valid exposition), with the
router's OWN registry (the ``ha_router_*`` family) rendered first.

CLI::

    python -m tpu_render_cluster.ha.shards --controlPort 9900 \\
        --shards 127.0.0.1:9902,127.0.0.1:9912 \\
        [--telemetryPort 9800 --shardTelemetry 127.0.0.1:9801,127.0.0.1:9811]

Shard health is the operator's concern (each shard exposes its own
``/healthz``); a shard that is down answers requests routed to it with
``ok: false`` and an explanatory error instead of taking the router down
— and a shard whose telemetry endpoint is unreachable degrades to its
absence in the federated view (counted in
``ha_router_scrape_failures_total``), never to a router 500.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import time
import urllib.parse
import urllib.request
import zlib
from typing import TYPE_CHECKING, Any

from tpu_render_cluster.obs import LoopLagMonitor, MetricsRegistry, get_registry
from tpu_render_cluster.utils.env import env_float

if TYPE_CHECKING:
    from tpu_render_cluster.sched.rebalance import Move, ShardLoad
from tpu_render_cluster.obs.prometheus import (
    CONTENT_TYPE,
    parse_prometheus,
    render_prometheus,
    render_sample_line,
)
from tpu_render_cluster.sched.control import MAX_LINE_BYTES, control_request

logger = logging.getLogger(__name__)


def shard_for_job_name(job_name: str, shard_count: int) -> int:
    """Deterministic job->shard placement (crc32: stable across Python
    processes, unlike ``hash``, so a resubmitted or re-routed status
    query lands on the same shard every time)."""
    if shard_count <= 0:
        raise ValueError("shard_count must be positive")
    return zlib.crc32(job_name.encode("utf-8")) % shard_count


def split_routed_job_id(job_id: str) -> tuple[int, str] | None:
    """``"s2/job-0007"`` -> ``(2, "job-0007")``; None when unprefixed."""
    if not job_id.startswith("s"):
        return None
    head, sep, rest = job_id.partition("/")
    if not sep or not rest:
        return None
    try:
        return int(head[1:]), rest
    except ValueError:
        return None


class ShardRouter:
    """Routing logic over a list of shard control endpoints."""

    def __init__(
        self,
        shards: list[tuple[str, int]],
        *,
        worker_endpoints: list[tuple[str, int]] | None = None,
        timeout: float = 30.0,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        if not shards:
            raise ValueError("ShardRouter needs at least one shard")
        if worker_endpoints is not None and len(worker_endpoints) != len(shards):
            raise ValueError(
                f"{len(worker_endpoints)} worker endpoint(s) for "
                f"{len(shards)} shard(s)"
            )
        self.shards = shards
        # Per-shard WORKER (WebSocket) endpoints, in --shards order. Only
        # needed for the ops that point workers somewhere: route_worker
        # (re-homing after a shard death) and rebalance moves.
        self.worker_endpoints = worker_endpoints
        self.timeout = timeout
        self.metrics = metrics if metrics is not None else get_registry()
        self._requests = self.metrics.counter(
            "ha_router_requests_total",
            "Control requests through the shard router, by op and shard "
            "('all' for fan-outs)",
            labels=("op", "shard"),
        )
        self._routed_jobs = self.metrics.counter(
            "ha_router_jobs_routed_total",
            "Submissions hashed onto each shard",
            labels=("shard",),
        )
        # Shared with TelemetryFederation (same name, same labels): a
        # control fan-out degrading a dead shard to absence is the same
        # observable event as a telemetry scrape doing so.
        self._fanout_failures = self.metrics.counter(
            "ha_router_scrape_failures_total",
            "Shard telemetry scrapes that failed (shard absent from the "
            "federated view)",
            labels=("shard",),
        )

    def shard_for(self, job_name: str) -> int:
        return shard_for_job_name(job_name, len(self.shards))

    def update_shard(
        self,
        shard: int,
        control: tuple[str, int],
        worker: tuple[str, int] | None = None,
    ) -> None:
        """Re-point one shard's endpoints (a promotion installed a new
        primary). Routing math is positional, so the keyspace mapping is
        untouched — only the addresses behind slot ``shard`` change."""
        self.shards[shard] = control
        if worker is not None and self.worker_endpoints is not None:
            self.worker_endpoints[shard] = worker

    async def _forward(
        self, shard: int, request: dict[str, Any]
    ) -> dict[str, Any]:
        host, port = self.shards[shard]
        try:
            return await control_request(
                host, port, request, timeout=self.timeout
            )
        except (OSError, ValueError, ConnectionError, asyncio.TimeoutError) as e:
            return {
                "ok": False,
                "error": f"shard {shard} ({host}:{port}) unreachable: {e}",
                "shard": shard,
                "unreachable": True,
            }

    async def _fan_out(self, request: dict[str, Any]) -> list[dict[str, Any]]:
        return list(
            await asyncio.gather(
                *(self._forward(i, request) for i in range(len(self.shards)))
            )
        )

    async def handle_request(self, request: dict[str, Any]) -> dict[str, Any]:
        op = request.get("op")
        if op == "submit":
            spec = request.get("spec") or {}
            job_name = ((spec.get("job") or {}).get("job_name"))
            if not isinstance(job_name, str) or not job_name:
                return {"ok": False, "error": "submit spec has no job_name"}
            shard = self.shard_for(job_name)
            self._requests.inc(op="submit", shard=str(shard))
            self._routed_jobs.inc(shard=str(shard))
            response = await self._forward(shard, request)
            if response.get("ok") and isinstance(response.get("job_id"), str):
                # Prefix the shard so every later op routes statelessly.
                response = {
                    **response,
                    "job_id": f"s{shard}/{response['job_id']}",
                    "shard": shard,
                }
            return response
        if op in ("status", "cancel") and isinstance(request.get("job_id"), str):
            routed = split_routed_job_id(request["job_id"])
            if routed is None:
                return {
                    "ok": False,
                    "error": f"job_id {request['job_id']!r} is not shard-"
                    "routed (expected 's<shard>/job-NNNN')",
                }
            shard, inner_id = routed
            if not 0 <= shard < len(self.shards):
                return {"ok": False, "error": f"unknown shard in job_id: {shard}"}
            self._requests.inc(op=str(op), shard=str(shard))
            return await self._forward(shard, {**request, "job_id": inner_id})
        if op in ("status", "alerts", "drain", "ping"):
            # Global fan-out, aggregated per shard. A dead shard degrades
            # exactly like the federated /metrics view: it is ABSENT from
            # ``shards`` and counted in ha_router_scrape_failures_total —
            # the caller sees the survivors' merged answer, not one
            # shard's connection error poisoning the whole response.
            self._requests.inc(op=str(op), shard="all")
            responses = await self._fan_out(request)
            shards: dict[str, dict[str, Any]] = {}
            unreachable: list[int] = []
            for i, response in enumerate(responses):
                if response.get("unreachable"):
                    unreachable.append(i)
                    self._fanout_failures.inc(shard=str(i))
                    logger.warning(
                        "Fan-out %s: %s", op, response.get("error")
                    )
                    continue
                shards[str(i)] = response
            out: dict[str, Any] = {
                "ok": bool(shards)
                and all(r.get("ok") for r in shards.values()),
                "shards": shards,
            }
            if unreachable:
                out["unreachable"] = unreachable
            return out
        if op == "route_worker":
            # Where should a worker (re)connect? The least-backlogged
            # LIVE shard's worker endpoint — the re-home path workers
            # take when their shard dies (worker --router).
            self._requests.inc(op="route_worker", shard="all")
            if self.worker_endpoints is None:
                return {
                    "ok": False,
                    "error": "router has no --shardWorkers endpoints",
                }
            loads = await self.shard_loads()
            live = [load for load in loads if load.alive]
            if not live:
                return {"ok": False, "error": "no live shards"}
            best = min(live, key=lambda load: load.queue_depth)
            host, port = self.worker_endpoints[best.shard]
            return {"ok": True, "shard": best.shard, "host": host, "port": port}
        return {"ok": False, "error": f"unknown op: {op!r}"}

    async def shard_loads(self) -> "list[ShardLoad]":
        """Every shard's rebalance load summary (dead shards included as
        ``alive=False`` placeholders) — the rebalancer's scrape and
        route_worker's ranking input."""
        from tpu_render_cluster.sched.rebalance import ShardLoad

        responses = await self._fan_out({"op": "status"})
        loads: list[ShardLoad] = []
        for i, response in enumerate(responses):
            view = (response.get("sched") or {}).get("rebalance")
            if not response.get("ok") or not isinstance(view, dict):
                if response.get("unreachable"):
                    self._fanout_failures.inc(shard=str(i))
                loads.append(ShardLoad.dead(i))
                continue
            loads.append(ShardLoad.from_view(i, view))
        return loads


class PromotionMonitor:
    """Automatic failover: probe shards, promote a follower when one dies.

    The router is the only component with a cluster-wide view, so it is
    where "the primary is gone" becomes a decision rather than a stream
    of connection errors. Each shard is probed every
    ``TRC_HA_REPL_PROBE_SECONDS`` (``probe_fn`` injectable — the default
    is a control-plane ping; chaos tests substitute cheaper probes).
    A shard continuously unreachable for ``TRC_HA_REPL_PROMOTE_TIMEOUT``
    seconds with registered followers is declared dead: the monitor
    queries every follower's replication position, picks the MOST
    CAUGHT-UP one (max applied seq — minimizes lost suffix), and sends it
    the ``promote`` op (ha/replicate.py ``PromotableFollower``). The
    promotion epoch-bumps via ``JobLedger.open()``, so a revived old
    primary is fenced on both the worker protocol and the replication
    stream. On success the router's shard table is re-pointed at the new
    primary's control/worker endpoints — the crc32 keyspace mapping is
    positional and survives unchanged — and workers re-home through
    ``route_worker``.

    Detection->serving time is stamped on ``ha_failover_mttr_seconds``
    (the same gauge the single-host failover path stamps) and counted in
    ``ha_router_promotions_total``; each promotion also fires the flight
    recorder's ``promotion`` trigger when one is wired.
    """

    def __init__(
        self,
        router: ShardRouter,
        followers: dict[int, list[tuple[str, int]]],
        *,
        probe_fn: Any = None,
        probe_interval: float | None = None,
        promote_timeout: float | None = None,
        flightrec: Any = None,
    ) -> None:
        self.router = router
        # shard index -> PromotableFollower control endpoints.
        self.followers = followers
        self.probe_fn = probe_fn
        self.probe_interval = (
            probe_interval
            if probe_interval is not None
            else max(0.05, env_float("TRC_HA_REPL_PROBE_SECONDS", 0.5))
        )
        self.promote_timeout = (
            promote_timeout
            if promote_timeout is not None
            else max(0.1, env_float("TRC_HA_REPL_PROMOTE_TIMEOUT", 2.0))
        )
        self.flightrec = flightrec
        self.promotions: list[dict[str, Any]] = []
        self._down_since: dict[int, float] = {}
        self._promoting: set[int] = set()
        self._running = False
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(self.run(), name="promotion-monitor")

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    async def run(self) -> None:
        self._running = True
        while self._running:
            await asyncio.sleep(self.probe_interval)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - keep probing through chaos
                logger.warning("Promotion-monitor tick failed: %s", e)

    async def tick(self) -> None:
        """One probe round (tests drive this directly)."""
        now = time.monotonic()
        for shard in range(len(self.router.shards)):
            if shard in self._promoting:
                continue
            if await self._probe(shard):
                self._down_since.pop(shard, None)
                continue
            first = self._down_since.setdefault(shard, now)
            if (
                now - first >= self.promote_timeout
                and self.followers.get(shard)
            ):
                self._promoting.add(shard)
                try:
                    await self._promote(shard, detected_at=first)
                finally:
                    self._promoting.discard(shard)

    async def _probe(self, shard: int) -> bool:
        if self.probe_fn is not None:
            return bool(await self.probe_fn(shard, *self.router.shards[shard]))
        host, port = self.router.shards[shard]
        try:
            response = await control_request(
                host, port, {"op": "ping"}, timeout=self.probe_interval * 2
            )
            return bool(response.get("ok"))
        except (OSError, ValueError, ConnectionError, asyncio.TimeoutError):
            return False

    async def _follower_status(
        self, host: str, port: int
    ) -> dict[str, Any] | None:
        try:
            response = await control_request(
                host, port, {"op": "status"}, timeout=self.probe_interval * 4
            )
        except (OSError, ValueError, ConnectionError, asyncio.TimeoutError):
            return None
        return response if response.get("ok") else None

    async def _promote(self, shard: int, *, detected_at: float) -> None:
        # Most-caught-up follower wins: every record it holds is one the
        # dead primary fsynced, so max applied seq = min lost suffix.
        candidates = []
        for host, port in self.followers.get(shard, []):
            status = await self._follower_status(host, port)
            if status is None or status.get("fenced"):
                continue
            candidates.append((int(status.get("last_seq", -1)), host, port))
        if not candidates:
            logger.error(
                "Shard %d is dead but no follower is reachable; cannot "
                "promote.", shard,
            )
            return
        last_seq, host, port = max(candidates)
        logger.warning(
            "Shard %d unreachable for %.2fs; promoting follower %s:%d "
            "(applied seq %d).",
            shard, time.monotonic() - detected_at, host, port, last_seq,
        )
        try:
            response = await control_request(
                host, port, {"op": "promote"}, timeout=self.router.timeout
            )
        except (OSError, ValueError, ConnectionError, asyncio.TimeoutError) as e:
            logger.error("Promote of %s:%d failed: %s", host, port, e)
            return
        if not response.get("ok"):
            logger.error(
                "Promote of %s:%d refused: %s", host, port,
                response.get("error"),
            )
            return
        mttr = time.monotonic() - detected_at
        record: dict[str, Any] = {
            "shard": shard,
            "follower": f"{host}:{port}",
            "epoch": response.get("epoch"),
            "replayed_seq": response.get("replayed_seq"),
            "mttr_seconds": mttr,
        }
        if response.get("serving"):
            new_control = (str(response["host"]), int(response["control_port"]))
            new_worker = (str(response["host"]), int(response["port"]))
            self.router.update_shard(shard, new_control, new_worker)
            record["control"] = f"{new_control[0]}:{new_control[1]}"
            record["worker"] = f"{new_worker[0]}:{new_worker[1]}"
        self.promotions.append(record)
        self._down_since.pop(shard, None)
        # Satellite: router-driven promotions stamp the SAME MTTR gauge
        # the single-host standby path stamps — one series answers "how
        # fast does this cluster recover" regardless of the failover path.
        self.router.metrics.gauge(
            "ha_failover_mttr_seconds",
            "Seconds from primary-death detection to a promoted "
            "replacement serving",
        ).set(mttr)
        self.router.metrics.counter(
            "ha_router_promotions_total",
            "Followers promoted to shard primary by the router",
            labels=("shard",),
        ).inc(shard=str(shard))
        if self.flightrec is not None:
            from tpu_render_cluster.obs.flightrec import TRIGGER_PROMOTION

            self.flightrec.trigger(TRIGGER_PROMOTION, dict(record))
        logger.warning(
            "Shard %d promoted: %s (epoch %s, %.3fs after detection).",
            shard, record["follower"], record.get("epoch"), mttr,
        )


_JSON_CONTENT_TYPE = "application/json; charset=utf-8"


class TelemetryFederation:
    """Fan-out scraper over every shard's telemetry endpoints.

    Serves (through ``TelemetryServer`` ``extra_routes``) a federated
    ``/metrics`` and ``/history``: each shard is scraped concurrently,
    its series re-tagged ``shard="<i>"``, and the merge re-served as one
    document. Reuses the exposition parser/renderer (obs/prometheus.py)
    so label escaping survives the round trip.
    """

    def __init__(
        self,
        telemetry_endpoints: list[tuple[str, int]],
        *,
        metrics: MetricsRegistry | None = None,
        timeout: float = 5.0,
    ) -> None:
        if not telemetry_endpoints:
            raise ValueError("TelemetryFederation needs at least one endpoint")
        self.endpoints = telemetry_endpoints
        self.timeout = timeout
        self.metrics = metrics if metrics is not None else get_registry()
        self._scrapes = self.metrics.counter(
            "ha_router_scrapes_total",
            "Federated telemetry scrapes issued to shards, by path",
            labels=("path", "shard"),
        )
        self._scrape_failures = self.metrics.counter(
            "ha_router_scrape_failures_total",
            "Shard telemetry scrapes that failed (shard absent from the "
            "federated view)",
            labels=("shard",),
        )

    async def _fetch(self, shard: int, path_and_query: str) -> str | None:
        host, port = self.endpoints[shard]
        url = f"http://{host}:{port}{path_and_query}"
        self._scrapes.inc(
            path=path_and_query.partition("?")[0], shard=str(shard)
        )

        def get() -> str:
            with urllib.request.urlopen(url, timeout=self.timeout) as response:
                return response.read().decode("utf-8")

        try:
            return await asyncio.to_thread(get)
        except Exception as e:  # noqa: BLE001 - a dead shard degrades, not breaks
            logger.warning("Shard %d telemetry scrape %s failed: %s", shard, url, e)
            self._scrape_failures.inc(shard=str(shard))
            return None

    @staticmethod
    def _shard_series_key(label_str: str, shard: int) -> str:
        suffix = f"shard={shard}"
        return f"{label_str},{suffix}" if label_str else suffix

    async def federated_metrics(
        self, query: dict[str, str]
    ) -> tuple[int, str, str]:
        """Merged /metrics: router-own families first (typed), then every
        shard's samples re-labeled ``shard="<i>"``."""
        texts = await asyncio.gather(
            *(self._fetch(i, "/metrics") for i in range(len(self.endpoints)))
        )

        def merge() -> str:
            # O(total lines) regex parsing + re-rendering: off-loop, like
            # the built-in /metrics render — the router's event loop also
            # serves control traffic (submit/status/drain) and must not
            # stall for the duration of a big federated scrape.
            lines = [render_prometheus(self.metrics.snapshot()).rstrip("\n")]
            for shard, text in enumerate(texts):
                if text is None:
                    continue
                try:
                    parsed = parse_prometheus(text)
                except ValueError as e:
                    logger.warning(
                        "Shard %d served malformed exposition: %s", shard, e
                    )
                    self._scrape_failures.inc(shard=str(shard))
                    continue
                for name in sorted(parsed):
                    for labels, value in parsed[name]:
                        lines.append(
                            render_sample_line(
                                name, {**labels, "shard": str(shard)}, value
                            )
                        )
            return "\n".join(line for line in lines if line) + "\n"

        return 200, CONTENT_TYPE, await asyncio.to_thread(merge)

    async def federated_history(
        self, query: dict[str, str]
    ) -> tuple[int, str, str]:
        """Merged /history: the query is forwarded verbatim to every
        shard; series responses merge under shard-tagged keys, summary
        responses nest per shard."""
        suffix = "/history"
        if query:
            suffix += "?" + urllib.parse.urlencode(query)
        documents = await asyncio.gather(
            *(self._fetch(i, suffix) for i in range(len(self.endpoints)))
        )
        shards: dict[str, Any] = {}
        merged_series: dict[str, Any] = {}
        merged_rest: dict[str, Any] = {}
        for shard, text in enumerate(documents):
            if text is None:
                shards[str(shard)] = {"ok": False, "error": "unreachable"}
                continue
            try:
                document = json.loads(text)
            except json.JSONDecodeError as e:
                shards[str(shard)] = {"ok": False, "error": f"bad JSON: {e}"}
                self._scrape_failures.inc(shard=str(shard))
                continue
            if isinstance(document.get("series"), dict):
                for label_str, series in document["series"].items():
                    merged_series[
                        self._shard_series_key(label_str, shard)
                    ] = series
                # Echo the query shape, not per-shard aggregates (a single
                # shard's "merged" quantile would masquerade as global).
                merged_rest = {
                    k: document[k]
                    for k in ("name", "kind", "query", "seconds", "q")
                    if k in document
                }
                shards[str(shard)] = {"ok": bool(document.get("ok", True))}
            else:
                shards[str(shard)] = document
        payload: dict[str, Any] = {
            "ok": all(bool(entry.get("ok", True)) for entry in shards.values()),
            "federated": True,
            "shards": shards,
        }
        if merged_series:
            payload.update(merged_rest)
            payload["series"] = merged_series
        return 200, _JSON_CONTENT_TYPE, json.dumps(payload, default=str)


class ShardRouterServer:
    """JSON-lines TCP front end over a ``ShardRouter`` (the shard-side
    twin of ``sched/control.py``'s ``ControlServer``)."""

    def __init__(
        self, router: ShardRouter, host: str = "127.0.0.1", port: int = 0
    ) -> None:
        self.router = router
        self.host = host
        self.port = port
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=MAX_LINE_BYTES
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info(
            "Shard router listening on %s:%d over %d shard(s)",
            self.host,
            self.port,
            len(self.router.shards),
        )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("Shard router close timed out.")

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    request = json.loads(line)
                    if not isinstance(request, dict):
                        raise ValueError("request must be a JSON object")
                except (json.JSONDecodeError, ValueError) as e:
                    response: dict[str, Any] = {
                        "ok": False,
                        "error": f"bad request: {e}",
                    }
                else:
                    response = await self.router.handle_request(request)
                writer.write(json.dumps(response).encode("utf-8") + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 - one client must not kill routing
            logger.warning("Router connection from %s failed: %s", peer, e)
        finally:
            writer.close()


def parse_shard_list(text: str) -> list[tuple[str, int]]:
    """``"h1:9902,h2:9902"`` -> ``[("h1", 9902), ("h2", 9902)]``."""
    shards: list[tuple[str, int]] = []
    for chunk in text.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        host, sep, port = chunk.rpartition(":")
        if not sep:
            raise ValueError(f"shard {chunk!r} is not host:port")
        shards.append((host, int(port)))
    if not shards:
        raise ValueError("no shards given")
    return shards


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trc-shard-router",
        description="JSON-lines control front end hashing jobs across "
        "master shards",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--controlPort", dest="control_port", type=int, default=9900
    )
    parser.add_argument(
        "--shards",
        required=True,
        help="Comma-separated host:port control endpoints, one per master "
        "shard (the `master serve --controlPort` addresses).",
    )
    parser.add_argument("--timeout", type=float, default=30.0)
    parser.add_argument(
        "--telemetryPort",
        dest="telemetry_port",
        type=int,
        default=None,
        help="Serve FEDERATED telemetry on this port: /metrics and "
        "/history fan out to every --shardTelemetry endpoint and re-serve "
        "the merged series tagged shard=\"<i>\" (0 picks an ephemeral "
        "port). Defaults to the TRC_OBS_ROUTER_PORT environment variable; "
        "omit both to disable.",
    )
    parser.add_argument(
        "--shardTelemetry",
        dest="shard_telemetry",
        default=None,
        help="Comma-separated host:port TELEMETRY endpoints, one per "
        "shard in --shards order (each master's --telemetryPort address). "
        "Required when --telemetryPort is set.",
    )
    parser.add_argument(
        "--shardWorkers",
        dest="shard_workers",
        default=None,
        help="Comma-separated host:port WORKER (WebSocket) endpoints, one "
        "per shard in --shards order. Enables the route_worker op (worker "
        "re-homing after a shard death) and --rebalance moves.",
    )
    parser.add_argument(
        "--followers",
        default=None,
        help="Ledger-follower control endpoints for automatic promotion: "
        "semicolon-separated per-shard groups in --shards order, each a "
        "comma-separated host:port list (ha.replicate --controlPort "
        "addresses); an empty group means that shard has no follower. "
        "Example: '127.0.0.1:9905;;127.0.0.1:9925' gives shards 0 and 2 "
        "one follower each.",
    )
    parser.add_argument(
        "--rebalance",
        action="store_true",
        help="Run the hot->cold worker rebalancer (sched/rebalance.py); "
        "requires --shardWorkers. Also enabled by TRC_REBALANCE=1.",
    )
    return parser


async def execute_move(router: ShardRouter, move: "Move") -> int:
    """Execute one rebalance move: tell the hot shard's control plane to
    shed ``move.count`` workers toward the cold shard's worker endpoint.
    Returns how many workers the hot shard reported migrating."""
    if router.worker_endpoints is None:
        return 0
    host, port = router.worker_endpoints[move.target]
    response = await router._forward(
        move.source,
        {
            "op": "migrate_workers",
            "count": move.count,
            "host": host,
            "port": port,
            "reason": f"rebalance->s{move.target}",
        },
    )
    if not response.get("ok"):
        logger.warning(
            "Rebalance move on shard %d failed: %s",
            move.source, response.get("error"),
        )
        return 0
    return int(response.get("migrating", 0))


def parse_follower_groups(text: str) -> dict[int, list[tuple[str, int]]]:
    """``"h:9905;;h:9925"`` -> ``{0: [("h", 9905)], 2: [("h", 9925)]}``."""
    groups: dict[int, list[tuple[str, int]]] = {}
    for shard, chunk in enumerate(text.split(";")):
        chunk = chunk.strip()
        if chunk:
            groups[shard] = parse_shard_list(chunk)
    return groups


async def serve(args: argparse.Namespace) -> int:
    from tpu_render_cluster.obs.http import TelemetryServer, resolve_telemetry_port
    from tpu_render_cluster.sched.rebalance import RebalanceLoop, rebalance_enabled

    router = ShardRouter(
        parse_shard_list(args.shards),
        worker_endpoints=(
            parse_shard_list(args.shard_workers) if args.shard_workers else None
        ),
        timeout=args.timeout,
    )
    server = ShardRouterServer(router, args.host, args.control_port)
    await server.start()
    # The router is one asyncio loop fronting every shard: a stall here
    # delays ALL shards' control traffic, so its lag is worth a series.
    loopmon = LoopLagMonitor(router.metrics, role="router")
    loopmon.start()
    telemetry = None
    telemetry_port = resolve_telemetry_port(
        args.telemetry_port, "TRC_OBS_ROUTER_PORT"
    )
    if telemetry_port is not None:
        if not args.shard_telemetry:
            raise SystemExit(
                "--telemetryPort needs --shardTelemetry (one telemetry "
                "host:port per shard)"
            )
        endpoints = parse_shard_list(args.shard_telemetry)
        if len(endpoints) != len(router.shards):
            raise SystemExit(
                f"--shardTelemetry lists {len(endpoints)} endpoint(s) for "
                f"{len(router.shards)} shard(s)"
            )
        federation = TelemetryFederation(
            endpoints, metrics=router.metrics, timeout=args.timeout
        )
        telemetry = TelemetryServer(
            router.metrics,
            host=args.host,
            port=telemetry_port,
            healthz_fn=lambda: {
                "role": "shard-router",
                "shards": len(router.shards),
            },
            extra_routes={
                "/metrics": federation.federated_metrics,
                "/history": federation.federated_history,
            },
        )
        await telemetry.start()
        print(
            f"Federated telemetry on {args.host}:{telemetry.port} "
            f"(/metrics + /history across {len(endpoints)} shard(s))"
        )
    monitor = None
    if args.followers:
        monitor = PromotionMonitor(router, parse_follower_groups(args.followers))
        monitor.start()
        print(
            f"Promotion monitor armed over {len(monitor.followers)} "
            f"shard(s) with followers"
        )
    rebalancer = None
    if args.rebalance or rebalance_enabled():
        if router.worker_endpoints is None:
            raise SystemExit("--rebalance needs --shardWorkers")
        rebalancer = RebalanceLoop(
            router.shard_loads,
            lambda move: execute_move(router, move),
            metrics=router.metrics,
        )
        rebalancer.start()
        print("Rebalancer running (hot->cold worker migration)")
    print(
        f"Shard router on {args.host}:{server.port} over "
        f"{len(router.shards)} shard(s): "
        + ", ".join(f"s{i}={h}:{p}" for i, (h, p) in enumerate(router.shards))
    )
    try:
        await asyncio.Event().wait()  # serve until interrupted
    finally:
        if rebalancer is not None:
            await rebalancer.stop()
        if monitor is not None:
            await monitor.stop()
        await loopmon.stop()
        if telemetry is not None:
            await telemetry.stop()
        await server.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return asyncio.run(serve(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
