"""Shard router: one control-plane front end over N master shards.

The single master ceilings out (NORTHSTAR.md: 3,233 assignments/s at 160
workers) because one event loop serializes every dispatch RPC, result
event, and scheduler tick. Sharding splits the control plane
horizontally: N independent ``master serve`` processes (shards), each
owning a SLICE of the worker pool (workers connect to their shard's
worker port directly — the router never touches the render-traffic
path), with this router as the single submission endpoint.

The router speaks the same JSON-lines protocol as ``sched/control.py``
(so ``python -m tpu_render_cluster.sched.submit`` and shell scripts work
unchanged against it) and routes:

- ``submit`` — stable-hashes the job name (crc32, deterministic across
  processes and runs) onto a shard and forwards; the returned job id is
  prefixed ``s<shard>/`` so later ops route without a lookup table;
- ``status``/``cancel`` with a ``s<shard>/job-NNNN`` id — routed to the
  owning shard (the prefix is stripped before forwarding);
- ``status`` (global), ``alerts``, ``drain``, ``ping`` — fanned out to
  every shard and aggregated under ``shards``.

Federated telemetry (``TelemetryFederation``): with ``--telemetryPort``
and ``--shardTelemetry`` the router additionally serves ``/metrics`` and
``/history`` that fan out to every shard's telemetry endpoints and
re-serve the merged result with each series tagged ``shard="<i>"`` — one
scrape sees the whole replicated control plane (per-shard ledger append
histograms, failover MTTR, queue depths) without N scrape targets. The
federated ``/metrics`` re-emits scraped samples through the 0.0.4 parser
(``parse_prometheus`` -> ``render_sample_line``; HELP/TYPE of remote
series are not retained — untyped samples are valid exposition), with the
router's OWN registry (the ``ha_router_*`` family) rendered first.

CLI::

    python -m tpu_render_cluster.ha.shards --controlPort 9900 \\
        --shards 127.0.0.1:9902,127.0.0.1:9912 \\
        [--telemetryPort 9800 --shardTelemetry 127.0.0.1:9801,127.0.0.1:9811]

Shard health is the operator's concern (each shard exposes its own
``/healthz``); a shard that is down answers requests routed to it with
``ok: false`` and an explanatory error instead of taking the router down
— and a shard whose telemetry endpoint is unreachable degrades to its
absence in the federated view (counted in
``ha_router_scrape_failures_total``), never to a router 500.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import urllib.parse
import urllib.request
import zlib
from typing import Any

from tpu_render_cluster.obs import LoopLagMonitor, MetricsRegistry, get_registry
from tpu_render_cluster.obs.prometheus import (
    CONTENT_TYPE,
    parse_prometheus,
    render_prometheus,
    render_sample_line,
)
from tpu_render_cluster.sched.control import MAX_LINE_BYTES, control_request

logger = logging.getLogger(__name__)


def shard_for_job_name(job_name: str, shard_count: int) -> int:
    """Deterministic job->shard placement (crc32: stable across Python
    processes, unlike ``hash``, so a resubmitted or re-routed status
    query lands on the same shard every time)."""
    if shard_count <= 0:
        raise ValueError("shard_count must be positive")
    return zlib.crc32(job_name.encode("utf-8")) % shard_count


def split_routed_job_id(job_id: str) -> tuple[int, str] | None:
    """``"s2/job-0007"`` -> ``(2, "job-0007")``; None when unprefixed."""
    if not job_id.startswith("s"):
        return None
    head, sep, rest = job_id.partition("/")
    if not sep or not rest:
        return None
    try:
        return int(head[1:]), rest
    except ValueError:
        return None


class ShardRouter:
    """Routing logic over a list of shard control endpoints."""

    def __init__(
        self,
        shards: list[tuple[str, int]],
        *,
        timeout: float = 30.0,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        if not shards:
            raise ValueError("ShardRouter needs at least one shard")
        self.shards = shards
        self.timeout = timeout
        self.metrics = metrics if metrics is not None else get_registry()
        self._requests = self.metrics.counter(
            "ha_router_requests_total",
            "Control requests through the shard router, by op and shard "
            "('all' for fan-outs)",
            labels=("op", "shard"),
        )
        self._routed_jobs = self.metrics.counter(
            "ha_router_jobs_routed_total",
            "Submissions hashed onto each shard",
            labels=("shard",),
        )

    def shard_for(self, job_name: str) -> int:
        return shard_for_job_name(job_name, len(self.shards))

    async def _forward(
        self, shard: int, request: dict[str, Any]
    ) -> dict[str, Any]:
        host, port = self.shards[shard]
        try:
            return await control_request(
                host, port, request, timeout=self.timeout
            )
        except (OSError, ValueError, ConnectionError, asyncio.TimeoutError) as e:
            return {
                "ok": False,
                "error": f"shard {shard} ({host}:{port}) unreachable: {e}",
                "shard": shard,
            }

    async def _fan_out(self, request: dict[str, Any]) -> list[dict[str, Any]]:
        return list(
            await asyncio.gather(
                *(self._forward(i, request) for i in range(len(self.shards)))
            )
        )

    async def handle_request(self, request: dict[str, Any]) -> dict[str, Any]:
        op = request.get("op")
        if op == "submit":
            spec = request.get("spec") or {}
            job_name = ((spec.get("job") or {}).get("job_name"))
            if not isinstance(job_name, str) or not job_name:
                return {"ok": False, "error": "submit spec has no job_name"}
            shard = self.shard_for(job_name)
            self._requests.inc(op="submit", shard=str(shard))
            self._routed_jobs.inc(shard=str(shard))
            response = await self._forward(shard, request)
            if response.get("ok") and isinstance(response.get("job_id"), str):
                # Prefix the shard so every later op routes statelessly.
                response = {
                    **response,
                    "job_id": f"s{shard}/{response['job_id']}",
                    "shard": shard,
                }
            return response
        if op in ("status", "cancel") and isinstance(request.get("job_id"), str):
            routed = split_routed_job_id(request["job_id"])
            if routed is None:
                return {
                    "ok": False,
                    "error": f"job_id {request['job_id']!r} is not shard-"
                    "routed (expected 's<shard>/job-NNNN')",
                }
            shard, inner_id = routed
            if not 0 <= shard < len(self.shards):
                return {"ok": False, "error": f"unknown shard in job_id: {shard}"}
            self._requests.inc(op=str(op), shard=str(shard))
            return await self._forward(shard, {**request, "job_id": inner_id})
        if op in ("status", "alerts", "drain", "ping"):
            # Global fan-out, aggregated per shard.
            self._requests.inc(op=str(op), shard="all")
            responses = await self._fan_out(request)
            return {
                "ok": all(r.get("ok") for r in responses),
                "shards": {str(i): r for i, r in enumerate(responses)},
            }
        return {"ok": False, "error": f"unknown op: {op!r}"}


_JSON_CONTENT_TYPE = "application/json; charset=utf-8"


class TelemetryFederation:
    """Fan-out scraper over every shard's telemetry endpoints.

    Serves (through ``TelemetryServer`` ``extra_routes``) a federated
    ``/metrics`` and ``/history``: each shard is scraped concurrently,
    its series re-tagged ``shard="<i>"``, and the merge re-served as one
    document. Reuses the exposition parser/renderer (obs/prometheus.py)
    so label escaping survives the round trip.
    """

    def __init__(
        self,
        telemetry_endpoints: list[tuple[str, int]],
        *,
        metrics: MetricsRegistry | None = None,
        timeout: float = 5.0,
    ) -> None:
        if not telemetry_endpoints:
            raise ValueError("TelemetryFederation needs at least one endpoint")
        self.endpoints = telemetry_endpoints
        self.timeout = timeout
        self.metrics = metrics if metrics is not None else get_registry()
        self._scrapes = self.metrics.counter(
            "ha_router_scrapes_total",
            "Federated telemetry scrapes issued to shards, by path",
            labels=("path", "shard"),
        )
        self._scrape_failures = self.metrics.counter(
            "ha_router_scrape_failures_total",
            "Shard telemetry scrapes that failed (shard absent from the "
            "federated view)",
            labels=("shard",),
        )

    async def _fetch(self, shard: int, path_and_query: str) -> str | None:
        host, port = self.endpoints[shard]
        url = f"http://{host}:{port}{path_and_query}"
        self._scrapes.inc(
            path=path_and_query.partition("?")[0], shard=str(shard)
        )

        def get() -> str:
            with urllib.request.urlopen(url, timeout=self.timeout) as response:
                return response.read().decode("utf-8")

        try:
            return await asyncio.to_thread(get)
        except Exception as e:  # noqa: BLE001 - a dead shard degrades, not breaks
            logger.warning("Shard %d telemetry scrape %s failed: %s", shard, url, e)
            self._scrape_failures.inc(shard=str(shard))
            return None

    @staticmethod
    def _shard_series_key(label_str: str, shard: int) -> str:
        suffix = f"shard={shard}"
        return f"{label_str},{suffix}" if label_str else suffix

    async def federated_metrics(
        self, query: dict[str, str]
    ) -> tuple[int, str, str]:
        """Merged /metrics: router-own families first (typed), then every
        shard's samples re-labeled ``shard="<i>"``."""
        texts = await asyncio.gather(
            *(self._fetch(i, "/metrics") for i in range(len(self.endpoints)))
        )

        def merge() -> str:
            # O(total lines) regex parsing + re-rendering: off-loop, like
            # the built-in /metrics render — the router's event loop also
            # serves control traffic (submit/status/drain) and must not
            # stall for the duration of a big federated scrape.
            lines = [render_prometheus(self.metrics.snapshot()).rstrip("\n")]
            for shard, text in enumerate(texts):
                if text is None:
                    continue
                try:
                    parsed = parse_prometheus(text)
                except ValueError as e:
                    logger.warning(
                        "Shard %d served malformed exposition: %s", shard, e
                    )
                    self._scrape_failures.inc(shard=str(shard))
                    continue
                for name in sorted(parsed):
                    for labels, value in parsed[name]:
                        lines.append(
                            render_sample_line(
                                name, {**labels, "shard": str(shard)}, value
                            )
                        )
            return "\n".join(line for line in lines if line) + "\n"

        return 200, CONTENT_TYPE, await asyncio.to_thread(merge)

    async def federated_history(
        self, query: dict[str, str]
    ) -> tuple[int, str, str]:
        """Merged /history: the query is forwarded verbatim to every
        shard; series responses merge under shard-tagged keys, summary
        responses nest per shard."""
        suffix = "/history"
        if query:
            suffix += "?" + urllib.parse.urlencode(query)
        documents = await asyncio.gather(
            *(self._fetch(i, suffix) for i in range(len(self.endpoints)))
        )
        shards: dict[str, Any] = {}
        merged_series: dict[str, Any] = {}
        merged_rest: dict[str, Any] = {}
        for shard, text in enumerate(documents):
            if text is None:
                shards[str(shard)] = {"ok": False, "error": "unreachable"}
                continue
            try:
                document = json.loads(text)
            except json.JSONDecodeError as e:
                shards[str(shard)] = {"ok": False, "error": f"bad JSON: {e}"}
                self._scrape_failures.inc(shard=str(shard))
                continue
            if isinstance(document.get("series"), dict):
                for label_str, series in document["series"].items():
                    merged_series[
                        self._shard_series_key(label_str, shard)
                    ] = series
                # Echo the query shape, not per-shard aggregates (a single
                # shard's "merged" quantile would masquerade as global).
                merged_rest = {
                    k: document[k]
                    for k in ("name", "kind", "query", "seconds", "q")
                    if k in document
                }
                shards[str(shard)] = {"ok": bool(document.get("ok", True))}
            else:
                shards[str(shard)] = document
        payload: dict[str, Any] = {
            "ok": all(bool(entry.get("ok", True)) for entry in shards.values()),
            "federated": True,
            "shards": shards,
        }
        if merged_series:
            payload.update(merged_rest)
            payload["series"] = merged_series
        return 200, _JSON_CONTENT_TYPE, json.dumps(payload, default=str)


class ShardRouterServer:
    """JSON-lines TCP front end over a ``ShardRouter`` (the shard-side
    twin of ``sched/control.py``'s ``ControlServer``)."""

    def __init__(
        self, router: ShardRouter, host: str = "127.0.0.1", port: int = 0
    ) -> None:
        self.router = router
        self.host = host
        self.port = port
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=MAX_LINE_BYTES
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info(
            "Shard router listening on %s:%d over %d shard(s)",
            self.host,
            self.port,
            len(self.router.shards),
        )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("Shard router close timed out.")

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    request = json.loads(line)
                    if not isinstance(request, dict):
                        raise ValueError("request must be a JSON object")
                except (json.JSONDecodeError, ValueError) as e:
                    response: dict[str, Any] = {
                        "ok": False,
                        "error": f"bad request: {e}",
                    }
                else:
                    response = await self.router.handle_request(request)
                writer.write(json.dumps(response).encode("utf-8") + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 - one client must not kill routing
            logger.warning("Router connection from %s failed: %s", peer, e)
        finally:
            writer.close()


def parse_shard_list(text: str) -> list[tuple[str, int]]:
    """``"h1:9902,h2:9902"`` -> ``[("h1", 9902), ("h2", 9902)]``."""
    shards: list[tuple[str, int]] = []
    for chunk in text.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        host, sep, port = chunk.rpartition(":")
        if not sep:
            raise ValueError(f"shard {chunk!r} is not host:port")
        shards.append((host, int(port)))
    if not shards:
        raise ValueError("no shards given")
    return shards


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trc-shard-router",
        description="JSON-lines control front end hashing jobs across "
        "master shards",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--controlPort", dest="control_port", type=int, default=9900
    )
    parser.add_argument(
        "--shards",
        required=True,
        help="Comma-separated host:port control endpoints, one per master "
        "shard (the `master serve --controlPort` addresses).",
    )
    parser.add_argument("--timeout", type=float, default=30.0)
    parser.add_argument(
        "--telemetryPort",
        dest="telemetry_port",
        type=int,
        default=None,
        help="Serve FEDERATED telemetry on this port: /metrics and "
        "/history fan out to every --shardTelemetry endpoint and re-serve "
        "the merged series tagged shard=\"<i>\" (0 picks an ephemeral "
        "port). Defaults to the TRC_OBS_ROUTER_PORT environment variable; "
        "omit both to disable.",
    )
    parser.add_argument(
        "--shardTelemetry",
        dest="shard_telemetry",
        default=None,
        help="Comma-separated host:port TELEMETRY endpoints, one per "
        "shard in --shards order (each master's --telemetryPort address). "
        "Required when --telemetryPort is set.",
    )
    return parser


async def serve(args: argparse.Namespace) -> int:
    from tpu_render_cluster.obs.http import TelemetryServer, resolve_telemetry_port

    router = ShardRouter(
        parse_shard_list(args.shards), timeout=args.timeout
    )
    server = ShardRouterServer(router, args.host, args.control_port)
    await server.start()
    # The router is one asyncio loop fronting every shard: a stall here
    # delays ALL shards' control traffic, so its lag is worth a series.
    loopmon = LoopLagMonitor(router.metrics, role="router")
    loopmon.start()
    telemetry = None
    telemetry_port = resolve_telemetry_port(
        args.telemetry_port, "TRC_OBS_ROUTER_PORT"
    )
    if telemetry_port is not None:
        if not args.shard_telemetry:
            raise SystemExit(
                "--telemetryPort needs --shardTelemetry (one telemetry "
                "host:port per shard)"
            )
        endpoints = parse_shard_list(args.shard_telemetry)
        if len(endpoints) != len(router.shards):
            raise SystemExit(
                f"--shardTelemetry lists {len(endpoints)} endpoint(s) for "
                f"{len(router.shards)} shard(s)"
            )
        federation = TelemetryFederation(
            endpoints, metrics=router.metrics, timeout=args.timeout
        )
        telemetry = TelemetryServer(
            router.metrics,
            host=args.host,
            port=telemetry_port,
            healthz_fn=lambda: {
                "role": "shard-router",
                "shards": len(router.shards),
            },
            extra_routes={
                "/metrics": federation.federated_metrics,
                "/history": federation.federated_history,
            },
        )
        await telemetry.start()
        print(
            f"Federated telemetry on {args.host}:{telemetry.port} "
            f"(/metrics + /history across {len(endpoints)} shard(s))"
        )
    print(
        f"Shard router on {args.host}:{server.port} over "
        f"{len(router.shards)} shard(s): "
        + ", ".join(f"s{i}={h}:{p}" for i, (h, p) in enumerate(router.shards))
    )
    try:
        await asyncio.Event().wait()  # serve until interrupted
    finally:
        await loopmon.stop()
        if telemetry is not None:
            await telemetry.stop()
        await server.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return asyncio.run(serve(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
