"""Ledger streaming replication: a standby on ANOTHER HOST, no shared disk.

PR 11's failover story required the standby to open the primary's ledger
directory — shared storage. This module removes that requirement: the
primary streams every committed ledger record over the wire and a
follower maintains its own replay-ready replica directory, so promotion
is a plain ``JobLedger.open()`` on the FOLLOWER's local disk.

Three pieces:

- :class:`ReplicationServer` — primary side. A JSON-lines TCP endpoint
  (the sched/control.py idiom: one ``protocol.messages`` envelope per
  line) serving N followers. An attach request carries the follower's
  last contiguous sequence number; the primary answers with its epoch,
  its current head, and — when the follower's position predates the
  compaction floor — the snapshot document, then the backlog records,
  then the live tail (fed by the ledger's post-fsync commit listener, so
  a follower can never observe a record a crash could still un-write).
  Followers ack cumulatively; the primary's per-follower lag gauge is
  derived from the acks.

- :class:`LedgerFollower` — follower side. Tails the stream into a local
  segmented replica (same on-disk format as the primary's, torn-tail
  recovery included), persisting the primary's epoch so a later
  promotion out-fences it. Strictly sequential: a sequence gap, a torn
  mid-stream line, or a record/envelope mismatch aborts the connection
  and re-attaches from the last contiguous record (truncate-and-refetch
  — a partial record is NEVER applied). Epoch-fenced on both ends: the
  primary refuses an attach from a follower that has durably seen a
  NEWER epoch (the primary is deposed), and the follower refuses a
  stream whose epoch is OLDER than its own (a deposed primary revived).

- :class:`PromotableFollower` — the follower's control endpoint. A tiny
  JSON-lines server (``status`` / ``promote`` / ``ping``) the shard
  router's liveness monitor drives: ``promote`` stops the tail, opens
  the replica ledger (epoch bump > every epoch the dead primary ever
  streamed), and hands it to an injected callback that builds the
  serving master — returning the endpoints the router re-routes to.

Tuning (``TRC_HA_REPL_*``, utils/env.py idiom): ``TRC_HA_REPL_ACK_EVERY``
records per cumulative ack, ``TRC_HA_REPL_RETRY_SECONDS`` between
follower re-attach attempts.

CLI: ``python -m tpu_render_cluster.ha.replicate --directory D
--primary HOST:PORT --controlPort C`` runs a follower with its control
endpoint; add ``--servePort``/``--serveControlPort`` to let a promotion
start the full scheduler service from the adopted ledger in-process.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import deque
from pathlib import Path
from typing import Any, Awaitable, Callable

from tpu_render_cluster.ha.ledger import (
    JobLedger,
    LedgerReplay,
    _check_version,
    _fsync_dir,
    _fsync_enabled,
    _segment_max_records,
    _SEGMENT_RE,
)
from tpu_render_cluster.protocol.messages import (
    Message,
    ReplicationAckEvent,
    ReplicationAttachRequest,
    ReplicationAttachResponse,
    ReplicationRecordEvent,
    decode_message,
    encode_message,
)
from tpu_render_cluster.utils.env import env_float, env_int

logger = logging.getLogger(__name__)

MAX_LINE_BYTES = 16 * 1024 * 1024

# Seconds of stream silence before the follower flushes a pending ack
# anyway, keeping the primary's lag gauge fresh between append bursts.
IDLE_ACK_SECONDS = 1.0


def _ack_every() -> int:
    return max(1, env_int("TRC_HA_REPL_ACK_EVERY", 32))


def _retry_seconds() -> float:
    return max(0.01, env_float("TRC_HA_REPL_RETRY_SECONDS", 0.5))


def _encode_line(message: Message) -> bytes:
    return encode_message(message).encode("utf-8") + b"\n"


class ReplicationFencedError(RuntimeError):
    """The attach was refused on epoch grounds — retrying is pointless
    until an operator re-points the follower (or this end IS the stale
    one and must stand down)."""


# ---------------------------------------------------------------------------
# Primary side


class _FollowerStream:
    """One attached follower's live-tail state on the primary."""

    __slots__ = ("follower_id", "queue", "sent_floor", "acked_seq")

    def __init__(self, follower_id: str) -> None:
        self.follower_id = follower_id
        self.queue: asyncio.Queue = asyncio.Queue()
        self.sent_floor = 0  # records <= floor went out with the backlog
        self.acked_seq = 0


class ReplicationServer:
    """Primary-side replication endpoint over an ``open()``'d ledger."""

    def __init__(
        self,
        ledger: JobLedger,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics=None,
    ) -> None:
        self.ledger = ledger
        self.host = host
        self.port = port
        self.metrics = metrics
        self._server: asyncio.Server | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._streams: set[_FollowerStream] = set()
        self._listening = False

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=MAX_LINE_BYTES
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self.ledger.add_commit_listener(self._on_commit)
        self._listening = True
        logger.info(
            "Ledger replication streaming on %s:%d (epoch %d).",
            self.host, self.port, self.ledger.epoch,
        )

    async def stop(self) -> None:
        self._listening = False
        self.ledger.remove_commit_listener(self._on_commit)
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("Replication server close timed out.")
            self._server = None

    # -- live tail feed (called from the appender thread) --------------------

    def _on_commit(self, seq: int, record: dict[str, Any]) -> None:
        loop = self._loop
        if loop is None or loop.is_closed() or not self._listening:
            return
        try:
            loop.call_soon_threadsafe(self._fan_out_record, seq, record)
        except RuntimeError:  # loop shut down between the checks
            pass

    def _fan_out_record(self, seq: int, record: dict[str, Any]) -> None:
        for stream in self._streams:
            stream.queue.put_nowait((seq, record))

    # -- connection handling -------------------------------------------------

    def _count_refused(self, end: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "ha_replication_refused_total",
                "Replication attaches refused on epoch-fencing grounds, "
                "by which end refused (primary = deposed self, follower = "
                "stale stream)",
                labels=("end",),
            ).inc(end=end)

    def _set_follower_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "ha_replication_followers_units",
                "Followers currently attached to this primary's stream",
            ).set(len(self._streams))

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        stream: _FollowerStream | None = None
        sender: asyncio.Task | None = None
        try:
            line = await asyncio.wait_for(reader.readline(), 30.0)
            if not line:
                return
            request = decode_message(line)
            if not isinstance(request, ReplicationAttachRequest):
                logger.warning(
                    "Replication connection from %s opened with %s; closing.",
                    peer, type(request).__name__,
                )
                return
            follower_id = request.follower_id or f"{peer}"
            head = self.ledger.replay.last_seq
            if request.epoch is not None and request.epoch > self.ledger.epoch:
                # The follower has durably seen a NEWER master epoch than
                # ours: we are a deposed primary. Refuse to stream the
                # stale timeline instead of splitting the brain.
                self._count_refused("primary")
                writer.write(_encode_line(ReplicationAttachResponse(
                    request.message_request_id,
                    epoch=self.ledger.epoch,
                    primary_seq=head,
                    error=(
                        f"primary epoch {self.ledger.epoch} predates "
                        f"follower-observed epoch {request.epoch}; this "
                        "primary is deposed"
                    ),
                )))
                await writer.drain()
                return
            # Register the live tail BEFORE the backlog read: a commit
            # landing while the segment files are read off-loop buffers in
            # stream.queue (the sender starts after the backlog goes out),
            # and the sent floor skips whatever the backlog read already
            # covered — no record can land in the crack either way.
            stream = _FollowerStream(follower_id)
            self._streams.add(stream)
            self._set_follower_gauge()
            snapshot, records = await asyncio.to_thread(
                self.ledger.records_since, request.last_seq
            )
            stream.sent_floor = max(
                [request.last_seq]
                + ([int(snapshot["seq"])] if snapshot is not None else [])
                + [int(r["seq"]) for r in records]
            )
            writer.write(_encode_line(ReplicationAttachResponse(
                request.message_request_id,
                epoch=self.ledger.epoch,
                primary_seq=head,
                snapshot=snapshot,
            )))
            if snapshot is not None and self.metrics is not None:
                self.metrics.counter(
                    "ha_replication_snapshots_sent_total",
                    "Ledger snapshots shipped to followers whose attach "
                    "position predated the compaction floor",
                ).inc()
            sent = 0
            for record in records:
                writer.write(_encode_line(
                    ReplicationRecordEvent(int(record["seq"]), record)
                ))
                sent += 1
                if sent % 256 == 0:
                    await writer.drain()
            await writer.drain()
            if self.metrics is not None and sent:
                self.metrics.counter(
                    "ha_replication_records_sent_total",
                    "Ledger records streamed to followers (backlog + live)",
                    labels=("follower",),
                ).inc(sent, follower=follower_id)
            logger.info(
                "Follower %s attached at seq %d (%d backlog record(s)%s).",
                follower_id, request.last_seq, sent,
                ", snapshot" if snapshot is not None else "",
            )
            sender = asyncio.create_task(
                self._pump(stream, writer), name=f"repl-pump-{follower_id}"
            )
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    message = decode_message(line)
                except ValueError:
                    return
                if isinstance(message, ReplicationAckEvent):
                    stream.acked_seq = max(stream.acked_seq, message.seq)
                    if self.metrics is not None:
                        self.metrics.gauge(
                            "ha_replication_lag_units",
                            "Committed records not yet acked by each "
                            "follower (primary head minus cumulative ack)",
                            labels=("follower",),
                        ).set(
                            max(0, self.ledger.replay.last_seq - stream.acked_seq),
                            follower=follower_id,
                        )
        except (
            ConnectionError,
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
            OSError,
            ValueError,
        ) as e:
            logger.info("Replication connection from %s ended: %s", peer, e)
        finally:
            if sender is not None:
                sender.cancel()
                try:
                    await sender
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            if stream is not None:
                self._streams.discard(stream)
                self._set_follower_gauge()
            writer.close()

    async def _pump(
        self, stream: _FollowerStream, writer: asyncio.StreamWriter
    ) -> None:
        """Forward live-committed records to one follower, in order."""
        while True:
            seq, record = await stream.queue.get()
            if seq <= stream.sent_floor:
                continue  # the backlog already carried it
            writer.write(_encode_line(ReplicationRecordEvent(seq, record)))
            await writer.drain()
            if self.metrics is not None:
                self.metrics.counter(
                    "ha_replication_records_sent_total",
                    "Ledger records streamed to followers (backlog + live)",
                    labels=("follower",),
                ).inc(follower=stream.follower_id)


# ---------------------------------------------------------------------------
# Follower side


class LedgerFollower:
    """Tails a primary's record stream into a local replica directory.

    The replica uses the exact ledger on-disk format, so promotion is
    ``JobLedger.open(directory)`` — the epoch bump lands ABOVE every
    epoch the primary ever streamed because each observed epoch is
    persisted to the replica's ``EPOCH`` file as it arrives.
    """

    def __init__(
        self,
        directory: str | Path,
        primary_host: str,
        primary_port: int,
        *,
        metrics=None,
        follower_id: str | None = None,
    ) -> None:
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.primary_host = primary_host
        self.primary_port = primary_port
        self.metrics = metrics
        self.follower_id = follower_id or f"follower-{os.getpid()}"
        self.epoch = JobLedger.peek_epoch(self.directory)
        self.replay = JobLedger.replay_directory(self.directory)
        self.last_seq = self.replay.last_seq
        self.records_applied = 0
        self.fenced = False
        self.promoted = False
        # Chaos hook (``follower_lag`` fault kind): extra seconds slept
        # before each record is applied, simulating a slow replica disk.
        self.inject_delay_seconds = 0.0
        # Raw apply-lag samples (seconds between the primary's append and
        # the follower's durable apply) for the bench's p50/p99 readout.
        self.lag_samples: deque[float] = deque(maxlen=4096)
        self._running = False
        self._task: asyncio.Task | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._segment_file = None
        self._segment_records = 0
        segments = self._segments()
        self._segment_index = segments[-1][0] if segments else 0
        if segments:
            # Same crash repair open() performs: a torn local tail (the
            # follower died mid-append) is truncated back to the last
            # complete record; a complete record that merely lost its
            # newline gets it appended. last_seq already excludes the
            # torn record (replay_directory dropped it).
            probe = JobLedger(self.directory, self.epoch)
            if self.replay.torn_tail:
                probe._truncate_torn_tail(segments[-1][1])
            else:
                probe._repair_missing_newline(segments[-1][1])

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.create_task(
            self.run(), name=f"ledger-follower-{self.follower_id}"
        )

    async def run(self) -> None:
        """Attach-and-stream until stopped or fenced; every failure mode
        (connection loss, gap, torn line) re-attaches from the last
        contiguous record after ``TRC_HA_REPL_RETRY_SECONDS``."""
        self._running = True
        while self._running and not self.fenced:
            try:
                await self._attach_and_stream()
            except ReplicationFencedError as e:
                logger.warning("Follower %s fenced: %s", self.follower_id, e)
                self.fenced = True
                break
            except (
                ConnectionError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
                OSError,
                ValueError,
            ) as e:
                if not self._running:
                    break
                if self.metrics is not None:
                    self.metrics.counter(
                        "ha_replication_reconnects_total",
                        "Follower re-attach attempts after a stream "
                        "failure (connection loss, gap, torn record)",
                    ).inc()
                logger.info(
                    "Follower %s stream ended (%s); re-attaching from seq %d.",
                    self.follower_id, e, self.last_seq,
                )
            try:
                await asyncio.sleep(_retry_seconds())
            except asyncio.CancelledError:
                break

    async def stop(self) -> None:
        self._running = False
        self.abort_connection()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        await asyncio.to_thread(self._close_segment)

    def abort_connection(self) -> None:
        """Hard-drop the current stream connection (chaos
        ``replication_partition``; also part of stop())."""
        writer = self._writer
        if writer is not None:
            transport = writer.transport
            if transport is not None:
                transport.abort()

    async def promote(self, *, metrics=None, flightrec=None) -> JobLedger:
        """Stop tailing and claim the replica for a new master
        incarnation. The returned ledger's epoch is strictly greater
        than every epoch the dead primary ever streamed."""
        await self.stop()
        ledger = await asyncio.to_thread(
            JobLedger.open,
            self.directory,
            metrics=metrics if metrics is not None else self.metrics,
        )
        self.promoted = True
        if flightrec is not None:
            from tpu_render_cluster.obs.flightrec import TRIGGER_PROMOTION

            flightrec.trigger(
                TRIGGER_PROMOTION,
                {
                    "follower_id": self.follower_id,
                    "epoch": ledger.epoch,
                    "replayed_seq": ledger.replay.last_seq,
                },
            )
        logger.info(
            "Follower %s promoted: epoch %d, %d record(s) in the replica.",
            self.follower_id, ledger.epoch, ledger.replay.last_seq,
        )
        return ledger

    # -- stream handling -----------------------------------------------------

    async def _attach_and_stream(self) -> None:
        reader, writer = await asyncio.open_connection(
            self.primary_host, self.primary_port, limit=MAX_LINE_BYTES
        )
        self._writer = writer
        try:
            writer.write(_encode_line(ReplicationAttachRequest.new(
                self.last_seq,
                epoch=self.epoch if self.epoch > 0 else None,
                follower_id=self.follower_id,
            )))
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), 30.0)
            if not line or not line.endswith(b"\n"):
                raise ConnectionError("truncated attach response")
            response = decode_message(line)
            if not isinstance(response, ReplicationAttachResponse):
                raise ValueError(
                    f"expected an attach response, got {type(response).__name__}"
                )
            if response.error is not None:
                # The primary refused us — it knows it is deposed. Its
                # stream is stale; stop tailing it.
                raise ReplicationFencedError(response.error)
            if response.epoch < self.epoch:
                # A deposed primary revived and does NOT know: its epoch
                # is older than one we durably observed. Refuse the
                # stream (the mirror-image fence of the primary's).
                if self.metrics is not None:
                    self.metrics.counter(
                        "ha_replication_refused_total",
                        "Replication attaches refused on epoch-fencing "
                        "grounds, by which end refused (primary = deposed "
                        "self, follower = stale stream)",
                        labels=("end",),
                    ).inc(end="follower")
                raise ReplicationFencedError(
                    f"primary streams epoch {response.epoch} but this "
                    f"replica has durably seen epoch {self.epoch}; "
                    "refusing the stale timeline"
                )
            if response.epoch > self.epoch:
                await asyncio.to_thread(self._persist_epoch, response.epoch)
            if response.snapshot is not None:
                await asyncio.to_thread(
                    self._install_snapshot, response.snapshot
                )
            primary_head = max(response.primary_seq, self.last_seq)
            self._set_lag_gauges(primary_head)
            unacked = 0
            while True:
                try:
                    line = await asyncio.wait_for(
                        reader.readline(), IDLE_ACK_SECONDS
                    )
                except asyncio.TimeoutError:
                    if unacked:
                        writer.write(_encode_line(
                            ReplicationAckEvent(self.last_seq)
                        ))
                        await writer.drain()
                        unacked = 0
                    continue
                if not line:
                    raise ConnectionError("stream closed")
                if not line.endswith(b"\n"):
                    # A torn mid-stream line: the primary (or the network)
                    # died mid-record. NEVER applied — re-attach refetches
                    # from the last contiguous record.
                    self._count_torn()
                    raise ConnectionError("torn record at stream tail")
                try:
                    message = decode_message(line)
                except ValueError as e:
                    self._count_torn()
                    raise ConnectionError(f"malformed stream line: {e}")
                if not isinstance(message, ReplicationRecordEvent):
                    continue
                if message.seq <= self.last_seq:
                    continue  # re-attach overlap; already durable here
                if message.seq != self.last_seq + 1:
                    if self.metrics is not None:
                        self.metrics.counter(
                            "ha_replication_gaps_total",
                            "Sequence gaps detected in the record stream "
                            "(each forces a re-attach + segment re-fetch)",
                        ).inc()
                    raise ConnectionError(
                        f"sequence gap: expected {self.last_seq + 1}, "
                        f"got {message.seq}"
                    )
                record = message.record
                try:
                    record_seq = int(record["seq"])
                except (KeyError, TypeError, ValueError):
                    record_seq = -1
                if record_seq != message.seq:
                    self._count_torn()
                    raise ConnectionError("record/envelope seq mismatch")
                _check_version(record)  # LedgerCorruptError is fatal
                if self.inject_delay_seconds > 0:
                    await asyncio.sleep(self.inject_delay_seconds)
                await asyncio.to_thread(self._append_record, record)
                primary_head = max(primary_head, message.seq)
                self._observe_applied(record, primary_head)
                unacked += 1
                if unacked >= _ack_every():
                    writer.write(_encode_line(
                        ReplicationAckEvent(self.last_seq)
                    ))
                    await writer.drain()
                    unacked = 0
        finally:
            self._writer = None
            writer.close()

    # -- replica persistence -------------------------------------------------

    def _segments(self) -> list[tuple[int, Path]]:
        out = []
        for entry in self.directory.iterdir():
            match = _SEGMENT_RE.match(entry.name)
            if match is not None:
                out.append((int(match.group(1)), entry))
        return sorted(out)

    def _close_segment(self) -> None:
        if self._segment_file is not None:
            try:
                self._segment_file.flush()
                if _fsync_enabled():
                    os.fsync(self._segment_file.fileno())
            finally:
                self._segment_file.close()
                self._segment_file = None

    def _current_segment(self):
        if (
            self._segment_file is not None
            and self._segment_records >= _segment_max_records()
        ):
            self._close_segment()
        if self._segment_file is None:
            self._segment_index += 1
            path = self.directory / f"segment-{self._segment_index:08d}.jsonl"
            self._segment_file = open(path, "a", encoding="utf-8")
            self._segment_records = 0
            _fsync_dir(self.directory)
        return self._segment_file

    def _append_record(self, record: dict[str, Any]) -> None:
        """Durably append one streamed record to the replica (write +
        flush + fsync, the primary's append discipline) and fold it into
        the live replay."""
        line = json.dumps(record, separators=(",", ":")) + "\n"
        f = self._current_segment()
        f.write(line)
        f.flush()
        if _fsync_enabled():
            os.fsync(f.fileno())
        self._segment_records += 1
        self.replay.apply(record)
        seq = int(record["seq"])
        self.replay.last_seq = seq
        self.replay.records += 1
        self.last_seq = seq
        self.records_applied += 1

    def _persist_epoch(self, epoch: int) -> None:
        epoch_path = self.directory / "EPOCH"
        tmp = epoch_path.with_suffix(".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(f"{epoch}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, epoch_path)
        _fsync_dir(self.directory)
        self.epoch = epoch

    def _install_snapshot(self, snapshot: dict[str, Any]) -> None:
        """Reset the replica to a primary-shipped snapshot (our attach
        position predated the primary's compaction floor)."""
        _check_version(snapshot)
        self._close_segment()
        for _, segment_path in self._segments():
            try:
                segment_path.unlink()
            except OSError as e:  # pragma: no cover
                logger.warning("Could not drop %s: %s", segment_path, e)
        path = self.directory / "snapshot.json"
        tmp = path.with_suffix(".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snapshot, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.directory)
        self.replay = LedgerReplay.from_snapshot(snapshot, self.epoch)
        self.last_seq = self.replay.last_seq
        logger.info(
            "Follower %s installed a snapshot at seq %d.",
            self.follower_id, self.last_seq,
        )

    # -- metrics -------------------------------------------------------------

    def _count_torn(self) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "ha_replication_torn_tails_total",
                "Torn or malformed stream lines discarded by the follower "
                "(truncate-and-refetch; a partial record is never applied)",
            ).inc()

    def _set_lag_gauges(self, primary_head: int) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "ha_replication_behind_units",
                "Records the follower still trails the primary's known head",
            ).set(max(0, primary_head - self.last_seq))

    def _observe_applied(
        self, record: dict[str, Any], primary_head: int
    ) -> None:
        lag = max(0.0, time.time() - float(record.get("ts") or time.time()))
        self.lag_samples.append(lag)
        if self.metrics is not None:
            self.metrics.counter(
                "ha_replication_records_applied_total",
                "Records durably applied to the local replica ledger",
            ).inc()
            self.metrics.histogram(
                "ha_replication_lag_seconds",
                "Seconds between the primary's durable append and the "
                "follower's durable apply of the same record",
            ).observe(lag)
        self._set_lag_gauges(primary_head)


# ---------------------------------------------------------------------------
# The follower's control endpoint (what the shard router drives)


class PromotableFollower:
    """JSON-lines ``status``/``promote``/``ping`` frontend over a
    :class:`LedgerFollower`.

    ``promote`` is idempotent: the first call stops the tail, opens the
    replica ledger, and runs the injected ``promote_callback(ledger)``
    (which builds the serving master and returns the endpoints to
    re-route to, e.g. ``{"ok": True, "host": ..., "port": ...,
    "control_port": ...}``); later calls return the cached result, so a
    router retrying through a timeout cannot double-promote.
    """

    def __init__(
        self,
        follower: LedgerFollower,
        *,
        promote_callback: Callable[[JobLedger], Awaitable[dict[str, Any]]] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics=None,
        flightrec=None,
    ) -> None:
        self.follower = follower
        self.promote_callback = promote_callback
        self.host = host
        self.port = port
        self.metrics = metrics
        self.flightrec = flightrec
        self._server: asyncio.Server | None = None
        self._promote_lock = asyncio.Lock()
        self._promote_result: dict[str, Any] | None = None
        self.ledger: JobLedger | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=MAX_LINE_BYTES
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info(
            "Follower control endpoint on %s:%d.", self.host, self.port
        )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("Follower control close timed out.")
            self._server = None

    def status(self) -> dict[str, Any]:
        return {
            "ok": True,
            "follower_id": self.follower.follower_id,
            "last_seq": self.follower.last_seq,
            "epoch": self.follower.epoch,
            "records_applied": self.follower.records_applied,
            "fenced": self.follower.fenced,
            "promoted": self.follower.promoted,
        }

    async def promote(self) -> dict[str, Any]:
        async with self._promote_lock:
            if self._promote_result is not None:
                return self._promote_result
            self.ledger = await self.follower.promote(
                metrics=self.metrics, flightrec=self.flightrec
            )
            if self.promote_callback is not None:
                result = dict(await self.promote_callback(self.ledger))
            else:
                result = {"ok": True}
            result.setdefault("ok", True)
            result["epoch"] = self.ledger.epoch
            result["replayed_seq"] = self.ledger.replay.last_seq
            self._promote_result = result
            return result

    async def handle_request(self, request: dict[str, Any]) -> dict[str, Any]:
        op = request.get("op")
        try:
            if op == "ping":
                return {"ok": True, "pong": True, "role": "ledger-follower"}
            if op == "status":
                return self.status()
            if op == "promote":
                return await self.promote()
            return {"ok": False, "error": f"unknown op: {op!r}"}
        except (ValueError, RuntimeError, KeyError, TypeError, OSError) as e:
            return {"ok": False, "error": str(e)}

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    request = json.loads(line)
                    if not isinstance(request, dict):
                        raise ValueError("request must be a JSON object")
                except (json.JSONDecodeError, ValueError) as e:
                    response: dict[str, Any] = {
                        "ok": False, "error": f"bad request: {e}"
                    }
                else:
                    response = await self.handle_request(request)
                writer.write(json.dumps(response).encode("utf-8") + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 - one bad client is not fatal
            logger.warning("Follower control connection %s failed: %s", peer, e)
        finally:
            writer.close()


# ---------------------------------------------------------------------------
# CLI


def build_parser():
    import argparse

    parser = argparse.ArgumentParser(
        prog="trc-follower",
        description="Ledger replication follower (replica + control endpoint)",
    )
    parser.add_argument(
        "--directory", required=True,
        help="Local replica ledger directory (created if missing).",
    )
    parser.add_argument(
        "--primary", required=True,
        help="HOST:PORT of the primary's replication endpoint "
        "(master --replicationPort).",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--controlPort", dest="control_port", type=int, default=9905,
        help="JSON-lines status/promote endpoint the shard router probes.",
    )
    parser.add_argument(
        "--servePort", dest="serve_port", type=int, default=None,
        help="Worker WebSocket port a promotion binds the scheduler "
        "service to (omit to make promote a ledger-adopt only).",
    )
    parser.add_argument(
        "--serveControlPort", dest="serve_control_port", type=int, default=0,
        help="Scheduler control-plane port of the promoted service.",
    )
    return parser


async def _run_follower(args) -> int:
    from tpu_render_cluster.obs import get_registry

    primary_host, _, primary_port = args.primary.rpartition(":")
    registry = get_registry()
    follower = LedgerFollower(
        args.directory, primary_host or "127.0.0.1", int(primary_port),
        metrics=registry,
    )
    serve_done: asyncio.Event = asyncio.Event()

    async def promote_callback(ledger: JobLedger) -> dict[str, Any]:
        if args.serve_port is None:
            return {"ok": True, "serving": False}
        from tpu_render_cluster.jobs.models import BlenderJob
        from tpu_render_cluster.sched.control import ControlServer
        from tpu_render_cluster.sched.manager import JobManager
        from tpu_render_cluster.sched.models import JobSpec

        manager = JobManager(args.host, args.serve_port, ledger=ledger)
        for entry in ledger.replay.unfinished_jobs():
            if entry.job is None:
                continue
            manager.submit(JobSpec(
                job=BlenderJob.from_dict(entry.job),
                weight=entry.weight,
                priority=entry.priority,
            ))
        control = ControlServer(manager, args.host, args.serve_control_port)
        await control.start()

        async def _serve() -> None:
            try:
                await manager.serve()
            finally:
                await control.stop()
                serve_done.set()

        asyncio.create_task(_serve(), name="promoted-master")
        return {
            "ok": True,
            "serving": True,
            "host": args.host,
            "port": args.serve_port,
            "control_port": control.port,
        }

    endpoint = PromotableFollower(
        follower,
        promote_callback=promote_callback,
        host=args.host,
        port=args.control_port,
        metrics=registry,
    )
    follower.start()
    await endpoint.start()
    print(
        f"Follower tailing {args.primary} into {args.directory}; "
        f"control on {args.host}:{endpoint.port}."
    )
    try:
        while True:
            if follower.promoted:
                await serve_done.wait()
                return 0
            if follower.fenced:
                print("Follower fenced (stale-epoch stream); exiting.")
                return 1
            await asyncio.sleep(0.5)
    finally:
        await endpoint.stop()
        await follower.stop()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return asyncio.run(_run_follower(args))
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    import sys

    sys.exit(main())
