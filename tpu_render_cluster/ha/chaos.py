"""Master-failover chaos: kill the primary mid-job, audit the standby.

The failover analog of ``chaos/runner.py``: one real in-process cluster
(accepting server, 3-step handshake, heartbeats, real WebSockets), a
seeded fault plan that includes the control-plane kinds
(``master_kill`` / ``master_partition``), and an invariant audit at the
end. The run has two acts:

1. **Primary** — a ledger-backed ``ClusterManager`` starts the job; the
   plan's worker faults (stragglers, duplicated sends, drops) execute as
   usual. At the scheduled offsets, ``master_partition`` aborts every
   master-side worker socket (workers reconnect into the SAME epoch —
   the ordinary reconnect path) and ``master_kill`` cancels the primary
   outright, socket-death and all.
2. **Standby** — a fresh ``ClusterManager`` opens the same ledger
   directory (epoch bump), replays the finished set, binds the SAME
   port, and re-adopts the workers as they re-announce (fresh sessions —
   the epoch piggyback tells them their old session is gone). The job
   completes; results of predecessor assignments arrive fenced with the
   old epoch and are refused, never double-counted.

The audit (``check_failover_invariants``) is the cross-incarnation
exactly-once equation::

    ledger_replayed + (ok - duplicates) == units_total

plus zero ghost mirrors, zero unplanned evictions/drains, and a merged
cluster timeline whose flows all resolve. MTTR is measured as
kill -> first post-adoption queue-add dispatch.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from pathlib import Path
from typing import Any

from tpu_render_cluster.chaos.inject import MasterChaosHooks, WorkerChaosController
from tpu_render_cluster.chaos.plan import (
    KIND_MASTER_KILL,
    KIND_MASTER_PARTITION,
    FaultPlan,
)
from tpu_render_cluster.chaos.runner import (
    DEFAULT_RENDER_SECONDS,
    ChaosReport,
    _make_job,
    _timing_overrides,
    unit_latency_stats,
)
from tpu_render_cluster.ha.ledger import JobLedger
from tpu_render_cluster.harness import local as local_harness
from tpu_render_cluster.master.cluster import ClusterManager
from tpu_render_cluster.master.state import FrameStatus
from tpu_render_cluster.obs import MetricsRegistry
from tpu_render_cluster.worker.backends.chaos import FaultyBackend
from tpu_render_cluster.worker.backends.mock import MockBackend
from tpu_render_cluster.worker.runtime import Worker

logger = logging.getLogger(__name__)

DEFAULT_FAILOVER_FRAMES = 48
STANDBY_BIND_RETRIES = 20
STANDBY_BIND_RETRY_SECONDS = 0.1


def check_failover_invariants(
    standby: ClusterManager,
    plan: FaultPlan,
    *,
    cluster_trace_document: Any | None = None,
) -> list[str]:
    """The failover audit, over the STANDBY incarnation's final state."""
    from tpu_render_cluster.chaos.invariants import counter_total, ledger_stats

    violations: list[str] = []
    state = standby.state
    total = len(state.frames)

    unfinished = sorted(
        (unit for unit, record in state.frames.items()
         if record.status is not FrameStatus.FINISHED),
        key=lambda u: u.sort_key,
    )
    if unfinished:
        violations.append(
            f"completion: {len(unfinished)} unit(s) not FINISHED after "
            f"failover: {[u.label for u in unfinished[:10]]}"
        )
    if state.finished_count() != total:
        violations.append(
            f"completion: finished_count {state.finished_count()} != "
            f"unit table size {total}"
        )

    # Cross-incarnation exactly-once: what the ledger restored plus what
    # the standby's result stream delivered (first copies only) must
    # cover every unit exactly once.
    delivered = state.ledger["ok_results"] - state.ledger["duplicate_results"]
    if standby.replayed_units + delivered != total:
        violations.append(
            "exactly-once across failover: replayed + (ok - duplicates) = "
            f"{standby.replayed_units} + ({state.ledger['ok_results']} - "
            f"{state.ledger['duplicate_results']}) = "
            f"{standby.replayed_units + delivered}, expected {total}"
        )

    for worker in standby.workers.values():
        if len(worker.queue) > 0:
            ghosts = sorted(
                (f.unit for f in worker.queue.all_frames()),
                key=lambda u: u.sort_key,
            )
            violations.append(
                f"ghost assignments: worker {worker.worker_id:08x} "
                f"({'dead' if worker.is_dead else 'alive'}) still mirrors "
                f"unit(s) {[u.label for u in ghosts[:10]]}"
            )

    # A failover plan removes no workers: nobody may be evicted or
    # drained in the standby incarnation (the primary's registry is
    # audited by the caller's stats, not here — it died mid-run).
    snapshot = standby.metrics.snapshot()
    ledger = ledger_stats(snapshot)
    expected_evictions = plan.expected_evictions()
    if ledger["evictions"] != expected_evictions:
        violations.append(
            f"evictions: standby master_worker_evictions_total = "
            f"{ledger['evictions']:.0f}, plan injected {expected_evictions}"
        )
    if ledger["drains"] != plan.expected_drains():
        violations.append(
            f"drains: standby master_worker_drains_total = "
            f"{ledger['drains']:.0f}, plan injected {plan.expected_drains()}"
        )

    # The fence must be consistent with itself: every refusal the metrics
    # counted landed in the per-job ledger too.
    refused_metric = counter_total(snapshot, "master_stale_epoch_events_total")
    if refused_metric != state.ledger["stale_epoch_results"]:
        violations.append(
            f"epoch fence: master_stale_epoch_events_total "
            f"{refused_metric:.0f} != per-job stale_epoch_results "
            f"{state.ledger['stale_epoch_results']}"
        )

    if cluster_trace_document is not None:
        from tpu_render_cluster.obs import validate_trace_document

        problems = validate_trace_document(cluster_trace_document)
        for problem in problems[:10]:
            violations.append(f"cluster trace: {problem}")
    return violations


async def _failover_run(
    job,
    plan: FaultPlan,
    backends: list[FaultyBackend],
    controllers: list[WorkerChaosController],
    hooks: MasterChaosHooks,
    registries: list[MetricsRegistry],
    primary_registry: MetricsRegistry,
    standby_registry: MetricsRegistry,
    ledger_directory: Path,
    failover_stats: dict[str, Any],
):
    loop = asyncio.get_running_loop()
    started = loop.time()
    watchdogs: list[asyncio.Task] = []

    primary_ledger = JobLedger.open(ledger_directory, metrics=primary_registry)
    primary = ClusterManager(
        "127.0.0.1",
        0,
        job,
        metrics=primary_registry,
        dispatch_delay_fn=hooks.dispatch_delay,
        ledger=primary_ledger,
    )
    primary_task = asyncio.create_task(
        primary.initialize_server_and_run_job(), name="primary-master"
    )
    while primary._server is None:
        if primary_task.done():
            await primary_task
            raise RuntimeError("primary master exited before startup")
        await asyncio.sleep(0.01)
    port = primary.port
    failover_stats["primary_epoch"] = primary_ledger.epoch

    workers = [
        Worker(
            "127.0.0.1",
            port,
            backend,
            metrics=registries[slot],
            connection_wrapper=controllers[slot].wrap_connection,
        )
        for slot, backend in enumerate(backends)
    ]
    worker_tasks = [
        asyncio.create_task(w.connect_and_run_to_job_completion()) for w in workers
    ]
    for slot, worker in enumerate(workers):
        hooks.map_worker(worker.worker_id, slot)
        controllers[slot].attach(worker, worker_tasks[slot].cancel)
        watchdogs.append(
            asyncio.create_task(
                controllers[slot].run_timed_faults(),
                name=f"chaos-watchdog-{slot}",
            )
        )

    standby: ClusterManager | None = None
    try:
        # Act 1+2: execute the control-plane fault schedule.
        killed = False
        for event in plan.master_events():
            await asyncio.sleep(max(0.0, started + event.at_seconds - loop.time()))
            if event.kind == KIND_MASTER_PARTITION:
                # The master vanishes from every worker's point of view
                # without dying: abort each logical connection's inner
                # socket. The workers reconnect into the SAME epoch — the
                # ordinary resume-session path, no state dropped.
                logger.info("chaos: partitioning the master from all workers")
                failover_stats["master_partitions"] = (
                    failover_stats.get("master_partitions", 0) + 1
                )
                for handle in primary.workers.values():
                    handle.connection._connection.abort()
            elif event.kind == KIND_MASTER_KILL and not killed:
                killed = True
                logger.info("chaos: killing the primary master")
                failover_stats["kill_at"] = time.time()
                primary_task.cancel()
                await asyncio.gather(primary_task, return_exceptions=True)

        if not killed:
            # No kill scheduled: degenerate to a plain run (the caller's
            # plan is wrong, but don't hang the harness).
            master_trace, worker_traces = await primary_task
            return master_trace, worker_traces, primary, workers

        # Act 2: the standby opens the same ledger (epoch bump), binds the
        # SAME port the workers know, replays, and re-adopts.
        standby_ledger = JobLedger.open(ledger_directory, metrics=standby_registry)
        failover_stats["standby_epoch"] = standby_ledger.epoch

        def adoption_probe(worker_id: int, frame_index: int) -> float:
            if "first_dispatch_at" not in failover_stats:
                failover_stats["first_dispatch_at"] = time.time()
            return hooks.dispatch_delay(worker_id, frame_index)

        standby = ClusterManager(
            "127.0.0.1",
            port,
            job,
            metrics=standby_registry,
            dispatch_delay_fn=adoption_probe,
            ledger=standby_ledger,
        )
        failover_stats["replayed_units"] = standby.replayed_units
        standby_task: asyncio.Task | None = None
        for attempt in range(STANDBY_BIND_RETRIES):
            standby_task = asyncio.create_task(
                standby.initialize_server_and_run_job(), name="standby-master"
            )
            while standby._server is None and not standby_task.done():
                await asyncio.sleep(0.01)
            if standby._server is not None:
                break
            # Bind failed (the primary's socket not fully released yet):
            # surface anything that is not an address-in-use retry.
            try:
                await standby_task
            except OSError:
                await asyncio.sleep(STANDBY_BIND_RETRY_SECONDS)
                continue
            raise RuntimeError("standby master exited before startup")
        if standby._server is None:
            raise RuntimeError(
                f"standby could not bind port {port} after "
                f"{STANDBY_BIND_RETRIES} attempts"
            )
        master_trace, worker_traces = await standby_task
        if "first_dispatch_at" in failover_stats and "kill_at" in failover_stats:
            mttr = (
                failover_stats["first_dispatch_at"] - failover_stats["kill_at"]
            )
            failover_stats["mttr_seconds"] = mttr
            # Registered, not just computed: the recovery time of the last
            # failover belongs on the standby's /metrics beside the other
            # ha_* series (the dashboard's HA section reads it federated).
            standby_registry.gauge(
                "ha_failover_mttr_seconds",
                "Master kill to the standby's first post-adoption dispatch "
                "in the most recent failover",
            ).set(mttr)
        return master_trace, worker_traces, standby, workers
    finally:
        for watchdog in watchdogs:
            watchdog.cancel()
        await asyncio.gather(*watchdogs, return_exceptions=True)
        # Reap worker tasks (they exit once the standby collected traces;
        # anything still alive after the grace is cancelled).
        _done, pending = await asyncio.wait(worker_tasks, timeout=3.0)
        for task in pending:
            task.cancel()
        await asyncio.gather(*worker_tasks, return_exceptions=True)


def run_chaos_failover_job(
    plan: FaultPlan,
    *,
    frames: int = DEFAULT_FAILOVER_FRAMES,
    ledger_directory: str | Path | None = None,
    results_directory: str | Path | None = None,
    render_seconds: float = DEFAULT_RENDER_SECONDS,
    timeout: float = 240.0,
    tile_grid: tuple[int, int] | None = None,
) -> ChaosReport:
    """Run one seeded failover scenario end to end and audit it.

    The plan must contain a ``master_kill`` event (``FaultPlan.
    generate_failover`` builds a canonical one). The report's
    ``stats["failover"]`` carries the epochs, the ledger-replayed unit
    count, and the measured MTTR (master kill to the standby's first
    post-adoption dispatch).
    """
    import tempfile

    job = _make_job(plan, frames, None, tile_grid)
    if ledger_directory is None:
        ledger_directory = Path(tempfile.mkdtemp(prefix="trc-ha-ledger-"))
    ledger_directory = Path(ledger_directory)

    registries = [MetricsRegistry() for _ in range(plan.workers)]
    controllers = [
        WorkerChaosController(slot, plan.events_for(slot), registry=registries[slot])
        for slot in range(plan.workers)
    ]
    primary_registry = MetricsRegistry()
    standby_registry = MetricsRegistry()
    hooks = MasterChaosHooks(plan, registry=primary_registry)
    backends = [
        FaultyBackend(
            MockBackend(
                load_seconds=0.004,
                save_seconds=0.004,
                render_seconds=render_seconds,
            ),
            controllers[slot],
        )
        for slot in range(plan.workers)
    ]
    failover_stats: dict[str, Any] = {}
    started = time.time()
    with _timing_overrides(plan.timings):
        master_trace, worker_traces, manager, workers = asyncio.run(
            asyncio.wait_for(
                _failover_run(
                    job,
                    plan,
                    backends,
                    controllers,
                    hooks,
                    registries,
                    primary_registry,
                    standby_registry,
                    ledger_directory,
                    failover_stats,
                ),
                timeout,
            )
        )

    artifacts: dict[str, str] = {}
    if results_directory is not None:
        results_directory = Path(results_directory)
        results_directory.mkdir(parents=True, exist_ok=True)
        prefix = results_directory / (
            f"failover-{plan.seed}-{plan.fingerprint()}"
        )
        trace_path, metrics_path, cluster_trace_path = (
            local_harness.save_obs_artifacts(prefix, manager, workers)
        )
        artifacts = {
            "trace_events": str(trace_path),
            "metrics": str(metrics_path),
            "cluster_trace": str(cluster_trace_path),
        }
        cluster_trace_document = json.loads(
            Path(cluster_trace_path).read_text(encoding="utf-8")
        )
    else:
        from tpu_render_cluster.obs import merge_timeline

        cluster_trace_document = merge_timeline(
            manager.cluster_timeline_processes()
        )

    violations = check_failover_invariants(
        manager, plan, cluster_trace_document=cluster_trace_document
    )
    from tpu_render_cluster.chaos.invariants import ledger_stats

    stats: dict[str, Any] = {
        "frames_total": len(manager.state.frames),
        "tiles_per_frame": job.tiles_per_frame(),
        "job_seconds": master_trace.job_finish_time - master_trace.job_start_time,
        "wall_seconds": time.time() - started,
        "worker_traces_collected": len(worker_traces),
        "failover": failover_stats,
        "ledger": {
            **ledger_stats(manager.metrics.snapshot()),
            "stale_epoch_results": manager.state.ledger["stale_epoch_results"],
        },
        "primary_ledger": ledger_stats(primary_registry.snapshot()),
        "unit_latency": unit_latency_stats(manager.state.unit_seconds),
    }
    return ChaosReport(
        plan=plan, violations=violations, stats=stats, artifacts=artifacts
    )
