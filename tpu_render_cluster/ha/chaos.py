"""Master-failover chaos: kill the primary mid-job, audit the standby.

The failover analog of ``chaos/runner.py``: one real in-process cluster
(accepting server, 3-step handshake, heartbeats, real WebSockets), a
seeded fault plan that includes the control-plane kinds
(``master_kill`` / ``master_partition``), and an invariant audit at the
end. The run has two acts:

1. **Primary** — a ledger-backed ``ClusterManager`` starts the job; the
   plan's worker faults (stragglers, duplicated sends, drops) execute as
   usual. At the scheduled offsets, ``master_partition`` aborts every
   master-side worker socket (workers reconnect into the SAME epoch —
   the ordinary reconnect path) and ``master_kill`` cancels the primary
   outright, socket-death and all.
2. **Standby** — a fresh ``ClusterManager`` opens the same ledger
   directory (epoch bump), replays the finished set, binds the SAME
   port, and re-adopts the workers as they re-announce (fresh sessions —
   the epoch piggyback tells them their old session is gone). The job
   completes; results of predecessor assignments arrive fenced with the
   old epoch and are refused, never double-counted.

The audit (``check_failover_invariants``) is the cross-incarnation
exactly-once equation::

    ledger_replayed + (ok - duplicates) == units_total

plus zero ghost mirrors, zero unplanned evictions/drains, and a merged
cluster timeline whose flows all resolve. MTTR is measured as
kill -> first post-adoption queue-add dispatch.

Two cross-host scenarios build on the same skeleton:

- ``run_chaos_replicated_failover`` — the standby's ledger arrives by
  STREAMING REPLICATION (ha/replicate.py), never a shared directory; the
  stream is partitioned and lagged mid-job (``replication_partition`` /
  ``follower_lag``), then the primary dies and the router's
  ``PromotionMonitor`` promotes the follower (epoch bump out-fencing the
  dead primary), which finishes the job on the primary's port.
- ``run_chaos_shard_kill`` — two router-fronted ``JobManager`` shards;
  one is killed whole-host (master + control) and the router itself is
  bounced (``router_kill``); the orphaned workers re-home to the
  survivor through ``route_worker`` and the survivor completes the
  entire backlog exactly once, with the dead shard degraded to absence
  in every fan-out.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from pathlib import Path
from typing import Any

from tpu_render_cluster.chaos.inject import MasterChaosHooks, WorkerChaosController
from tpu_render_cluster.chaos.plan import (
    KIND_FOLLOWER_LAG,
    KIND_MASTER_KILL,
    KIND_MASTER_PARTITION,
    KIND_REPLICATION_PARTITION,
    KIND_ROUTER_KILL,
    FaultPlan,
)
from tpu_render_cluster.chaos.runner import (
    DEFAULT_RENDER_SECONDS,
    ChaosReport,
    _make_job,
    _timing_overrides,
    unit_latency_stats,
)
from tpu_render_cluster.ha.ledger import JobLedger
from tpu_render_cluster.harness import local as local_harness
from tpu_render_cluster.master.cluster import ClusterManager
from tpu_render_cluster.master.state import FrameStatus
from tpu_render_cluster.obs import MetricsRegistry
from tpu_render_cluster.worker.backends.chaos import FaultyBackend
from tpu_render_cluster.worker.backends.mock import MockBackend
from tpu_render_cluster.worker.runtime import Worker

logger = logging.getLogger(__name__)

DEFAULT_FAILOVER_FRAMES = 48
STANDBY_BIND_RETRIES = 20
STANDBY_BIND_RETRY_SECONDS = 0.1


def check_failover_invariants(
    standby: ClusterManager,
    plan: FaultPlan,
    *,
    cluster_trace_document: Any | None = None,
) -> list[str]:
    """The failover audit, over the STANDBY incarnation's final state."""
    from tpu_render_cluster.chaos.invariants import counter_total, ledger_stats

    violations: list[str] = []
    state = standby.state
    total = len(state.frames)

    unfinished = sorted(
        (unit for unit, record in state.frames.items()
         if record.status is not FrameStatus.FINISHED),
        key=lambda u: u.sort_key,
    )
    if unfinished:
        violations.append(
            f"completion: {len(unfinished)} unit(s) not FINISHED after "
            f"failover: {[u.label for u in unfinished[:10]]}"
        )
    if state.finished_count() != total:
        violations.append(
            f"completion: finished_count {state.finished_count()} != "
            f"unit table size {total}"
        )

    # Cross-incarnation exactly-once: what the ledger restored plus what
    # the standby's result stream delivered (first copies only) must
    # cover every unit exactly once.
    delivered = state.ledger["ok_results"] - state.ledger["duplicate_results"]
    if standby.replayed_units + delivered != total:
        violations.append(
            "exactly-once across failover: replayed + (ok - duplicates) = "
            f"{standby.replayed_units} + ({state.ledger['ok_results']} - "
            f"{state.ledger['duplicate_results']}) = "
            f"{standby.replayed_units + delivered}, expected {total}"
        )

    for worker in standby.workers.values():
        if len(worker.queue) > 0:
            ghosts = sorted(
                (f.unit for f in worker.queue.all_frames()),
                key=lambda u: u.sort_key,
            )
            violations.append(
                f"ghost assignments: worker {worker.worker_id:08x} "
                f"({'dead' if worker.is_dead else 'alive'}) still mirrors "
                f"unit(s) {[u.label for u in ghosts[:10]]}"
            )

    # A failover plan removes no workers: nobody may be evicted or
    # drained in the standby incarnation (the primary's registry is
    # audited by the caller's stats, not here — it died mid-run).
    snapshot = standby.metrics.snapshot()
    ledger = ledger_stats(snapshot)
    expected_evictions = plan.expected_evictions()
    if ledger["evictions"] != expected_evictions:
        violations.append(
            f"evictions: standby master_worker_evictions_total = "
            f"{ledger['evictions']:.0f}, plan injected {expected_evictions}"
        )
    if ledger["drains"] != plan.expected_drains():
        violations.append(
            f"drains: standby master_worker_drains_total = "
            f"{ledger['drains']:.0f}, plan injected {plan.expected_drains()}"
        )

    # The fence must be consistent with itself: every refusal the metrics
    # counted landed in the per-job ledger too.
    refused_metric = counter_total(snapshot, "master_stale_epoch_events_total")
    if refused_metric != state.ledger["stale_epoch_results"]:
        violations.append(
            f"epoch fence: master_stale_epoch_events_total "
            f"{refused_metric:.0f} != per-job stale_epoch_results "
            f"{state.ledger['stale_epoch_results']}"
        )

    if cluster_trace_document is not None:
        from tpu_render_cluster.obs import validate_trace_document

        problems = validate_trace_document(cluster_trace_document)
        for problem in problems[:10]:
            violations.append(f"cluster trace: {problem}")
    return violations


async def _failover_run(
    job,
    plan: FaultPlan,
    backends: list[FaultyBackend],
    controllers: list[WorkerChaosController],
    hooks: MasterChaosHooks,
    registries: list[MetricsRegistry],
    primary_registry: MetricsRegistry,
    standby_registry: MetricsRegistry,
    ledger_directory: Path,
    failover_stats: dict[str, Any],
):
    loop = asyncio.get_running_loop()
    started = loop.time()
    watchdogs: list[asyncio.Task] = []

    primary_ledger = JobLedger.open(ledger_directory, metrics=primary_registry)
    primary = ClusterManager(
        "127.0.0.1",
        0,
        job,
        metrics=primary_registry,
        dispatch_delay_fn=hooks.dispatch_delay,
        ledger=primary_ledger,
    )
    primary_task = asyncio.create_task(
        primary.initialize_server_and_run_job(), name="primary-master"
    )
    while primary._server is None:
        if primary_task.done():
            await primary_task
            raise RuntimeError("primary master exited before startup")
        await asyncio.sleep(0.01)
    port = primary.port
    failover_stats["primary_epoch"] = primary_ledger.epoch

    workers = [
        Worker(
            "127.0.0.1",
            port,
            backend,
            metrics=registries[slot],
            connection_wrapper=controllers[slot].wrap_connection,
        )
        for slot, backend in enumerate(backends)
    ]
    worker_tasks = [
        asyncio.create_task(w.connect_and_run_to_job_completion()) for w in workers
    ]
    for slot, worker in enumerate(workers):
        hooks.map_worker(worker.worker_id, slot)
        controllers[slot].attach(worker, worker_tasks[slot].cancel)
        watchdogs.append(
            asyncio.create_task(
                controllers[slot].run_timed_faults(),
                name=f"chaos-watchdog-{slot}",
            )
        )

    standby: ClusterManager | None = None
    try:
        # Act 1+2: execute the control-plane fault schedule.
        killed = False
        for event in plan.master_events():
            await asyncio.sleep(max(0.0, started + event.at_seconds - loop.time()))
            if event.kind == KIND_MASTER_PARTITION:
                # The master vanishes from every worker's point of view
                # without dying: abort each logical connection's inner
                # socket. The workers reconnect into the SAME epoch — the
                # ordinary resume-session path, no state dropped.
                logger.info("chaos: partitioning the master from all workers")
                failover_stats["master_partitions"] = (
                    failover_stats.get("master_partitions", 0) + 1
                )
                for handle in primary.workers.values():
                    handle.connection._connection.abort()
            elif event.kind == KIND_MASTER_KILL and not killed:
                killed = True
                logger.info("chaos: killing the primary master")
                failover_stats["kill_at"] = time.time()
                primary_task.cancel()
                await asyncio.gather(primary_task, return_exceptions=True)

        if not killed:
            # No kill scheduled: degenerate to a plain run (the caller's
            # plan is wrong, but don't hang the harness).
            master_trace, worker_traces = await primary_task
            return master_trace, worker_traces, primary, workers

        # Act 2: the standby opens the same ledger (epoch bump), binds the
        # SAME port the workers know, replays, and re-adopts.
        standby_ledger = JobLedger.open(ledger_directory, metrics=standby_registry)
        failover_stats["standby_epoch"] = standby_ledger.epoch

        def adoption_probe(worker_id: int, frame_index: int) -> float:
            if "first_dispatch_at" not in failover_stats:
                failover_stats["first_dispatch_at"] = time.time()
            return hooks.dispatch_delay(worker_id, frame_index)

        standby = ClusterManager(
            "127.0.0.1",
            port,
            job,
            metrics=standby_registry,
            dispatch_delay_fn=adoption_probe,
            ledger=standby_ledger,
        )
        failover_stats["replayed_units"] = standby.replayed_units
        standby_task: asyncio.Task | None = None
        for attempt in range(STANDBY_BIND_RETRIES):
            standby_task = asyncio.create_task(
                standby.initialize_server_and_run_job(), name="standby-master"
            )
            while standby._server is None and not standby_task.done():
                await asyncio.sleep(0.01)
            if standby._server is not None:
                break
            # Bind failed (the primary's socket not fully released yet):
            # surface anything that is not an address-in-use retry.
            try:
                await standby_task
            except OSError:
                await asyncio.sleep(STANDBY_BIND_RETRY_SECONDS)
                continue
            raise RuntimeError("standby master exited before startup")
        if standby._server is None:
            raise RuntimeError(
                f"standby could not bind port {port} after "
                f"{STANDBY_BIND_RETRIES} attempts"
            )
        master_trace, worker_traces = await standby_task
        if "first_dispatch_at" in failover_stats and "kill_at" in failover_stats:
            mttr = (
                failover_stats["first_dispatch_at"] - failover_stats["kill_at"]
            )
            failover_stats["mttr_seconds"] = mttr
            # Registered, not just computed: the recovery time of the last
            # failover belongs on the standby's /metrics beside the other
            # ha_* series (the dashboard's HA section reads it federated).
            standby_registry.gauge(
                "ha_failover_mttr_seconds",
                "Master kill to the standby's first post-adoption dispatch "
                "in the most recent failover",
            ).set(mttr)
        return master_trace, worker_traces, standby, workers
    finally:
        for watchdog in watchdogs:
            watchdog.cancel()
        await asyncio.gather(*watchdogs, return_exceptions=True)
        # Reap worker tasks (they exit once the standby collected traces;
        # anything still alive after the grace is cancelled).
        _done, pending = await asyncio.wait(worker_tasks, timeout=3.0)
        for task in pending:
            task.cancel()
        await asyncio.gather(*worker_tasks, return_exceptions=True)


def run_chaos_failover_job(
    plan: FaultPlan,
    *,
    frames: int = DEFAULT_FAILOVER_FRAMES,
    ledger_directory: str | Path | None = None,
    results_directory: str | Path | None = None,
    render_seconds: float = DEFAULT_RENDER_SECONDS,
    timeout: float = 240.0,
    tile_grid: tuple[int, int] | None = None,
) -> ChaosReport:
    """Run one seeded failover scenario end to end and audit it.

    The plan must contain a ``master_kill`` event (``FaultPlan.
    generate_failover`` builds a canonical one). The report's
    ``stats["failover"]`` carries the epochs, the ledger-replayed unit
    count, and the measured MTTR (master kill to the standby's first
    post-adoption dispatch).
    """
    import tempfile

    job = _make_job(plan, frames, None, tile_grid)
    if ledger_directory is None:
        ledger_directory = Path(tempfile.mkdtemp(prefix="trc-ha-ledger-"))
    ledger_directory = Path(ledger_directory)

    registries = [MetricsRegistry() for _ in range(plan.workers)]
    controllers = [
        WorkerChaosController(slot, plan.events_for(slot), registry=registries[slot])
        for slot in range(plan.workers)
    ]
    primary_registry = MetricsRegistry()
    standby_registry = MetricsRegistry()
    hooks = MasterChaosHooks(plan, registry=primary_registry)
    backends = [
        FaultyBackend(
            MockBackend(
                load_seconds=0.004,
                save_seconds=0.004,
                render_seconds=render_seconds,
            ),
            controllers[slot],
        )
        for slot in range(plan.workers)
    ]
    failover_stats: dict[str, Any] = {}
    started = time.time()
    with _timing_overrides(plan.timings):
        master_trace, worker_traces, manager, workers = asyncio.run(
            asyncio.wait_for(
                _failover_run(
                    job,
                    plan,
                    backends,
                    controllers,
                    hooks,
                    registries,
                    primary_registry,
                    standby_registry,
                    ledger_directory,
                    failover_stats,
                ),
                timeout,
            )
        )

    artifacts: dict[str, str] = {}
    if results_directory is not None:
        results_directory = Path(results_directory)
        results_directory.mkdir(parents=True, exist_ok=True)
        prefix = results_directory / (
            f"failover-{plan.seed}-{plan.fingerprint()}"
        )
        trace_path, metrics_path, cluster_trace_path = (
            local_harness.save_obs_artifacts(prefix, manager, workers)
        )
        artifacts = {
            "trace_events": str(trace_path),
            "metrics": str(metrics_path),
            "cluster_trace": str(cluster_trace_path),
        }
        cluster_trace_document = json.loads(
            Path(cluster_trace_path).read_text(encoding="utf-8")
        )
    else:
        from tpu_render_cluster.obs import merge_timeline

        cluster_trace_document = merge_timeline(
            manager.cluster_timeline_processes()
        )

    violations = check_failover_invariants(
        manager, plan, cluster_trace_document=cluster_trace_document
    )
    from tpu_render_cluster.chaos.invariants import ledger_stats

    stats: dict[str, Any] = {
        "frames_total": len(manager.state.frames),
        "tiles_per_frame": job.tiles_per_frame(),
        "job_seconds": master_trace.job_finish_time - master_trace.job_start_time,
        "wall_seconds": time.time() - started,
        "worker_traces_collected": len(worker_traces),
        "failover": failover_stats,
        "ledger": {
            **ledger_stats(manager.metrics.snapshot()),
            "stale_epoch_results": manager.state.ledger["stale_epoch_results"],
        },
        "primary_ledger": ledger_stats(primary_registry.snapshot()),
        "unit_latency": unit_latency_stats(manager.state.unit_seconds),
    }
    return ChaosReport(
        plan=plan, violations=violations, stats=stats, artifacts=artifacts
    )


# ---------------------------------------------------------------------------
# Cross-host replicated failover: streaming replication, NO shared filesystem


async def _replicated_failover_run(
    job,
    plan: FaultPlan,
    backends: list[FaultyBackend],
    controllers: list[WorkerChaosController],
    hooks: MasterChaosHooks,
    registries: list[MetricsRegistry],
    primary_registry: MetricsRegistry,
    follower_registry: MetricsRegistry,
    standby_registry: MetricsRegistry,
    router_registry: MetricsRegistry,
    primary_directory: Path,
    replica_directory: Path,
    failover_stats: dict[str, Any],
):
    from tpu_render_cluster.ha.replicate import (
        LedgerFollower,
        PromotableFollower,
        ReplicationServer,
    )
    from tpu_render_cluster.ha.shards import PromotionMonitor, ShardRouter

    loop = asyncio.get_running_loop()
    started = loop.time()
    watchdogs: list[asyncio.Task] = []
    holder: dict[str, Any] = {}

    primary_ledger = JobLedger.open(primary_directory, metrics=primary_registry)
    replication = ReplicationServer(
        primary_ledger, host="127.0.0.1", port=0, metrics=primary_registry
    )
    await replication.start()
    primary = ClusterManager(
        "127.0.0.1",
        0,
        job,
        metrics=primary_registry,
        dispatch_delay_fn=hooks.dispatch_delay,
        ledger=primary_ledger,
    )
    primary_task = asyncio.create_task(
        primary.initialize_server_and_run_job(), name="primary-master"
    )
    while primary._server is None:
        if primary_task.done():
            await primary_task
            raise RuntimeError("primary master exited before startup")
        await asyncio.sleep(0.01)
    port = primary.port
    failover_stats["primary_epoch"] = primary_ledger.epoch

    # The replica lives in a DIFFERENT directory on (conceptually) a
    # different host: every byte it holds arrived over the TCP stream.
    follower = LedgerFollower(
        replica_directory,
        "127.0.0.1",
        replication.port,
        metrics=follower_registry,
        follower_id="chaos-follower",
    )
    follower.start()

    def adoption_probe(worker_id: int, frame_index: int) -> float:
        if "first_dispatch_at" not in failover_stats:
            failover_stats["first_dispatch_at"] = time.time()
        return hooks.dispatch_delay(worker_id, frame_index)

    async def promote_callback(ledger: JobLedger) -> dict[str, Any]:
        # The promoted replica serves on the SAME worker port the dead
        # primary used, so the workers' ordinary reconnect loop lands on
        # the new incarnation (epoch piggyback -> fresh sessions).
        standby = ClusterManager(
            "127.0.0.1",
            port,
            job,
            metrics=standby_registry,
            dispatch_delay_fn=adoption_probe,
            ledger=ledger,
        )
        failover_stats["replayed_units"] = standby.replayed_units
        failover_stats["standby_epoch"] = ledger.epoch
        standby_task: asyncio.Task | None = None
        for _attempt in range(STANDBY_BIND_RETRIES):
            standby_task = asyncio.create_task(
                standby.initialize_server_and_run_job(), name="standby-master"
            )
            while standby._server is None and not standby_task.done():
                await asyncio.sleep(0.01)
            if standby._server is not None:
                break
            try:
                await standby_task
            except OSError:
                await asyncio.sleep(STANDBY_BIND_RETRY_SECONDS)
                continue
            raise RuntimeError("standby master exited before startup")
        if standby._server is None:
            raise RuntimeError(
                f"standby could not bind port {port} after "
                f"{STANDBY_BIND_RETRIES} attempts"
            )
        holder["standby"] = standby
        holder["task"] = standby_task
        return {
            "ok": True,
            "serving": True,
            "host": "127.0.0.1",
            "port": port,
            "control_port": port,
        }

    control = PromotableFollower(
        follower,
        promote_callback=promote_callback,
        host="127.0.0.1",
        port=0,
        metrics=standby_registry,
    )
    await control.start()

    async def tcp_probe(_shard: int, host: str, probe_port: int) -> bool:
        try:
            _reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, probe_port), 0.25
            )
        except (OSError, asyncio.TimeoutError):
            return False
        writer.close()
        return True

    router = ShardRouter([("127.0.0.1", port)], metrics=router_registry)
    monitor = PromotionMonitor(
        router,
        {0: [("127.0.0.1", control.port)]},
        probe_fn=tcp_probe,
        probe_interval=0.1,
        promote_timeout=0.4,
    )
    monitor.start()

    workers = [
        Worker(
            "127.0.0.1",
            port,
            backend,
            metrics=registries[slot],
            connection_wrapper=controllers[slot].wrap_connection,
        )
        for slot, backend in enumerate(backends)
    ]
    worker_tasks = [
        asyncio.create_task(w.connect_and_run_to_job_completion()) for w in workers
    ]
    for slot, worker in enumerate(workers):
        hooks.map_worker(worker.worker_id, slot)
        controllers[slot].attach(worker, worker_tasks[slot].cancel)
        watchdogs.append(
            asyncio.create_task(
                controllers[slot].run_timed_faults(),
                name=f"chaos-watchdog-{slot}",
            )
        )

    try:
        killed = False
        schedule = sorted(
            plan.master_events() + plan.replication_events(),
            key=lambda e: e.at_seconds,
        )
        for event in schedule:
            await asyncio.sleep(max(0.0, started + event.at_seconds - loop.time()))
            if event.kind == KIND_REPLICATION_PARTITION:
                # Sever the stream and keep severing any reattach for the
                # window: the follower must gap-detect + catch up after.
                logger.info("chaos: partitioning the replication stream")
                failover_stats["replication_partitions"] = (
                    failover_stats.get("replication_partitions", 0) + 1
                )
                deadline = loop.time() + event.duration_seconds
                while loop.time() < deadline:
                    follower.abort_connection()
                    await asyncio.sleep(0.05)
            elif event.kind == KIND_FOLLOWER_LAG:
                logger.info(
                    "chaos: lagging the follower by %.3fs/record for %.2fs",
                    event.multiplier, event.duration_seconds,
                )
                failover_stats["follower_lags"] = (
                    failover_stats.get("follower_lags", 0) + 1
                )
                follower.inject_delay_seconds = event.multiplier

                async def clear_lag(duration: float = event.duration_seconds):
                    await asyncio.sleep(duration)
                    follower.inject_delay_seconds = 0.0

                watchdogs.append(asyncio.create_task(clear_lag()))
            elif event.kind == KIND_ROUTER_KILL:
                # This scenario's "router" is the promotion monitor; a
                # dead router must merely delay promotion, never lose it.
                logger.info("chaos: killing the router/monitor")
                failover_stats["router_kills"] = (
                    failover_stats.get("router_kills", 0) + 1
                )
                await monitor.stop()

                async def revive_monitor(
                    duration: float = event.duration_seconds,
                ):
                    await asyncio.sleep(duration)
                    monitor.start()

                watchdogs.append(asyncio.create_task(revive_monitor()))
            elif event.kind == KIND_MASTER_PARTITION:
                logger.info("chaos: partitioning the master from all workers")
                failover_stats["master_partitions"] = (
                    failover_stats.get("master_partitions", 0) + 1
                )
                for handle in primary.workers.values():
                    handle.connection._connection.abort()
            elif event.kind == KIND_MASTER_KILL and not killed:
                killed = True
                logger.info("chaos: killing the primary master (and stream)")
                failover_stats["kill_at"] = time.time()
                primary_task.cancel()
                await asyncio.gather(primary_task, return_exceptions=True)
                await replication.stop()

        if not killed:
            master_trace, worker_traces = await primary_task
            return master_trace, worker_traces, primary, workers

        # The router detects the death and promotes; wait for the standby
        # it installs, then for the job to finish under it.
        deadline = loop.time() + 60.0
        while "task" not in holder:
            if loop.time() > deadline:
                raise RuntimeError(
                    "promotion monitor never promoted the follower"
                )
            await asyncio.sleep(0.02)
        standby = holder["standby"]
        master_trace, worker_traces = await holder["task"]
        if "first_dispatch_at" in failover_stats and "kill_at" in failover_stats:
            mttr = (
                failover_stats["first_dispatch_at"] - failover_stats["kill_at"]
            )
            failover_stats["mttr_seconds"] = mttr
            standby_registry.gauge(
                "ha_failover_mttr_seconds",
                "Master kill to the standby's first post-adoption dispatch "
                "in the most recent failover",
            ).set(mttr)
        failover_stats["promotions"] = list(monitor.promotions)
        failover_stats["follower"] = {
            "records_applied": follower.records_applied,
            "last_seq": follower.last_seq,
            "fenced": follower.fenced,
            "lag": unit_latency_stats(list(follower.lag_samples)),
        }
        return master_trace, worker_traces, standby, workers
    finally:
        await monitor.stop()
        await control.stop()
        await follower.stop()
        await replication.stop()
        for watchdog in watchdogs:
            watchdog.cancel()
        await asyncio.gather(*watchdogs, return_exceptions=True)
        _done, pending = await asyncio.wait(worker_tasks, timeout=3.0)
        for task in pending:
            task.cancel()
        await asyncio.gather(*worker_tasks, return_exceptions=True)


def run_chaos_replicated_failover(
    plan: FaultPlan,
    *,
    frames: int = DEFAULT_FAILOVER_FRAMES,
    primary_directory: str | Path | None = None,
    replica_directory: str | Path | None = None,
    results_directory: str | Path | None = None,
    render_seconds: float = DEFAULT_RENDER_SECONDS,
    timeout: float = 240.0,
    tile_grid: tuple[int, int] | None = None,
) -> ChaosReport:
    """Cross-host failover under chaos: the ledger reaches the standby by
    STREAMING REPLICATION only (ha/replicate.py), never a shared path.

    The plan should come from ``FaultPlan.generate_replicated_failover``:
    the stream is severed and re-established, the follower briefly
    lagged, then the primary killed — the router's ``PromotionMonitor``
    detects the death, promotes the most-caught-up follower (epoch bump),
    and the promoted replica finishes the job on the primary's port. The
    audit is ``check_failover_invariants`` over the promoted incarnation
    — the cross-host exactly-once equation ``follower_replayed +
    (ok - duplicates) == units`` — plus replication-specific checks
    (promotion happened exactly once, the promoted epoch out-fences the
    primary's, the replica directory is disjoint).
    """
    import os
    import tempfile

    job = _make_job(plan, frames, None, tile_grid)
    if primary_directory is None:
        primary_directory = Path(tempfile.mkdtemp(prefix="trc-ha-primary-"))
    if replica_directory is None:
        replica_directory = Path(tempfile.mkdtemp(prefix="trc-ha-replica-"))
    primary_directory = Path(primary_directory)
    replica_directory = Path(replica_directory)
    if primary_directory.resolve() == replica_directory.resolve():
        raise ValueError(
            "replicated failover needs DISJOINT primary/replica "
            "directories (that is the point)"
        )

    registries = [MetricsRegistry() for _ in range(plan.workers)]
    controllers = [
        WorkerChaosController(slot, plan.events_for(slot), registry=registries[slot])
        for slot in range(plan.workers)
    ]
    primary_registry = MetricsRegistry()
    follower_registry = MetricsRegistry()
    standby_registry = MetricsRegistry()
    router_registry = MetricsRegistry()
    hooks = MasterChaosHooks(plan, registry=primary_registry)
    backends = [
        FaultyBackend(
            MockBackend(
                load_seconds=0.004,
                save_seconds=0.004,
                render_seconds=render_seconds,
            ),
            controllers[slot],
        )
        for slot in range(plan.workers)
    ]
    failover_stats: dict[str, Any] = {}
    started = time.time()
    # A compressed chaos run needs the follower to reattach fast after a
    # severed stream (same spirit as _timing_overrides' env profile).
    retry_env = {"TRC_HA_REPL_RETRY_SECONDS": "0.05"}
    saved_retry = {name: os.environ.get(name) for name in retry_env}
    os.environ.update(retry_env)
    try:
        with _timing_overrides(plan.timings):
            master_trace, worker_traces, manager, workers = asyncio.run(
                asyncio.wait_for(
                    _replicated_failover_run(
                        job,
                        plan,
                        backends,
                        controllers,
                        hooks,
                        registries,
                        primary_registry,
                        follower_registry,
                        standby_registry,
                        router_registry,
                        primary_directory,
                        replica_directory,
                        failover_stats,
                    ),
                    timeout,
                )
            )
    finally:
        for name, value in saved_retry.items():
            if value is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = value

    artifacts: dict[str, str] = {}
    if results_directory is not None:
        results_directory = Path(results_directory)
        results_directory.mkdir(parents=True, exist_ok=True)
        prefix = results_directory / (
            f"replicated-failover-{plan.seed}-{plan.fingerprint()}"
        )
        trace_path, metrics_path, cluster_trace_path = (
            local_harness.save_obs_artifacts(prefix, manager, workers)
        )
        artifacts = {
            "trace_events": str(trace_path),
            "metrics": str(metrics_path),
            "cluster_trace": str(cluster_trace_path),
        }
        cluster_trace_document = json.loads(
            Path(cluster_trace_path).read_text(encoding="utf-8")
        )
    else:
        from tpu_render_cluster.obs import merge_timeline

        cluster_trace_document = merge_timeline(
            manager.cluster_timeline_processes()
        )

    violations = check_failover_invariants(
        manager, plan, cluster_trace_document=cluster_trace_document
    )
    promotions = failover_stats.get("promotions", [])
    if len(promotions) != 1:
        violations.append(
            f"promotion: expected exactly one router-driven promotion, "
            f"monitor recorded {len(promotions)}"
        )
    primary_epoch = failover_stats.get("primary_epoch")
    standby_epoch = failover_stats.get("standby_epoch")
    if (
        primary_epoch is not None
        and standby_epoch is not None
        and standby_epoch <= primary_epoch
    ):
        violations.append(
            f"epoch fence: promoted epoch {standby_epoch} does not exceed "
            f"the dead primary's {primary_epoch}"
        )
    follower_stats = failover_stats.get("follower", {})
    if follower_stats.get("records_applied", 0) <= 0:
        violations.append(
            "replication: the follower applied no records before promotion "
            "— the standby replayed a stale (or empty) replica"
        )

    from tpu_render_cluster.chaos.invariants import ledger_stats

    stats: dict[str, Any] = {
        "frames_total": len(manager.state.frames),
        "tiles_per_frame": job.tiles_per_frame(),
        "job_seconds": master_trace.job_finish_time - master_trace.job_start_time,
        "wall_seconds": time.time() - started,
        "worker_traces_collected": len(worker_traces),
        "failover": failover_stats,
        "ledger": {
            **ledger_stats(manager.metrics.snapshot()),
            "stale_epoch_results": manager.state.ledger["stale_epoch_results"],
        },
        "primary_ledger": ledger_stats(primary_registry.snapshot()),
        "unit_latency": unit_latency_stats(manager.state.unit_seconds),
    }
    return ChaosReport(
        plan=plan, violations=violations, stats=stats, artifacts=artifacts
    )


# ---------------------------------------------------------------------------
# Shard death under a router: workers re-home to the survivor


async def _shard_kill_run(
    specs: list[dict[str, Any]],
    plan: FaultPlan,
    backends: list[FaultyBackend],
    controllers: list[WorkerChaosController],
    hooks: MasterChaosHooks,
    registries: list[MetricsRegistry],
    shard_registries: list[MetricsRegistry],
    router_registry: MetricsRegistry,
    kill_stats: dict[str, Any],
):
    from tpu_render_cluster.ha.shards import ShardRouter, ShardRouterServer
    from tpu_render_cluster.sched.control import ControlServer, control_request
    from tpu_render_cluster.sched.manager import JobManager, SchedulerConfig
    from tpu_render_cluster.worker.main import make_router_route_fn

    loop = asyncio.get_running_loop()
    started = loop.time()
    watchdogs: list[asyncio.Task] = []

    managers: list[JobManager] = []
    serves: list[asyncio.Task] = []
    controls: list[ControlServer] = []
    for shard in range(2):
        manager = JobManager(
            "127.0.0.1",
            0,
            config=SchedulerConfig.from_env(),
            metrics=shard_registries[shard],
            # Every submitted job name hashes onto shard 1 (the survivor),
            # so the plan's dispatch hooks belong there.
            dispatch_delay_fn=hooks.dispatch_delay if shard == 1 else None,
        )
        serve_task = asyncio.create_task(manager.serve(), name=f"shard-{shard}")
        while manager._server is None:
            if serve_task.done():
                await serve_task
                raise RuntimeError(f"shard {shard} exited before startup")
            await asyncio.sleep(0.01)
        control = ControlServer(manager, "127.0.0.1", 0)
        await control.start()
        managers.append(manager)
        serves.append(serve_task)
        controls.append(control)

    router = ShardRouter(
        [("127.0.0.1", c.port) for c in controls],
        worker_endpoints=[("127.0.0.1", m.port) for m in managers],
        metrics=router_registry,
    )
    server = ShardRouterServer(router)
    await server.start()
    route_fn = make_router_route_fn(f"127.0.0.1:{server.port}")

    # First half of the pool homes on the doomed shard 0, the rest on the
    # survivor; everyone runs the re-homing serve loop.
    def home(slot: int) -> int:
        return 0 if slot < len(backends) // 2 else 1

    workers = [
        Worker(
            "127.0.0.1",
            managers[home(slot)].port,
            backend,
            metrics=registries[slot],
            connection_wrapper=controllers[slot].wrap_connection,
        )
        for slot, backend in enumerate(backends)
    ]
    worker_tasks = [
        asyncio.create_task(w.connect_and_serve(route_fn)) for w in workers
    ]
    for slot, worker in enumerate(workers):
        hooks.map_worker(worker.worker_id, slot)
        controllers[slot].attach(worker, worker_tasks[slot].cancel)
        watchdogs.append(
            asyncio.create_task(
                controllers[slot].run_timed_faults(),
                name=f"chaos-watchdog-{slot}",
            )
        )

    try:
        job_ids: list[str] = []
        for spec in specs:
            response = await control_request(
                "127.0.0.1", server.port, {"op": "submit", "spec": spec}
            )
            if not response.get("ok"):
                raise RuntimeError(f"router submit failed: {response.get('error')}")
            if not response["job_id"].startswith("s1/"):
                raise RuntimeError(
                    f"job {spec['job']['job_name']!r} routed to "
                    f"{response['job_id']} — shard-kill jobs must hash to "
                    "the survivor (shard 1)"
                )
            job_ids.append(response["job_id"])

        killed = False
        schedule = sorted(
            plan.master_events() + plan.replication_events(),
            key=lambda e: e.at_seconds,
        )
        for event in schedule:
            await asyncio.sleep(max(0.0, started + event.at_seconds - loop.time()))
            if event.kind == KIND_MASTER_KILL and not killed:
                killed = True
                logger.info("chaos: killing shard 0 (master + control)")
                kill_stats["kill_at"] = time.time()
                serves[0].cancel()
                await asyncio.gather(serves[0], return_exceptions=True)
                # The whole host dies: the control endpoint goes with the
                # master, so the router sees the shard as unreachable (not
                # a zombie answering status for a dead scheduler).
                await controls[0].stop()
            elif event.kind == KIND_ROUTER_KILL:
                logger.info("chaos: killing the shard router for %.2fs",
                            event.duration_seconds)
                kill_stats["router_kills"] = (
                    kill_stats.get("router_kills", 0) + 1
                )
                await server.stop()

                async def revive_router(duration: float = event.duration_seconds):
                    await asyncio.sleep(duration)
                    await server.start()

                watchdogs.append(asyncio.create_task(revive_router()))
            elif event.kind == KIND_MASTER_PARTITION:
                logger.info("chaos: partitioning shard 0 from its workers")
                for handle in managers[0].workers.values():
                    handle.connection._connection.abort()
            # replication_partition / follower_lag have no replication
            # plane in this scenario; they are inert if a plan carries them.

        if not killed:
            raise RuntimeError(
                "shard-kill plan has no master_kill event; use "
                "FaultPlan.generate_shard_kill"
            )

        # The orphaned workers re-home through the router; wait for the
        # survivor to have adopted the whole pool before draining so the
        # re-home itself is part of the audited run.
        deadline = loop.time() + 30.0
        while (
            len(managers[1].workers) < len(workers) and loop.time() < deadline
        ):
            await asyncio.sleep(0.05)
        kill_stats["survivor_workers"] = len(managers[1].workers)
        if len(managers[1].workers) >= len(workers):
            kill_stats["rehome_seconds"] = time.time() - kill_stats["kill_at"]

        # Drain through the router: the dead shard degrades to absence
        # (plus the scrape-failure counter), never to a connection error.
        drained = await control_request(
            "127.0.0.1", server.port, {"op": "drain"}
        )
        kill_stats["drain_ok"] = bool(drained.get("ok"))
        kill_stats["drain_unreachable"] = drained.get("unreachable")
        worker_traces = await serves[1]
        return worker_traces, managers, workers, job_ids
    finally:
        await server.stop()
        for control in controls:
            await control.stop()
        for serve_task in serves:
            if not serve_task.done():
                serve_task.cancel()
        await asyncio.gather(*serves, return_exceptions=True)
        for watchdog in watchdogs:
            watchdog.cancel()
        await asyncio.gather(*watchdogs, return_exceptions=True)
        _done, pending = await asyncio.wait(worker_tasks, timeout=3.0)
        for task in pending:
            task.cancel()
        await asyncio.gather(*worker_tasks, return_exceptions=True)


def run_chaos_shard_kill(
    plan: FaultPlan,
    *,
    jobs: int = 2,
    frames: int = 32,
    render_seconds: float = DEFAULT_RENDER_SECONDS,
    timeout: float = 240.0,
) -> ChaosReport:
    """Two router-fronted shards, one killed mid-backlog: the orphans
    re-home and the survivor completes every job.

    The plan should come from ``FaultPlan.generate_shard_kill``: shard
    0's master AND control endpoint die at the scheduled offset (a whole
    host gone), the router is bounced once so re-homing has to retry
    through the window, and the survivable worker faults (straggler,
    duplicated send, dropped send) keep the dedup seam honest across the
    re-home. All jobs are submitted THROUGH the router with names that
    hash onto shard 1, so killing shard 0 orphans only workers — the
    audit then demands the survivor finish the whole backlog exactly
    once (``check_multi_job_invariants``), the full pool re-homed, and
    the router's fan-outs degraded (absence + counter), not errored.
    """
    from tpu_render_cluster.ha.shards import shard_for_job_name
    from tpu_render_cluster.sched.models import JOB_FINISHED

    base = _make_job(plan, frames, None, None)
    names: list[str] = []
    candidate = 0
    while len(names) < jobs:
        name = f"chaos-seed-{plan.seed}-sk{candidate}"
        candidate += 1
        if shard_for_job_name(name, 2) == 1:
            names.append(name)
    survivor_pool = plan.workers - plan.workers // 2
    specs = [
        {
            "job": {
                **base.to_dict(),
                "job_name": name,
                "wait_for_number_of_workers": survivor_pool,
            },
            "weight": float(i + 1),
        }
        for i, name in enumerate(names)
    ]

    registries = [MetricsRegistry() for _ in range(plan.workers)]
    controllers = [
        WorkerChaosController(slot, plan.events_for(slot), registry=registries[slot])
        for slot in range(plan.workers)
    ]
    shard_registries = [MetricsRegistry(), MetricsRegistry()]
    router_registry = MetricsRegistry()
    hooks = MasterChaosHooks(plan, registry=shard_registries[1])
    backends = [
        FaultyBackend(
            MockBackend(
                load_seconds=0.004,
                save_seconds=0.004,
                render_seconds=render_seconds,
            ),
            controllers[slot],
        )
        for slot in range(plan.workers)
    ]
    kill_stats: dict[str, Any] = {}
    started = time.time()
    with _timing_overrides(plan.timings):
        worker_traces, managers, workers, job_ids = asyncio.run(
            asyncio.wait_for(
                _shard_kill_run(
                    specs,
                    plan,
                    backends,
                    controllers,
                    hooks,
                    registries,
                    shard_registries,
                    router_registry,
                    kill_stats,
                ),
                timeout,
            )
        )

    from tpu_render_cluster.chaos.invariants import (
        check_multi_job_invariants,
        counter_total,
        ledger_stats,
    )
    from tpu_render_cluster.obs import merge_timeline

    survivor = managers[1]
    cluster_trace_document = merge_timeline(survivor.cluster_timeline_processes())
    violations = check_multi_job_invariants(
        survivor, plan, cluster_trace_document=cluster_trace_document
    )
    for job_id in job_ids:
        inner = job_id.split("/", 1)[1]
        run = survivor._runs.get(inner)
        if run is None:
            violations.append(f"{job_id}: survivor has no such run")
        elif run.status != JOB_FINISHED:
            violations.append(
                f"{job_id}: ended the run in state {run.status!r}, "
                "expected finished"
            )
    if kill_stats.get("survivor_workers", 0) < plan.workers:
        violations.append(
            f"re-home: only {kill_stats.get('survivor_workers', 0)} of "
            f"{plan.workers} worker(s) reached the survivor shard"
        )
    if not kill_stats.get("drain_ok"):
        violations.append(
            "router degrade: the drain fan-out through the router failed "
            "outright instead of degrading the dead shard to absence"
        )
    router_snapshot = router_registry.snapshot()
    if counter_total(router_snapshot, "ha_router_scrape_failures_total") < 1:
        violations.append(
            "router degrade: no ha_router_scrape_failures_total sample — "
            "the dead shard was never degraded through a fan-out"
        )

    stats: dict[str, Any] = {
        "jobs": {
            job_id: survivor.job_status(job_id.split("/", 1)[1])
            for job_id in job_ids
        },
        "frames_total": frames * jobs,
        "wall_seconds": time.time() - started,
        "worker_traces_collected": len(worker_traces),
        "shard_kill": kill_stats,
        "ledger": ledger_stats(survivor.metrics.snapshot()),
        "router_scrape_failures": counter_total(
            router_snapshot, "ha_router_scrape_failures_total"
        ),
    }
    return ChaosReport(plan=plan, violations=violations, stats=stats)
