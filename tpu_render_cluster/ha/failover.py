"""Master failover: turning a ledger replay back into live master state.

A standby (or restarted) master recovers in three steps, all of which
reuse machinery that already exists for other reasons:

1. **Replay** — ``JobLedger.open`` bumps the epoch and replays the
   journal; ``apply_ledger_to_state`` marks every recorded-finished unit
   in the fresh ``ClusterManagerState`` so only the remainder is
   dispatched (the same transition ``--resume``'s output scan uses).
2. **Adoption** — live workers reconnect through their existing backoff
   path; the epoch piggybacked on the handshake tells them this is a new
   incarnation, so they re-announce as fresh sessions (dropping stale
   queue state) and receive the active jobs' ``event_job-started``
   replays through the late-joiner path.
3. **Fencing** — results of the predecessor's assignments arrive stamped
   with the old epoch and are counted + refused by the worker-handle
   dedup seam; the units they would have finished are simply re-rendered,
   and the exactly-once equation holds per incarnation:
   ``replayed + (ok - duplicates) == units_total``.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING

from tpu_render_cluster.ha.ledger import AsyncLedgerAppender
from tpu_render_cluster.jobs.tiles import WorkUnit

if TYPE_CHECKING:
    from tpu_render_cluster.ha.ledger import LedgerReplay
    from tpu_render_cluster.master.state import ClusterManagerState

logger = logging.getLogger(__name__)


def apply_ledger_to_state(
    state: "ClusterManagerState",
    replay: "LedgerReplay",
    *,
    include_closed: bool = False,
) -> tuple[int, list[int]]:
    """Mark the replay's finished units in a fresh frame table.

    Returns ``(replayed_units, frames_needing_stitch)``: the second is
    the tiled-job recovery edge — frames whose every tile the ledger
    recorded finished but whose ASSEMBLY record never landed (the crash
    hit between the last tile and the stitch); the caller re-schedules
    those stitches on the standby, reading the tile files the workers
    already wrote. Units the ledger knows but the job no longer defines
    (an edited job file) are skipped with a warning rather than trusted.

    Only OPEN generations are credited by default: a ledger entry whose
    lifecycle already closed (finished/cancelled) belongs to a previous
    submission that merely shares the name — a fresh same-named job must
    render from scratch. ``include_closed=True`` is the explicit
    ``--resume`` contract: continue THIS job wherever the ledger left it,
    even if it completed.
    """
    entry = replay.job(state.job.job_name)
    if entry is None or (entry.status != "started" and not include_closed):
        return 0, []
    replayed = 0
    needs_stitch: list[int] = []
    skipped = 0
    for frame_index, tile in sorted(
        entry.finished_units, key=lambda u: (u[0], -1 if u[1] is None else u[1])
    ):
        unit = WorkUnit(frame_index, tile)
        if unit not in state.frames:
            skipped += 1
            continue
        frame_completed = state.mark_frame_as_finished(unit)
        replayed += 1
        if frame_completed and state.job.tile_grid is not None:
            if frame_index in entry.assembled_frames:
                state.note_frame_assembled(frame_index)
            else:
                needs_stitch.append(frame_index)
    if skipped:
        logger.warning(
            "Ledger replay for %r: %d recorded unit(s) are not in the "
            "job's current unit table; ignored.",
            state.job.job_name,
            skipped,
        )
    if replayed:
        logger.info(
            "Ledger replay for %r: %d/%d unit(s) already finished"
            "%s.",
            state.job.job_name,
            replayed,
            len(state.frames),
            f", {len(needs_stitch)} frame(s) need re-stitching"
            if needs_stitch
            else "",
        )
    return replayed, needs_stitch


def adopt_ledger(
    state: "ClusterManagerState",
    ledger,
    *,
    metrics=None,
    include_closed: bool = False,
    spec: dict | None = None,
    job_id: str | None = None,
    weight: float = 1.0,
    priority: int = 0,
    appender=None,
) -> tuple[int, list[int]]:
    """The full recovery sequence for one job joining a ledgered master:
    replay application, replayed-unit accounting, sink attachment (AFTER
    replay, so restored units are not re-journaled), and the status-gated
    ``job_started`` append (only when the journal holds no OPEN
    generation of this name). One helper, shared by the single-job
    master's construction and the scheduler's admission, so the
    ordering invariants cannot drift between them. Returns
    ``(replayed_units, frames_needing_stitch)``.
    """
    replayed, needs_stitch = apply_ledger_to_state(
        state, ledger.replay, include_closed=include_closed
    )
    if replayed and metrics is not None:
        metrics.counter(
            "ha_ledger_replayed_units_total",
            "Units restored as finished from ledger replay instead of "
            "being re-rendered",
        ).inc(replayed)
    if appender is None:
        appender = AsyncLedgerAppender(ledger)
    attach_ledger_sinks(state, ledger, appender=appender)
    entry = ledger.replay.job(state.job.job_name)
    if entry is None or (entry.status != "started" and not include_closed):
        appender.schedule(
            ledger.append_job_started,
            state.job.job_name,
            spec=spec,
            job_id=job_id,
            weight=weight,
            priority=priority,
        )
    return replayed, needs_stitch


def attach_ledger_sinks(
    state: "ClusterManagerState", ledger, *, metrics=None, appender=None
) -> None:
    """Journal the state's exactly-once transitions from here on.

    Must run AFTER ``apply_ledger_to_state`` — replayed units must not be
    re-journaled. The sinks fire inside the master's async event handlers
    (the finished-event hot path), so the durable append is routed through
    an :class:`~tpu_render_cluster.ha.ledger.AsyncLedgerAppender` — FIFO,
    fsync on a worker thread, inline only when no loop is running. Append
    failures are logged by the appender, not raised: a full disk degrades
    failover durability (those units re-render after a crash), it must
    not kill the running job mid-event."""
    job_name = state.job.job_name
    if appender is None:
        appender = AsyncLedgerAppender(ledger)

    def on_unit_finished(unit: WorkUnit) -> None:
        appender.schedule(
            ledger.append_unit_finished, job_name, unit.frame_index, unit.tile
        )

    def on_frame_assembled(frame_index: int) -> None:
        appender.schedule(ledger.append_frame_assembled, job_name, frame_index)

    state.on_unit_finished = on_unit_finished
    if state.job.tile_grid is not None:
        state.on_frame_assembled = on_frame_assembled
