"""Replicated control plane (ROADMAP open item 2).

Three pieces make the master survivable and horizontally scalable:

- ``ha/ledger.py`` — the write-ahead **job ledger**: an append-only,
  fsync'd, segmented JSONL journal of job-lifecycle / unit-finished /
  frame-assembled transitions with periodic snapshots and a
  format-versioned replay path. The PR-4 exactly-once dedup ledger is
  the in-memory half of this; the WAL is the half that survives the
  process.
- ``ha/chaos.py`` — **master failover**, driven end to end by the chaos
  engine: kill the primary mid-job, start a standby on the same port,
  replay the ledger, re-adopt the live workers through the existing
  reconnect + late-joiner-replay path, fence stale traffic with the
  monotonic epoch the ledger mints per master incarnation.
- ``ha/shards.py`` — the **shard router** front end: one JSON-lines
  control socket that hashes submissions across N master shards, each
  owning a slice of the worker pool.
"""

from tpu_render_cluster.ha.ledger import (
    AsyncLedgerAppender,
    JobLedger,
    LedgerCorruptError,
    LedgerReplay,
)

__all__ = [
    "AsyncLedgerAppender",
    "JobLedger",
    "LedgerCorruptError",
    "LedgerReplay",
]
