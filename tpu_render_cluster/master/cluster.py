"""Cluster manager: the master's top-level orchestration.

Lifecycle (reference: master/src/cluster/mod.rs:484-672):
bind -> accept connections (3-step app handshake; first-connection builds a
worker, reconnecting swaps the socket into the existing logical connection)
-> barrier-wait for ``wait_for_number_of_workers`` -> broadcast job-started
-> run the distribution strategy to completion -> collect every worker's
trace (cancelling its heartbeat first; 600 s budget) -> shut down.

Improvements over the reference, kept behaviorally compatible:
- late-joining workers receive ``event_job-started`` at handshake time (the
  reference acknowledges this hole at master/src/cluster/mod.rs:616-617);
- a worker that misses heartbeats or fails mid-RPC is *evicted*: its queued
  frames return to the pending pool so the job still finishes (the
  reference leaves them assigned forever — SURVEY.md §5.3).
"""

from __future__ import annotations

import asyncio
import logging
import time

from pathlib import Path

from tpu_render_cluster import PROTOCOL_VERSION
from tpu_render_cluster.ha.ledger import AsyncLedgerAppender
from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.assembly import FrameAssemblyService
from tpu_render_cluster.master.speculate import (
    SpeculationService,
    speculation_loop,
)
from tpu_render_cluster.master.state import ClusterManagerState, FrameStatus
from tpu_render_cluster.master.strategies import run_strategy
from tpu_render_cluster.master.worker_handle import WorkerHandle
from tpu_render_cluster.obs import (
    FlightRecorder,
    HistorySampler,
    HistoryStore,
    LoopLagMonitor,
    MetricsRegistry,
    SnapshotWriter,
    TimelineProcess,
    Tracer,
    get_registry,
    merge_wire,
    resolve_flight_directory,
    tracer_process,
)
from tpu_render_cluster.obs.flightrec import (
    TRIGGER_EPOCH_FENCE,
    TRIGGER_JOB_FAILURE,
    TRIGGER_MASTER_FAILOVER,
    TRIGGER_SLO_ALERT,
    TRIGGER_WORKER_EVICTION,
)
from tpu_render_cluster.obs.http import TelemetryServer
from tpu_render_cluster.obs.slo import TRANSITION_FIRE, SloService, slo_loop
from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.traces.master_trace import MasterTrace
from tpu_render_cluster.traces.worker_trace import WorkerTrace
from tpu_render_cluster.transport.reconnect import (
    ReconnectableServerConnection,
    TransportMetrics,
)
from tpu_render_cluster.transport.wirecost import WireAccounting
from tpu_render_cluster.transport.ws import (
    WebSocketClosed,
    WebSocketConnection,
    websocket_accept,
)
from tpu_render_cluster.utils.cancellation import CancellationToken

logger = logging.getLogger(__name__)

HANDSHAKE_TIMEOUT = 30.0
BARRIER_POLL_SECONDS = 1.0  # reference: master/src/cluster/mod.rs:568-585


def job_state_view(state: ClusterManagerState) -> dict:
    """One job's live work-unit accounting + exactly-once ledger (the
    shared shape of the single-job and scheduler ``jobs`` sections). The
    ``frames_*`` keys count UNITS (tiles under a tile grid) — the quantity
    the dispatch/dedup machinery meters; the ``assembly`` section carries
    the frame-level view for tiled jobs."""
    total = len(state.frames)
    finished = state.finished_count()
    pending = state.pending_count()
    view = {
        "frames_total": total,
        "frames_finished": finished,
        "frames_pending": pending,
        "frames_in_flight": total - finished - pending,
        "ledger": dict(state.ledger),
    }
    if state.job.tile_grid is not None:
        view["assembly"] = state.assembly_view()
    return view


class ClusterManager:
    """Runs one job across a cluster of connected workers."""

    def __init__(
        self,
        host: str,
        port: int,
        job: BlenderJob | None,
        *,
        metrics: MetricsRegistry | None = None,
        span_tracer: Tracer | None = None,
        metrics_snapshot_path: str | Path | None = None,
        dispatch_delay_fn=None,
        output_base_directory: str | Path | None = None,
        telemetry_port: int | None = None,
        ledger=None,
        ledger_resume: bool = False,
        flight_directory: str | Path | None = None,
    ) -> None:
        self.host = host
        self.port = port
        # Write-ahead job ledger (ha/ledger.py; None = the reference
        # single-incarnation behavior, byte-identical wire traffic). When
        # set, the master stamps the ledger's epoch on handshakes and
        # queue-adds, journals every unit-finished/frame-assembled
        # transition, and — on a restart/standby takeover — starts from
        # the replayed finished set instead of re-rendering it.
        self.ledger = ledger
        self.epoch: int | None = ledger.epoch if ledger is not None else None
        # ``job=None`` is the SERVICE mode used by the multi-job scheduler
        # subclass (sched/manager.py JobManager): no frame table exists at
        # construction; per-job states are created at admission and looked
        # up through ``_state_for_job``. The single-job contract (one job,
        # one state, reference wire traffic) is unchanged when a job is
        # given.
        self.job = job
        # Chaos shim: ``(worker_id, frame_index) -> seconds`` to stall a
        # queue-add dispatch (master/worker_handle.py). None in production.
        self._dispatch_delay_fn = dispatch_delay_fn
        self.state = ClusterManagerState(job) if job is not None else None
        self.workers: dict[int, WorkerHandle] = {}
        self.cancellation = CancellationToken()
        # Defaults to the process-global registry so process-scoped sources
        # (ops/assignment's greedy-fallback counter, the render path) land
        # in the same snapshot as the master's own series.
        self.metrics = metrics if metrics is not None else get_registry()
        self.span_tracer = span_tracer or Tracer("master")
        self._transport_metrics = TransportMetrics(self.metrics)
        # Tiled frames: when the last tile of a frame lands, the assembly
        # service stitches the tile files into the frame's final image
        # (master/assembly.py). ``output_base_directory`` resolves a job's
        # %BASE% output prefix on the master's filesystem (None = the
        # job's paths are usable as-is, e.g. the in-process harness).
        self.assembly = FrameAssemblyService(
            metrics=self.metrics,
            span_tracer=self.span_tracer,
            base_directory=output_base_directory,
        )
        # Predictive scheduling (ROADMAP item 3): the shared cost model —
        # warm-started from a ``TRC_COST_MODEL`` snapshot when one is set,
        # refined online from every completion observation — plus the
        # straggler-hedging speculation engine (master/speculate.py; off
        # unless ``TRC_SPECULATION`` enables it). Imported lazily: the
        # sched package's __init__ imports the scheduler, which imports
        # this module.
        from tpu_render_cluster.sched.cost_model import (
            DEFAULT_COST_EMA_ALPHA,
            CostModelService,
            load_cost_model_from_env,
        )

        # A tpu-batch job's configured EMA alpha governs the shared model
        # (a loaded TRC_COST_MODEL snapshot carries its own).
        alpha = DEFAULT_COST_EMA_ALPHA
        if (
            job is not None
            and job.frame_distribution_strategy.strategy_type == "tpu-batch"
            and job.frame_distribution_strategy.tpu_batch is not None
        ):
            alpha = job.frame_distribution_strategy.tpu_batch.cost_ema_alpha
        self.cost_service = CostModelService(
            load_cost_model_from_env(), alpha=alpha, metrics=self.metrics
        )
        self.speculation = SpeculationService(
            cost=self.cost_service,
            metrics=self.metrics,
            span_tracer=self.span_tracer,
        )
        # Continuous observability (obs/history.py + obs/flightrec.py):
        # the embedded metrics-history ring sampled by an in-process loop
        # (started at bind, final sample at shutdown) serves /history and
        # feeds the always-on flight recorder, which dumps a blackbox
        # bundle on SLO fires, evictions, job failures, epoch-fence
        # refusals, and failover adoption.
        self.history = HistoryStore(self.metrics)
        self._history_sampler = HistorySampler(self.history)
        self.flightrec = FlightRecorder(
            history=self.history,
            span_tracer=self.span_tracer,
            metrics=self.metrics,
            directory=resolve_flight_directory(
                flight_directory,
                Path(metrics_snapshot_path).parent
                if metrics_snapshot_path is not None
                else None,
            ),
        )
        # Event-loop lag probe (obs/loopmon.py): started at bind, stopped
        # at shutdown; a sample over TRC_OBS_LOOPMON_THRESHOLD counts a
        # blocked episode and flight-records the window.
        self.loopmon = LoopLagMonitor(
            self.metrics,
            role="master",
            span_tracer=self.span_tracer,
            flightrec=self.flightrec,
        )
        # Handshake-path wire accounting (transport/wirecost.py); the
        # per-worker handles carry their own instance over the same
        # registry, so all master-side series land in one family.
        self._wire = WireAccounting(self.metrics)
        # Per-job SLO engine (obs/slo.py): fed by every winning result's
        # dispatch-to-result latency, ticked by a sidecar (single-job) or
        # the scheduler loop (service mode). Inert for jobs without an
        # [slo] table.
        self.slo = SloService(
            metrics=self.metrics,
            span_tracer=self.span_tracer,
            on_alert=self._on_slo_alert,
        )
        # Pull-based telemetry endpoints (obs/http.py): /metrics (Prom
        # text exposition), /healthz, /clusterz (cluster_view). None =
        # disabled; 0 = ephemeral port (resolved after _bind_server).
        self.telemetry = (
            TelemetryServer(
                self.metrics,
                host=host,
                port=telemetry_port,
                clusterz_fn=self.cluster_view,
                healthz_fn=self._healthz_view,
                history=self.history,
            )
            if telemetry_port is not None
            else None
        )
        # When set, a 1 Hz SnapshotWriter keeps this file fresh while the
        # job runs (live inspection), with a final write at shutdown.
        self._snapshot_writer = (
            SnapshotWriter(
                metrics_snapshot_path,
                self.metrics,
                extra_fn=self.cluster_view,
            )
            if metrics_snapshot_path is not None
            else None
        )
        self._job_started = False
        self._server: asyncio.Server | None = None
        # Frames a previous incarnation finished every tile of but never
        # stitched (crash between last tile and assembly): re-scheduled
        # once the job starts, from the tile files already on disk.
        self._replay_stitch_frames: list[int] = []
        self.replayed_units = 0
        # Durable appends from the event loop go through ONE FIFO appender
        # (ha/ledger.py): the fsync runs on a worker thread, never on the
        # loop serving heartbeats (the loop-blocking lint enforces this).
        self.ledger_appender = (
            AsyncLedgerAppender(self.ledger) if self.ledger is not None else None
        )
        if self.ledger is not None and self.state is not None:
            from tpu_render_cluster.ha.failover import adopt_ledger

            # Open generations always restore (a standby resuming an
            # in-flight job); closed ones only under the explicit
            # ``--resume`` contract — a plain re-run of a completed job
            # starts a fresh generation and renders from scratch.
            self.replayed_units, self._replay_stitch_frames = adopt_ledger(
                self.state,
                self.ledger,
                metrics=self.metrics,
                include_closed=ledger_resume,
                spec=job.to_dict(),
                appender=self.ledger_appender,
            )
            if self.replayed_units or self._replay_stitch_frames:
                # This incarnation adopted a predecessor's in-flight job:
                # record the takeover as a post-mortem bundle (the window
                # is empty this early — the bundle documents the adoption
                # itself: epoch, replayed unit count, pending stitches).
                self.flightrec.trigger(
                    TRIGGER_MASTER_FAILOVER,
                    {
                        "epoch": self.epoch,
                        "replayed_units": self.replayed_units,
                        "replay_stitch_frames": len(self._replay_stitch_frames),
                        "job": job.job_name,
                    },
                )

    # -- multi-job hooks (overridden by sched/manager.py JobManager) --------

    def _state_for_job(self, job_name: str | None) -> ClusterManagerState | None:
        """Map a worker event's ``job_name`` to the owning frame table.

        Single-job masters own exactly one state and every event belongs
        to it; the scheduler subclass resolves against its active-job map
        (returning None for cancelled/finished jobs, whose late events are
        then accounted as stale instead of applied).
        """
        return self.state

    def _active_job_announcements(self) -> list[tuple[int | None, str | None]]:
        """(trace_id, job_id) per job a late-joining worker must learn of.

        Resolves the inherited reference FIXME (master/src/cluster/mod.rs:
        616-617): a worker whose handshake completes after job start still
        receives the job-started event(s) — generalized to *every* active
        job so it holds with several jobs running concurrently.
        """
        if self._job_started and self.state is not None:
            return [(self.state.trace_id, None)]
        return []

    # -- public ------------------------------------------------------------

    async def _bind_server(self) -> None:
        """Bind the accept loop + start the live snapshot writer."""
        self._server = await asyncio.start_server(
            self._on_tcp_connection, self.host, self.port
        )
        actual_port = self._server.sockets[0].getsockname()[1]
        self.port = actual_port
        logger.info("Master listening on %s:%d", self.host, actual_port)
        if self._snapshot_writer is not None:
            self._snapshot_writer.start()
        self._history_sampler.start()
        self.loopmon.start()
        if self.telemetry is not None:
            await self.telemetry.start()

    def _on_slo_alert(self, alert) -> None:
        """SLO edge -> flight recorder: a FIRE is exactly the incident the
        blackbox exists for (the clear is history, not an emergency)."""
        if alert.transition == TRANSITION_FIRE:
            self.flightrec.trigger(TRIGGER_SLO_ALERT, alert.to_dict())

    def _on_worker_protocol_event(self, kind: str, detail: dict) -> None:
        """Worker-handle digest feed for the flight recorder's ring; an
        epoch-fence refusal additionally triggers a dump — stale traffic
        arriving at a live master means a failover just happened and the
        predecessor's final moments are worth keeping."""
        self.flightrec.record_event(kind, **detail)
        if kind == "stale_epoch_refusal":
            self.flightrec.trigger(TRIGGER_EPOCH_FENCE, detail)

    def _healthz_view(self) -> dict:
        view = {
            "role": "master",
            "workers_connected": len(self.workers),
            "workers_live": len(self.live_workers()),
            "job_started": self._job_started,
        }
        if self.epoch is not None:
            view["epoch"] = self.epoch
        return view

    async def _shutdown_server(self) -> None:
        """Stop the writer, cancel, close worker sockets, close the server."""
        if self.telemetry is not None:
            await self.telemetry.stop()
        await self.loopmon.stop()
        await self._history_sampler.stop()
        if self._snapshot_writer is not None:
            await self._snapshot_writer.stop()
        self.cancellation.cancel()
        # Close worker sockets BEFORE wait_closed(): since 3.12,
        # Server.wait_closed() waits for every live connection handler.
        for worker in list(self.workers.values()):
            await worker.shutdown()
        self._server.close()
        try:
            await asyncio.wait_for(self._server.wait_closed(), 5.0)
        except asyncio.TimeoutError:
            logger.warning("Server close timed out; continuing shutdown.")
        # Let deferred incident bundles land before the loop goes away.
        await self.flightrec.drain()
        if self.ledger is not None:
            if self.ledger_appender is not None:
                await self.ledger_appender.stop()
            try:
                await asyncio.to_thread(self.ledger.close)
            except OSError as e:
                logger.warning("Ledger close failed: %s", e)

    async def initialize_server_and_run_job(
        self,
    ) -> tuple[MasterTrace, list[tuple[str, WorkerTrace]]]:
        """Bind, run the job to completion, and collect all traces."""
        await self._bind_server()
        try:
            master_trace = await self._wait_for_workers_and_run_job()
            with self.span_tracer.span("collect traces", cat="master", track="job"):
                worker_traces = await self._collect_worker_traces()
            return master_trace, worker_traces
        finally:
            await self._shutdown_server()

    def live_workers(self) -> list[WorkerHandle]:
        return [w for w in self.workers.values() if not w.is_dead]

    def _jobs_view(self) -> dict:
        """Per-job live view folded into ``cluster_view()['jobs']`` (and
        with it into ``metrics-live.json``). Single-job masters report
        their one job with a trivially-full share; the scheduler subclass
        reports every submission with its fair-share targets."""
        if self.state is None:
            return {}
        return {
            self.state.job.job_name: {
                **job_state_view(self.state),
                "state": (
                    "finished" if self.state.all_frames_finished()
                    else ("running" if self._job_started else "waiting")
                ),
                "share_target": 1.0,
                "share_achieved": 1.0,
            }
        }

    def cluster_view(self) -> dict:
        """Live cluster-wide extras for the metrics snapshot.

        Combines the master's own frame-table view (all jobs' frame tables
        summed) with the most recent compact metrics payload each worker
        piggybacked on its heartbeat pong, plus their ``merge_wire``
        aggregation, and a per-job ``jobs`` section.
        """
        worker_payloads = {
            pm.worker_id_to_string(w.worker_id): w.latest_worker_metrics
            for w in self.workers.values()
            if w.latest_worker_metrics is not None
        }
        jobs_view = self._jobs_view()
        view: dict = {
            "cluster": {
                "frames_total": sum(
                    v["frames_total"] for v in jobs_view.values()
                ),
                "frames_finished": sum(
                    v["frames_finished"] for v in jobs_view.values()
                ),
                "frames_pending": sum(
                    v["frames_pending"] for v in jobs_view.values()
                ),
                "workers": {
                    pm.worker_id_to_string(w.worker_id): {
                        "queue_depth": len(w.queue),
                        "is_dead": w.is_dead,
                        "frames_stolen": w.frames_stolen_count,
                    }
                    for w in self.workers.values()
                },
            },
            "jobs": jobs_view,
        }
        prediction = self.cost_service.prediction_view()
        if prediction.get("samples_observed") or prediction.get("predictions"):
            view["prediction"] = prediction
        if self.speculation.config.enabled or self.speculation.launched_total:
            view["speculation"] = self.speculation.view()
        if self.slo.tracked():
            view["slo"] = self.slo.view()
        if self.flightrec.triggers or self.flightrec.dumps:
            view["flight"] = self.flightrec.view()
        if worker_payloads:
            view["worker_metrics"] = worker_payloads
            # Payloads crossed the wire from workers we don't control;
            # decode only shape-checks the top level, so a version-skewed
            # worker must degrade the aggregate view, not kill persistence.
            try:
                view["cluster_metrics"] = merge_wire(worker_payloads.values())
            except Exception as e:  # noqa: BLE001
                logger.warning("Worker metrics payloads failed to merge: %s", e)
        return view

    def timeline_other_data(self) -> dict | None:
        """Extra ``otherData`` for the merged cluster timeline (the
        scheduler subclass stamps its per-job summary; single-job masters
        add nothing)."""
        return None

    def cluster_timeline_processes(self) -> list[TimelineProcess]:
        """Everything the merged cluster timeline needs, master row first.

        One entry per process: the master's own span tracer (offset 0 by
        definition) plus, for every worker that piggybacked its span
        events on the job-finished response, those events tagged with the
        heartbeat estimator's offset for rebasing at export time. Workers that sent nothing (C++
        daemons, version skew) are simply absent — their causal links
        still show as master-side assign/result spans.
        """
        processes = [tracer_process(self.span_tracer, 0.0)]
        for worker in self.workers.values():
            collected = worker.collected_span_events
            if not collected or not isinstance(collected.get("events"), list):
                continue
            # The payload crossed the wire from a worker we don't control
            # and decode only shape-checks the top level: drop non-object
            # entries so a version-skewed peer degrades its own row instead
            # of killing the master's end-of-job artifact export.
            events = [e for e in collected["events"] if isinstance(e, dict)]
            if len(events) != len(collected["events"]):
                logger.warning(
                    "Worker %08x sent %d malformed span event(s); skipped.",
                    worker.worker_id,
                    len(collected["events"]) - len(events),
                )
            name = str(
                collected.get("process_name")
                or f"worker-{pm.worker_id_to_string(worker.worker_id)}"
            )
            try:
                dropped = int(collected.get("dropped") or 0)
            except (TypeError, ValueError):
                dropped = 0
            processes.append(
                TimelineProcess(
                    name=name,
                    events=events,
                    # Extrapolate the offset to NOW along the drift fit
                    # (collection time ~ the span timestamps' tail); with
                    # fewer than two samples this is the plain median.
                    offset_seconds=worker.clock_offset.offset_at(time.time()),
                    dropped=dropped,
                )
            )
        return processes

    # -- accept loop --------------------------------------------------------

    async def _on_tcp_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """WS upgrade + 3-step application handshake.

        Reference: master/src/cluster/mod.rs:280-481.
        """
        try:
            ws = await asyncio.wait_for(
                websocket_accept(reader, writer), HANDSHAKE_TIMEOUT
            )
        except Exception as e:  # noqa: BLE001
            logger.debug("WS upgrade failed: %s", e)
            writer.close()
            return
        try:
            with self.span_tracer.span(
                "handshake", cat="transport", track="accept",
                args={"peer": ws.peer_address()},
            ):
                await asyncio.wait_for(self._perform_handshake(ws), HANDSHAKE_TIMEOUT)
        except Exception as e:  # noqa: BLE001
            logger.warning("Handshake with %s failed: %s", ws.peer_address(), e)
            ws.abort()

    async def _perform_handshake(self, ws: WebSocketConnection) -> None:
        if self.cancellation.is_cancelled():
            # Shutting down (or crashed and being torn down): a reconnect
            # accepted NOW would swap a live socket into a handle whose
            # reader tasks are already stopped, parking the worker on an
            # open-but-dead connection instead of letting it fail over.
            ws.abort()
            return
        # The optional epoch tells a reconnecting worker whether this is
        # the incarnation it lost (resume the session) or a successor
        # (re-announce fresh); epoch-less masters stay byte-identical.
        await ws.send_text(
            self._wire.encode(
                pm.MasterHandshakeRequest(PROTOCOL_VERSION, epoch=self.epoch)
            )
        )
        response = self._wire.decode(await ws.receive_text())
        if not isinstance(response, pm.WorkerHandshakeResponse):
            raise WebSocketClosed(f"Expected handshake response, got {type(response)}")

        if response.handshake_type == pm.HANDSHAKE_TYPE_FIRST_CONNECTION:
            await ws.send_text(
                self._wire.encode(pm.MasterHandshakeAcknowledgement(True))
            )
            await self._register_new_worker(response.worker_id, ws)
        elif response.handshake_type == pm.HANDSHAKE_TYPE_RECONNECTING:
            known = response.worker_id in self.workers
            await ws.send_text(
                self._wire.encode(pm.MasterHandshakeAcknowledgement(known))
            )
            if not known:
                # Reference: reconnect from an unknown worker is refused
                # (master/src/cluster/mod.rs:378-385).
                logger.warning(
                    "Refusing reconnect from unknown worker %08x", response.worker_id
                )
                ws.abort()
                return
            worker = self.workers[response.worker_id]
            if self.cancellation.is_cancelled():
                # Teardown raced the handshake: the handle's reader tasks
                # are stopping, so adopting this socket would strand the
                # worker — abort and let it retry against our successor.
                ws.abort()
                return
            worker.connection.replace_inner_connection(ws)
            self.metrics.counter(
                "master_worker_reconnects_total",
                "Reconnect handshakes accepted from known workers",
                labels=("worker",),
            ).inc(worker=pm.worker_id_to_string(response.worker_id))
            worker.logger.info("Worker reconnected from %s", ws.peer_address())
        else:
            raise WebSocketClosed(
                f"Unknown handshake type: {response.handshake_type!r}"
            )

    async def _register_new_worker(self, worker_id: int, ws: WebSocketConnection) -> None:
        if worker_id in self.workers:
            logger.warning(
                "Worker id collision (%08x); refusing duplicate.", worker_id
            )
            ws.abort()
            return
        connection = ReconnectableServerConnection(
            ws, metrics=self._transport_metrics
        )
        dispatch_delay_fn = None
        if self._dispatch_delay_fn is not None:
            manager_fn = self._dispatch_delay_fn
            dispatch_delay_fn = lambda frame_index: manager_fn(  # noqa: E731
                worker_id, frame_index
            )
        worker = WorkerHandle(
            worker_id,
            connection,
            self.state,
            on_dead=self._evict_worker,
            metrics=self.metrics,
            span_tracer=self.span_tracer,
            dispatch_delay_fn=dispatch_delay_fn,
            state_resolver=self._state_for_job,
            on_frame_complete=self.assembly.schedule,
            on_unit_latency=self.slo.observe_unit_latency,
            on_protocol_event=self._on_worker_protocol_event,
            epoch=self.epoch,
        )
        self.workers[worker_id] = worker
        worker.start()
        logger.info(
            "Worker %08x connected from %s (%d/%d).",
            worker_id,
            ws.peer_address(),
            len(self.workers),
            self.job.wait_for_number_of_workers if self.job is not None else 0,
        )
        # Late joiners still learn which jobs have started (reference FIXME
        # at master/src/cluster/mod.rs:616-617) — replayed for EVERY active
        # job, which becomes load-bearing once several run concurrently.
        for trace_id, job_id in self._active_job_announcements():
            await worker.send_job_started(trace_id=trace_id, job_id=job_id)

    async def _evict_worker(self, worker: WorkerHandle, reason: str) -> None:
        """Return a dead worker's units to the pool so its jobs can finish."""
        logger.warning("Evicting worker %08x: %s", worker.worker_id, reason)
        self.flightrec.trigger(
            TRIGGER_WORKER_EVICTION,
            {
                "worker": pm.worker_id_to_string(worker.worker_id),
                "reason": reason,
                "queued_units": len(worker.queue),
            },
        )
        for frame in worker.queue.all_frames():
            state = self._state_for_job(frame.job_name)
            if state is None:
                continue  # the owning job is already gone
            record = state.frames.get(frame.unit)
            if (
                record is not None
                and record.status is not FrameStatus.FINISHED
                and record.worker_id == worker.worker_id
            ):
                # Ownership check: this worker's mirror can hold units
                # whose LIVE assignment is elsewhere (a speculative twin,
                # a ghost copy from a superseded dispatch) — requeueing
                # those would put a unit in play twice while its primary
                # still renders it.
                state.return_frame_to_pending(frame.unit)
        # No ghost assignments: a dead worker's mirror must not keep
        # offering steal candidates (or claim queue depth) for frames that
        # just went back to the pool.
        worker.queue.clear()

    # -- job execution ------------------------------------------------------

    async def _wait_for_workers_and_run_job(self) -> MasterTrace:
        target = self.job.wait_for_number_of_workers
        logger.info("Waiting for %d workers to connect...", target)
        warmup_task: asyncio.Task | None = None
        strategy = self.job.frame_distribution_strategy
        if strategy.strategy_type == "tpu-batch":
            # Compile the auction kernel while workers connect so the first
            # scheduling tick doesn't pay XLA compilation inside the job.
            from tpu_render_cluster.master.tpu_batch import (
                RATE_TARGET_CAP,
                scaled_slot_cap,
            )
            from tpu_render_cluster.ops.assignment import warmup

            assert strategy.tpu_batch is not None
            # Warm up to the tick loop's scaled slot cap — warming only
            # MAX_SLOTS_PER_TICK would clamp >64-worker clusters back to
            # 128 slots/tick — bounded by the cluster's actual slot demand
            # (target-or-rate-cap per worker).
            demand_bound = max(
                strategy.tpu_batch.target_queue_size, RATE_TARGET_CAP
            ) * max(1, target)
            max_slots = min(scaled_slot_cap(target), demand_bound)
            warmup_task = asyncio.create_task(asyncio.to_thread(warmup, max_slots))
        with self.span_tracer.span(
            "barrier wait", cat="master", track="job", args={"target": target}
        ):
            try:
                while len(self.workers) < target:
                    if self.cancellation.is_cancelled():
                        raise RuntimeError("Cancelled while waiting for workers.")
                    await asyncio.sleep(BARRIER_POLL_SECONDS)
                if warmup_task is not None:
                    try:
                        await warmup_task
                    except Exception as e:  # noqa: BLE001 - latency opt, not fatal
                        logger.warning(
                            "Auction warmup failed (%s); first ticks will pay "
                            "compilation lazily.",
                            e,
                        )
            except BaseException:
                if warmup_task is not None and not warmup_task.done():
                    warmup_task.cancel()
                raise
        logger.info("All %d workers connected; starting job.", target)

        self._job_started = True
        for worker in self.live_workers():
            await worker.send_job_started()
        if self._replay_stitch_frames:
            # Tiled failover edge: every tile of these frames landed under
            # the predecessor but the stitch never did — re-schedule it
            # from the tile files on disk before new results interleave.
            for frame_index in self._replay_stitch_frames:
                self.assembly.schedule(self.state, frame_index)
            self._replay_stitch_frames = []

        self.metrics.gauge(
            "master_job_units", "Work units in the job's frame table"
        ).set(len(self.state.frames))
        start = time.time()
        self.slo.register_job(self.job, started_at=start)
        with self.span_tracer.span(
            "run job",
            cat="master",
            track="job",
            args={"strategy": strategy.strategy_type, "frames": len(self.state.frames)},
        ):
            # Speculation sidecar: strategy-agnostic tail hedging (no-op
            # unless TRC_SPECULATION enabled). Runs beside the strategy so
            # the reference dispatch loops stay untouched.
            spec_task = asyncio.create_task(
                speculation_loop(
                    self.job,
                    self.state,
                    self.live_workers,
                    self.cancellation,
                    self.speculation,
                ),
                name="speculation-loop",
            )
            # SLO sidecar: periodic burn/deadline evaluation while the
            # strategy runs (only for jobs that declared objectives).
            slo_task = (
                asyncio.create_task(
                    slo_loop(self.slo, self.state, self.cancellation),
                    name="slo-loop",
                )
                if self.job.slo is not None
                else None
            )
            try:
                await run_strategy(
                    self.job,
                    self.state,
                    self.live_workers,
                    self.cancellation,
                    cost_service=self.cost_service,
                )
                # Let the sidecar settle open races (outcomes accounted,
                # losers unqueued) before the finalization sweep audits
                # the mirrors; it exits promptly once all frames finished.
                await spec_task
            finally:
                if not spec_task.done():
                    spec_task.cancel()
                    await asyncio.gather(spec_task, return_exceptions=True)
                if slo_task is not None:
                    slo_task.cancel()
                    await asyncio.gather(slo_task, return_exceptions=True)
                # Final SLO evaluation at the job's true end time — the
                # deadline verdict and the closing attainment are stamped
                # whether the strategy finished or raised.
                self.slo.finish_job(self.job.job_name)
                if self.state.failed_reason:
                    # Deterministic unit failure killed the job: dump the
                    # window leading up to it while the evidence is warm.
                    self.flightrec.trigger(
                        TRIGGER_JOB_FAILURE,
                        {
                            "job": self.job.job_name,
                            "reason": self.state.failed_reason,
                        },
                    )
                # Accepted late results can finish a unit while its
                # re-dispatched twin still sits queued on a live worker;
                # the job is over, so those mirror entries are ghosts now
                # — sweep them (closing their flows) before anything
                # audits the mirrors. Tiled jobs: the last tile's
                # finished event schedules the frame's stitch
                # asynchronously — completed frames' stitches must land
                # on disk even when the strategy RAISES (a failed job
                # must not abandon mid-write assembly tasks).
                for worker in self.live_workers():
                    worker.sweep_finished_units(self._state_for_job)
                await self.assembly.drain()
        finish = time.time()
        if not self.state.all_frames_finished():
            raise RuntimeError("Strategy exited before all frames finished.")
        if self.ledger_appender is not None:
            # Ordered AFTER every queued unit append; drained so the
            # journal's lifecycle closure is durable before we report the
            # job finished (the same point the synchronous append gave).
            self.ledger_appender.schedule(
                self.ledger.append_job_finished, self.job.job_name
            )
            await self.ledger_appender.drain()
        logger.info("All frames finished in %.2f s.", finish - start)
        return MasterTrace(job_start_time=start, job_finish_time=finish)

    async def _collect_worker_traces(self) -> list[tuple[str, WorkerTrace]]:
        """Gather traces; key format ``<worker_id:08x>-<addr>``.

        Reference: master/src/cluster/mod.rs:514-541.
        """
        traces: list[tuple[str, WorkerTrace]] = []
        for worker in self.workers.values():
            worker.cancel_heartbeat()
            if worker.is_dead:
                logger.warning(
                    "Skipping trace collection for dead worker %08x.",
                    worker.worker_id,
                )
                continue
            try:
                trace = await worker.finish_job_and_get_trace()
            except Exception as e:  # noqa: BLE001
                logger.error(
                    "Could not collect trace from %08x: %s", worker.worker_id, e
                )
                continue
            name = f"{pm.worker_id_to_string(worker.worker_id)}-{worker.connection.last_known_address}"
            traces.append((name, trace))
        return traces
