"""Per-worker façade on the master.

Aggregates the logical (reconnectable) connection, sender, router, queue
mirror, heartbeat task, and incoming-event handling — the asyncio
re-expression of the reference's ``Worker`` struct
(master/src/connection/mod.rs:36-423). Public surface:
``queue_frame`` / ``unqueue_frame`` (RPC + mirror/state sync),
``finish_job_and_get_trace`` (600 s timeout RPC —
master/src/connection/requester.rs:97), and ``maintain_heartbeat``
(10 s ping interval — master/src/connection/mod.rs:36-37).

Improvements over the reference (SURVEY.md §7 "known bugs to fix"):
an errored finished-event returns the frame to the pending pool instead of
hanging the job, and a heartbeat failure triggers worker eviction via the
``on_dead`` callback instead of leaving frames assigned to a ghost.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.queue_mirror import FrameOnWorker, WorkerQueueMirror
from tpu_render_cluster.master.state import ClusterManagerState
from tpu_render_cluster.obs import ClockOffsetEstimator, MetricsRegistry, Tracer
from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.transport.actors import MessageRouter, SenderHandle, request_response
from tpu_render_cluster.transport.reconnect import ReconnectableServerConnection
from tpu_render_cluster.utils.logging import WorkerLogger

HEARTBEAT_INTERVAL_SECONDS = 10.0  # reference: master/src/connection/mod.rs:36
HEARTBEAT_RESPONSE_TIMEOUT = 60.0  # reference: master/src/connection/receiver.rs:27
JOB_FINISH_TRACE_TIMEOUT = 600.0  # reference: master/src/connection/requester.rs:97


class WorkerHandle:
    """One connected worker, as seen by the master."""

    def __init__(
        self,
        worker_id: int,
        connection: ReconnectableServerConnection,
        state: ClusterManagerState,
        *,
        on_dead: Callable[["WorkerHandle", str], Awaitable[None]] | None = None,
        metrics: MetricsRegistry | None = None,
        span_tracer: Tracer | None = None,
    ) -> None:
        self.worker_id = worker_id
        self.connection = connection
        self.state = state
        self.queue = WorkerQueueMirror()
        self.frames_stolen_count = 0
        self.is_dead = False
        self.metrics = metrics
        self.span_tracer = span_tracer
        # Most recent compact metrics payload this worker piggybacked on a
        # heartbeat pong (None until the first instrumented pong arrives).
        self.latest_worker_metrics: dict | None = None
        # NTP-style clock-offset estimate (worker clock - master clock),
        # fed by the heartbeat's four timestamps; the merged cluster
        # timeline rebases this worker's span events by it.
        self.clock_offset = ClockOffsetEstimator()
        # Chrome trace events the worker piggybacked on its job-finished
        # response ({"process_name", "events"}), for the cluster timeline.
        self.collected_span_events: dict | None = None
        # Observed per-frame render durations (for scheduler cost models).
        self._rendering_started_at: dict[int, float] = {}
        self._completion_observations: list[tuple[int, float]] = []
        self._on_dead = on_dead
        self.logger = WorkerLogger(
            logging.getLogger("master.worker"),
            pm.worker_id_to_string(worker_id),
            connection.last_known_address,
        )

        self.sender = SenderHandle(self._send_message)
        self.router = MessageRouter(self._receive_message)
        self._heartbeat_task: asyncio.Task | None = None
        self._events_task: asyncio.Task | None = None
        self._tasks_started = False

    # -- transport adapters -------------------------------------------------

    async def _send_message(self, message: pm.Message) -> None:
        await self.connection.send_text(pm.encode_message(message))

    async def _receive_message(self) -> pm.Message:
        return pm.decode_message(await self.connection.receive_text())

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Spawn sender/receiver/heartbeat/event tasks."""
        assert not self._tasks_started
        self._tasks_started = True
        self.sender.start()
        self.router.start()
        self._events_task = asyncio.create_task(
            self._manage_incoming_events(), name=f"events-{self.worker_id:08x}"
        )
        self._heartbeat_task = asyncio.create_task(
            self._maintain_heartbeat(), name=f"heartbeat-{self.worker_id:08x}"
        )

    def cancel_heartbeat(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()

    async def shutdown(self) -> None:
        self.cancel_heartbeat()
        if self._events_task is not None:
            self._events_task.cancel()
        await self.router.stop()
        await self.sender.stop()
        self.connection.close()

    async def _mark_dead(self, reason: str) -> None:
        if self.is_dead:
            return
        self.is_dead = True
        self.logger.warning("Worker marked dead: %s", reason)
        # Terminate the Perfetto flows of every assignment still mirrored
        # here: the requeued frames open fresh chains elsewhere, and a
        # dangling flow-start would fail the trace validator on artifacts
        # from any run that lost a worker.
        now = time.time()
        for frame in self.queue.all_frames():
            self._complete_frame_flow(
                "frame evicted",
                frame.frame_index,
                frame.trace,
                start_wall=now,
                duration=0.0,
                extra_args={"reason": reason},
            )
        if self.metrics is not None:
            self.metrics.counter(
                "master_worker_evictions_total", "Workers marked dead and evicted"
            ).inc()
            # Zero (don't leave stale) this worker's depth: its frames are
            # returned to pending and re-queue elsewhere, and a frozen
            # nonzero series would double-count them in the live view.
            self.metrics.gauge(
                "master_worker_queue_depth",
                "Frames currently mirrored on each worker's queue",
                labels=("worker",),
            ).set(0, worker=self._worker_label())
        if self._on_dead is not None:
            await self._on_dead(self, reason)

    # -- observability helpers ----------------------------------------------

    def _worker_label(self) -> str:
        return pm.worker_id_to_string(self.worker_id)

    def _update_queue_depth_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "master_worker_queue_depth",
                "Frames currently mirrored on each worker's queue",
                labels=("worker",),
            ).set(len(self.queue), worker=self._worker_label())

    def _complete_frame_flow(
        self,
        name: str,
        frame_index: int,
        trace: pm.TraceContext | None,
        *,
        start_wall: float,
        duration: float,
        extra_args: dict | None = None,
    ) -> None:
        """Master-side terminal span for one assignment chain (result
        received / frame stolen), with the flow arrowhead bound inside it
        when the assignment's trace context is known."""
        if self.span_tracer is None:
            return
        args = {"frame": frame_index, **(extra_args or {})}
        track = f"worker-{self._worker_label()}"
        if trace is not None:
            args["flow"] = trace.flow_id
        self.span_tracer.complete(
            name,
            cat="master",
            start_wall=start_wall,
            duration=duration,
            track=track,
            args=args,
        )
        if trace is not None:
            self.span_tracer.flow_end(
                "frame",
                id=trace.flow_id,
                ts=start_wall + duration / 2.0,
                cat="frame",
                track=track,
                args={"frame": frame_index},
            )

    # -- scheduling RPCs ----------------------------------------------------

    async def queue_frame(
        self,
        job: BlenderJob,
        frame_index: int,
        *,
        stolen_from: int | None = None,
    ) -> None:
        """RPC a frame onto this worker's queue; sync mirror + global state.

        Reference: master/src/connection/mod.rs:139-168.
        """
        # Fresh span per ASSIGNMENT (not per frame): a re-queued or stolen
        # frame starts a new causal chain with its own Perfetto flow.
        trace = pm.TraceContext.new(self.state.trace_id)
        request = pm.MasterFrameQueueAddRequest.new(job, frame_index, trace=trace)
        rpc_started = time.perf_counter()
        rpc_started_wall = time.time()
        response = await request_response(
            self.sender, self.router, request, pm.WorkerFrameQueueAddResponse
        )
        if response.result != pm.FRAME_QUEUE_ADD_RESULT_ADDED:
            raise RuntimeError(
                f"Worker rejected frame {frame_index}: {response.error_reason}"
            )
        rpc_seconds = time.perf_counter() - rpc_started
        if self.metrics is not None:
            strategy = self.state.job.frame_distribution_strategy.strategy_type
            self.metrics.histogram(
                "master_assignment_latency_seconds",
                "queue-add RPC round-trip (request sent to ack received)",
                labels=("strategy",),
            ).observe(rpc_seconds, strategy=strategy)
        if self.span_tracer is not None:
            # Constant span name (frame index in args) so viewers and the
            # analysis roll-up aggregate all assignments into one stat.
            args = {"frame": frame_index, "flow": trace.flow_id}
            if stolen_from is not None:
                args["stolen_from"] = stolen_from
            track = f"worker-{self._worker_label()}"
            self.span_tracer.complete(
                "assign frame",
                cat="master",
                start_wall=rpc_started_wall,
                duration=rpc_seconds,
                track=track,
                args=args,
            )
            # Flow source, mid-span so it binds inside the assign slice;
            # the worker's queue_wait/read/render/write spans route it and
            # the result-received span terminates it.
            self.span_tracer.flow_start(
                "frame",
                id=trace.flow_id,
                ts=rpc_started_wall + rpc_seconds / 2.0,
                cat="frame",
                track=track,
                args={"frame": frame_index},
            )
        now = time.time()
        self.queue.add(
            FrameOnWorker(
                frame_index, queued_at=now, stolen_from=stolen_from, trace=trace
            )
        )
        self._update_queue_depth_gauge()
        self.state.mark_frame_as_queued(
            frame_index,
            self.worker_id,
            now,
            stolen_from=stolen_from,
            stolen_at=now if stolen_from is not None else None,
        )

    async def unqueue_frame(self, job_name: str, frame_index: int) -> str:
        """RPC-remove a frame (the steal primitive); returns the result enum.

        Tolerates the remove-vs-render races (``already-rendering`` /
        ``already-finished`` — reference: strategies.rs:347-373 leaves those
        to the caller).
        """
        request = pm.MasterFrameQueueRemoveRequest.new(job_name, frame_index)
        rpc_started_wall = time.time()
        rpc_started = time.perf_counter()
        response = await request_response(
            self.sender, self.router, request, pm.WorkerFrameQueueRemoveResponse
        )
        if response.result == pm.FRAME_QUEUE_REMOVE_RESULT_REMOVED:
            removed = self.queue.remove(frame_index)
            self._update_queue_depth_gauge()
            # A successful steal ends this assignment's causal chain (the
            # thief's queue_frame opens a fresh one) — terminate the flow
            # here so no dangling flow-start survives a stolen frame.
            if self.span_tracer is not None:
                self._complete_frame_flow(
                    "frame stolen",
                    frame_index,
                    removed.trace if removed is not None else None,
                    start_wall=rpc_started_wall,
                    duration=time.perf_counter() - rpc_started,
                    extra_args={"result": response.result},
                )
        return response.result

    def has_empty_queue(self) -> bool:
        return len(self.queue) == 0

    def drain_completion_observations(self) -> list[tuple[int, float]]:
        """Take (frame_index, seconds) samples observed since the last call."""
        observations, self._completion_observations = self._completion_observations, []
        return observations

    # -- job lifecycle RPCs --------------------------------------------------

    async def send_job_started(self) -> None:
        await self.sender.send_message(
            pm.MasterJobStartedEvent(trace_id=self.state.trace_id)
        )

    async def finish_job_and_get_trace(self):
        """Request the worker's trace; 600 s budget for huge traces."""
        request = pm.MasterJobFinishedRequest.new()
        response = await request_response(
            self.sender,
            self.router,
            request,
            pm.WorkerJobFinishedResponse,
            timeout=JOB_FINISH_TRACE_TIMEOUT,
        )
        # Keep the piggybacked span timeline (None from a C++ worker) for
        # the merged cluster timeline export.
        self.collected_span_events = response.span_events
        return response.trace

    # -- background loops ----------------------------------------------------

    async def _manage_incoming_events(self) -> None:
        """Apply rendering/finished events to the mirror + global state.

        Reference: master/src/connection/mod.rs:240-326.
        """
        rendering_queue = self.router.subscribe(pm.WorkerFrameQueueItemRenderingEvent)
        finished_queue = self.router.subscribe(pm.WorkerFrameQueueItemFinishedEvent)

        async def handle_rendering() -> None:
            while True:
                event = await rendering_queue.get()
                self.logger.debug("Frame %d started rendering.", event.frame_index)
                self._rendering_started_at[event.frame_index] = time.time()
                self.queue.set_rendering(event.frame_index)
                self.state.mark_frame_as_rendering(event.frame_index, self.worker_id)

        async def handle_finished() -> None:
            while True:
                event = await finished_queue.get()
                received_wall = time.time()
                received_mono = time.perf_counter()
                frame_on_worker = self.queue.remove(event.frame_index)
                self._update_queue_depth_gauge()
                # Terminal span of the assignment's causal chain on the
                # master timeline: the flow arrow from "assign frame"
                # through the worker's phases ends here. Prefer the trace
                # the event echoed (exact even across re-queues); fall back
                # to the mirror's record (a C++ worker echoes nothing).
                # After _mark_dead the eviction already terminated every
                # mirrored flow, so a late in-flight event records its span
                # WITHOUT a second terminal arrowhead.
                trace = event.trace
                if trace is None and frame_on_worker is not None:
                    trace = frame_on_worker.trace
                self._complete_frame_flow(
                    "frame result",
                    event.frame_index,
                    None if self.is_dead else trace,
                    start_wall=received_wall,
                    duration=time.perf_counter() - received_mono,
                    extra_args={"result": event.result},
                )
                if event.result == pm.FRAME_QUEUE_ITEM_FINISHED_OK:
                    self.logger.debug("Frame %d finished.", event.frame_index)
                    started = self._rendering_started_at.pop(event.frame_index, None)
                    if started is None and frame_on_worker is not None:
                        started = frame_on_worker.queued_at
                    if started is not None:
                        self._completion_observations.append(
                            (event.frame_index, max(1e-4, time.time() - started))
                        )
                    self.state.mark_frame_as_finished(event.frame_index)
                else:
                    # Reference workers swallow render errors and the master
                    # hangs (worker/src/rendering/queue.rs:169-174); we
                    # reschedule the frame instead.
                    self.logger.warning(
                        "Frame %d errored on worker (%s); rescheduling.",
                        event.frame_index,
                        event.error_reason,
                    )
                    self.state.return_frame_to_pending(event.frame_index)

        # gather instead of asyncio.TaskGroup so the master still runs on
        # Python 3.10; first failure cancels the sibling loop the same way.
        tasks = [
            asyncio.ensure_future(handle_rendering()),
            asyncio.ensure_future(handle_finished()),
        ]
        try:
            await asyncio.gather(*tasks)
        except asyncio.CancelledError:
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        except Exception as e:  # noqa: BLE001 - loop death is a worker failure
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            await self._mark_dead(f"event loop failed: {e}")

    async def _maintain_heartbeat(self) -> None:
        """Ping every 10 s; a missed pong (60 s) marks the worker dead.

        Reference: master/src/connection/mod.rs:327-423, except failure
        triggers eviction instead of only killing the heartbeat task.
        """
        pong_queue = self.router.subscribe(pm.WorkerHeartbeatResponse)
        try:
            while True:
                # Ping FIRST, then sleep (the reference sleeps first): the
                # immediate first exchange seeds the clock-offset estimator
                # at registration time, so even short jobs get their worker
                # rows rebased in the merged cluster timeline. Safe against
                # drops because the worker subscribes its heartbeat queue
                # before starting its receive loop.
                request = pm.MasterHeartbeatRequest.new_now()
                try:
                    sent_at = time.perf_counter()
                    await self.sender.send_message(request)
                    pong = await self.router.wait_for_message(
                        pm.WorkerHeartbeatResponse,
                        timeout=HEARTBEAT_RESPONSE_TIMEOUT,
                        queue=pong_queue,
                    )
                    pong_wall = time.time()
                    if self.metrics is not None:
                        self.metrics.histogram(
                            "transport_heartbeat_rtt_seconds",
                            "Heartbeat ping->pong round-trip per worker",
                            labels=("worker",),
                        ).observe(
                            time.perf_counter() - sent_at,
                            worker=self._worker_label(),
                        )
                    if pong.received_at is not None and pong.responded_at is not None:
                        self._observe_clock_sample(
                            request.request_time,
                            pong.received_at,
                            pong.responded_at,
                            pong_wall,
                        )
                    if pong.metrics is not None:
                        self.latest_worker_metrics = pong.metrics
                except (asyncio.TimeoutError, ConnectionError, Exception) as e:
                    if isinstance(e, asyncio.CancelledError):
                        raise
                    await self._mark_dead(f"heartbeat failed: {e}")
                    return
                await asyncio.sleep(HEARTBEAT_INTERVAL_SECONDS)
        except asyncio.CancelledError:
            raise
        finally:
            self.router.unsubscribe(pm.WorkerHeartbeatResponse, pong_queue)

    def _observe_clock_sample(
        self, t1: float, t2: float, t3: float, t4: float
    ) -> None:
        """Fold one NTP exchange into the estimator and export the gauges."""
        self.clock_offset.add_ping(t1, t2, t3, t4)
        if self.metrics is None:
            return
        label = self._worker_label()
        self.metrics.gauge(
            "master_worker_clock_offset_seconds",
            "Estimated worker-minus-master wall clock offset "
            "(median of the heartbeat NTP window)",
            labels=("worker",),
        ).set(self.clock_offset.offset(), worker=label)
        self.metrics.gauge(
            "master_worker_clock_drift_ppm",
            "Estimated worker clock drift rate vs the master (ppm)",
            labels=("worker",),
        ).set(self.clock_offset.drift_ppm(), worker=label)
