"""Per-worker façade on the master.

Aggregates the logical (reconnectable) connection, sender, router, queue
mirror, heartbeat task, and incoming-event handling — the asyncio
re-expression of the reference's ``Worker`` struct
(master/src/connection/mod.rs:36-423). Public surface:
``queue_frame`` / ``unqueue_frame`` (RPC + mirror/state sync),
``finish_job_and_get_trace`` (600 s timeout RPC —
master/src/connection/requester.rs:97), and ``maintain_heartbeat``
(10 s ping interval — master/src/connection/mod.rs:36-37).

Improvements over the reference (SURVEY.md §7 "known bugs to fix"):
an errored finished-event returns the frame to the pending pool instead of
hanging the job, and a heartbeat failure triggers worker eviction via the
``on_dead`` callback instead of leaving frames assigned to a ghost.

Exactly-once accounting under faults (driven by the chaos engine): every
incoming rendering/finished event is checked against the frame's CURRENT
assignment. A duplicated delivery, a late result from an evicted worker
whose frame was re-rendered elsewhere, or an errored result for a frame
this worker no longer owns are all recorded
(``master_duplicate_results_total`` / ``master_late_results_total`` /
``master_stale_results_total``) instead of corrupting the frame table —
the ledger invariant ``ok_results - duplicates == frames_total`` is what
``chaos/invariants.py`` asserts after every fault run. Master→worker RPCs
additionally carry send-side + ack deadlines so one wedged socket can
never stall the assignment loop for every other worker.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.jobs.tiles import WorkUnit
from tpu_render_cluster.master.queue_mirror import FrameOnWorker, WorkerQueueMirror
from tpu_render_cluster.master.state import ClusterManagerState, FrameStatus
from tpu_render_cluster.obs import ClockOffsetEstimator, MetricsRegistry, Tracer
from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.protocol.frames import DispatchFrameCache, frames_cached
from tpu_render_cluster.transport.actors import (
    DEFAULT_WAIT_TIMEOUT,
    MessageRouter,
    SenderHandle,
    request_response,
)
from tpu_render_cluster.transport.reconnect import ReconnectableServerConnection
from tpu_render_cluster.transport.wirecost import WireAccounting
from tpu_render_cluster.utils.env import env_float, env_int
from tpu_render_cluster.utils.logging import WorkerLogger

HEARTBEAT_INTERVAL_SECONDS = 10.0  # reference: master/src/connection/mod.rs:36
HEARTBEAT_RESPONSE_TIMEOUT = 60.0  # reference: master/src/connection/receiver.rs:27
JOB_FINISH_TRACE_TIMEOUT = 600.0  # reference: master/src/connection/requester.rs:97


def send_deadline_seconds() -> float:
    """Write-side deadline on master→worker sends (``TRC_SEND_DEADLINE_SECONDS``).

    Must exceed ``ReconnectableServerConnection.MAX_WAIT_FOR_RECONNECT``
    (30 s) or ordinary reconnect windows would be misread as wedges."""
    return env_float("TRC_SEND_DEADLINE_SECONDS", 45.0)


def rpc_deadline_seconds() -> float:
    """Ack deadline on queue add/remove RPCs (``TRC_RPC_DEADLINE_SECONDS``)."""
    return env_float("TRC_RPC_DEADLINE_SECONDS", DEFAULT_WAIT_TIMEOUT)


def unit_error_limit() -> int:
    """Errored results per unit before the job fails
    (``TRC_MAX_UNIT_ERRORS``). Transient render errors requeue and
    succeed elsewhere well inside this budget; a unit that keeps
    erroring deterministically (e.g. a tiled unit on a backend that
    cannot render sub-frame regions, cluster-wide) must fail the job
    loudly instead of redispatching in a hot loop forever."""
    return env_int("TRC_MAX_UNIT_ERRORS", 8)


def heartbeat_pong_retries() -> int:
    """Extra pings after a missed pong before eviction
    (``TRC_HEARTBEAT_PONG_RETRIES``). A pong can be lost to a transient
    partition that heals within the response window; one retry
    distinguishes that from a dead worker. Send *failures* still evict
    immediately — they mean the socket is gone and the reconnect window
    already expired."""
    return env_int("TRC_HEARTBEAT_PONG_RETRIES", 1)


class WorkerHandle:
    """One connected worker, as seen by the master."""

    # Class-level defaults so partially-constructed handles (tests build
    # them attribute-by-attribute) behave like epoch-less production ones.
    epoch: int | None = None
    _shutdown_started = False
    _on_protocol_event = None

    def __init__(
        self,
        worker_id: int,
        connection: ReconnectableServerConnection,
        state: ClusterManagerState | None,
        *,
        on_dead: Callable[["WorkerHandle", str], Awaitable[None]] | None = None,
        metrics: MetricsRegistry | None = None,
        span_tracer: Tracer | None = None,
        dispatch_delay_fn: Callable[[int], float] | None = None,
        state_resolver: Callable[[str | None], ClusterManagerState | None]
        | None = None,
        on_frame_complete: Callable[[ClusterManagerState, int], None]
        | None = None,
        on_unit_latency: Callable[[ClusterManagerState, WorkUnit, float], None]
        | None = None,
        on_protocol_event: Callable[[str, dict], None] | None = None,
        epoch: int | None = None,
    ) -> None:
        self.worker_id = worker_id
        self.connection = connection
        # Master incarnation epoch (ha/ledger.py; None without a ledger):
        # stamped on every queue-add and checked against the epoch echoed
        # by incoming frame events — an event fenced to a PREVIOUS
        # incarnation is counted and refused, never applied.
        self.epoch = epoch
        # Single-job masters pass the one state; the multi-job scheduler
        # passes ``state=None`` plus a resolver mapping the ``job_name``
        # every worker event carries to the owning job's state (None for
        # a job that is no longer active — cancelled or finished — whose
        # late events are then accounted as stale instead of applied).
        self.state = state
        self._state_resolver = state_resolver
        self.queue = WorkerQueueMirror()
        self.frames_stolen_count = 0
        self.is_dead = False
        # True when is_dead was reached via the graceful goodbye path
        # (counted as a drain, not an eviction).
        self.drained = False
        # Set by shutdown(): failures observed past this point are our
        # own teardown, not worker death (no eviction accounting).
        self._shutdown_started = False
        # Chaos shim: seconds to stall before dispatching a given frame's
        # queue-add RPC (no-op when None — the production default).
        self._dispatch_delay_fn = dispatch_delay_fn
        self.metrics = metrics
        self.span_tracer = span_tracer
        # Wire-cost accounting around the codec (transport/wirecost.py):
        # per-tag byte counters + serialize-time histograms on this end
        # of the socket (passthrough when no registry is wired).
        self._wire = WireAccounting(metrics)
        # Preserialized queue-add codec (protocol/frames.py): the job
        # segment is encoded once per (job generation, epoch) and spliced
        # into each dispatch frame.
        self._frames = DispatchFrameCache()
        # Most recent compact metrics payload this worker piggybacked on a
        # heartbeat pong (None until the first instrumented pong arrives).
        self.latest_worker_metrics: dict | None = None
        # NTP-style clock-offset estimate (worker clock - master clock),
        # fed by the heartbeat's four timestamps; the merged cluster
        # timeline rebases this worker's span events by it.
        self.clock_offset = ClockOffsetEstimator()
        # Chrome trace events the worker piggybacked on its job-finished
        # response ({"process_name", "events"}), for the cluster timeline.
        self.collected_span_events: dict | None = None
        # Fires when an ok result completes a whole FRAME (every tile
        # landed): the master's assembly hook. Sync by contract — the
        # implementation schedules its own task so event handling never
        # blocks on image stitching.
        self._on_frame_complete = on_frame_complete
        # Fires with each unit's winning-result dispatch-to-result latency
        # (the master_unit_latency_seconds stream) — the SLO engine's feed.
        self._on_unit_latency = on_unit_latency
        # Flight-recorder digest feed (obs/flightrec.py): compact
        # protocol-event summaries (dispatches, accepted results, fence
        # refusals, death) — cheap enough for the hottest event paths.
        self._on_protocol_event = on_protocol_event
        # Observed per-unit render durations (for scheduler cost models),
        # keyed (job_name, unit) — frame indices alias across jobs.
        self._rendering_started_at: dict[tuple[str, WorkUnit], float] = {}
        self._completion_observations: list[tuple[str, WorkUnit, float]] = []
        self._on_dead = on_dead
        self.logger = WorkerLogger(
            logging.getLogger("master.worker"),
            pm.worker_id_to_string(worker_id),
            connection.last_known_address,
        )

        self.sender = SenderHandle(self._send_message)
        self.router = MessageRouter(self._receive_message)
        self._heartbeat_task: asyncio.Task | None = None
        self._events_task: asyncio.Task | None = None
        self._tasks_started = False

    # -- transport adapters -------------------------------------------------

    async def _send_message(self, message: pm.Message) -> None:
        serialize_started = time.perf_counter()
        if (
            isinstance(message, pm.MasterFrameQueueAddRequest)
            and frames_cached()
        ):
            # Preserialized dispatch path: the job segment comes from the
            # per-generation cache and only the varying keys are spliced;
            # the wire accounting observes the already-encoded text (one
            # serialize per message end-to-end, never a re-encode to
            # measure). Byte-identical to encode_message by contract.
            text = self._frames.encode(message)
            self._wire.record_send(
                message.type_name,
                text,
                time.perf_counter() - serialize_started,
            )
        else:
            text = self._wire.encode(message)
        if isinstance(message, pm.MasterFrameQueueAddRequest):
            # The per-dispatch JSON cost ROADMAP item 3 wanted
            # preserialized, attributed as a tick phase (both paths, so
            # the A/B reads off one metric). Import is lazy:
            # sched/__init__ imports the manager which imports this
            # module, so a top-level sched import here would be circular.
            from tpu_render_cluster.sched.tickprof import observe_dispatch_phase

            observe_dispatch_phase(
                self.metrics,
                "dispatch_serialize",
                time.perf_counter() - serialize_started,
            )
        # Send-side deadline: a socket that accepts writes but never
        # drains (or a reconnect window that never closes) must surface as
        # a failure here instead of parking the sender actor — and with it
        # every RPC on this worker — forever.
        await asyncio.wait_for(
            self.connection.send_text(text),
            send_deadline_seconds(),
        )

    async def _receive_message(self) -> pm.Message:
        return self._wire.decode(await self.connection.receive_text())

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Spawn sender/receiver/heartbeat/event tasks."""
        assert not self._tasks_started
        self._tasks_started = True
        self.sender.start()
        self.router.start()
        self._events_task = asyncio.create_task(
            self._manage_incoming_events(), name=f"events-{self.worker_id:08x}"
        )
        self._heartbeat_task = asyncio.create_task(
            self._maintain_heartbeat(), name=f"heartbeat-{self.worker_id:08x}"
        )

    def cancel_heartbeat(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()

    async def shutdown(self) -> None:
        # An in-flight heartbeat send racing this teardown fails with
        # "sender closed" — that is US closing, not the worker dying, and
        # must not count an eviction (or requeue frames) on the way out.
        self._shutdown_started = True
        self.cancel_heartbeat()
        if self._events_task is not None:
            self._events_task.cancel()
        await self.router.stop()
        await self.sender.stop()
        self.connection.close()

    async def _mark_dead(self, reason: str) -> None:
        if self.is_dead or self._shutdown_started:
            return
        self.is_dead = True
        self.logger.warning("Worker marked dead: %s", reason)
        if self._on_protocol_event is not None:
            self._on_protocol_event(
                "worker_dead",
                {"worker": self._worker_label(), "reason": reason},
            )
        # Terminate the Perfetto flows of every assignment still mirrored
        # here: the requeued frames open fresh chains elsewhere, and a
        # dangling flow-start would fail the trace validator on artifacts
        # from any run that lost a worker.
        now = time.time()
        for frame in self.queue.all_frames():
            self._complete_frame_flow(
                "frame evicted",
                frame.unit,
                frame.trace,
                start_wall=now,
                duration=0.0,
                extra_args={"reason": reason},
            )
        if self.metrics is not None:
            self.metrics.counter(
                "master_worker_evictions_total", "Workers marked dead and evicted"
            ).inc()
            # Zero (don't leave stale) this worker's depth: its frames are
            # returned to pending and re-queue elsewhere, and a frozen
            # nonzero series would double-count them in the live view.
            self.metrics.gauge(
                "master_worker_queue_depth",
                "Frames currently mirrored on each worker's queue",
                labels=("worker",),
            ).set(0, worker=self._worker_label())
        if self._on_dead is not None:
            await self._on_dead(self, reason)

    # -- state routing --------------------------------------------------------

    def _state_for(self, job_name: str | None) -> ClusterManagerState | None:
        """The frame table owning ``job_name``'s frames (see __init__)."""
        if self._state_resolver is not None:
            return self._state_resolver(job_name)
        return self.state

    @staticmethod
    def _job_generation_mismatch(
        state: ClusterManagerState | None, event_job_id: str | None
    ) -> bool:
        """True when an event is stamped with a DIFFERENT submission's
        job_id than the active job of the same name — i.e. the name was
        reused after a cancel/finish and this event belongs to the old
        generation. Anonymous events (C++ workers echo no job_id) always
        match."""
        return (
            state is not None
            and event_job_id is not None
            and state.sched_job_id is not None
            and event_job_id != state.sched_job_id
        )

    # -- observability helpers ----------------------------------------------

    def _worker_label(self) -> str:
        return pm.worker_id_to_string(self.worker_id)

    def _update_queue_depth_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "master_worker_queue_depth",
                "Frames currently mirrored on each worker's queue",
                labels=("worker",),
            ).set(len(self.queue), worker=self._worker_label())

    def _complete_frame_flow(
        self,
        name: str,
        unit: WorkUnit,
        trace: pm.TraceContext | None,
        *,
        start_wall: float,
        duration: float,
        extra_args: dict | None = None,
    ) -> None:
        """Master-side terminal span for one assignment chain (result
        received / frame stolen), with the flow arrowhead bound inside it
        when the assignment's trace context is known."""
        if self.span_tracer is None:
            return
        args = {"frame": unit.frame_index, **(extra_args or {})}
        if unit.tile is not None:
            args["tile"] = unit.tile
        track = f"worker-{self._worker_label()}"
        if trace is not None:
            args["flow"] = trace.flow_id
        self.span_tracer.complete(
            name,
            cat="master",
            start_wall=start_wall,
            duration=duration,
            track=track,
            args=args,
        )
        if trace is not None:
            flow_args = {"frame": unit.frame_index}
            if unit.tile is not None:
                flow_args["tile"] = unit.tile
            self.span_tracer.flow_end(
                "frame",
                id=trace.flow_id,
                ts=start_wall + duration / 2.0,
                cat="frame",
                track=track,
                args=flow_args,
            )

    # -- scheduling RPCs ----------------------------------------------------

    async def queue_frame(
        self,
        job: BlenderJob,
        unit: WorkUnit | int,
        *,
        stolen_from: int | None = None,
        job_id: str | None = None,
        speculative: bool = False,
    ) -> None:
        """RPC a work unit onto this worker's queue; sync mirror + state.

        Reference: master/src/connection/mod.rs:139-168. ``job_id`` is the
        multi-job scheduler's submission id, piggybacked on the wire and
        echoed by (Python) workers; single-job dispatch leaves it None.
        ``unit.tile`` rides the same optional-key idiom — whole-frame
        dispatch encodes byte-identically to before (a bare int is
        accepted as a whole-frame unit for legacy callers/tests).

        ``speculative=True`` dispatches a duplicate TWIN of a unit whose
        live assignment stays on its PRIMARY worker: the wire message is
        byte-identical to any other dispatch (workers cannot tell), the
        mirror gains a normal entry here, but the frame record is NOT
        re-pointed — the primary still owns it, so the first accepted ok
        result wins through the existing dedup seam exactly as a
        late-result race would (master/speculate.py resolves the loser).
        """
        if isinstance(unit, int):
            unit = WorkUnit(unit)
        frame_index = unit.frame_index
        if self.is_dead:
            raise RuntimeError("Worker is dead; refusing dispatch.")
        state = self._state_for(job.job_name)
        if state is None:
            # The dispatch raced a cancel: the job is gone, nothing to queue.
            raise RuntimeError(
                f"Job {job.job_name!r} is no longer active; refusing dispatch."
            )
        if self._dispatch_delay_fn is not None:
            delay = self._dispatch_delay_fn(frame_index)
            if delay > 0.0:
                await asyncio.sleep(delay)
        # Fresh span per ASSIGNMENT (not per frame): a re-queued or stolen
        # frame starts a new causal chain with its own Perfetto flow.
        trace = pm.TraceContext.new(state.trace_id)
        request = pm.MasterFrameQueueAddRequest.new(
            job, frame_index, trace=trace, job_id=job_id, tile=unit.tile,
            epoch=self.epoch,
        )
        rpc_started = time.perf_counter()
        rpc_started_wall = time.time()
        response = await request_response(
            self.sender,
            self.router,
            request,
            pm.WorkerFrameQueueAddResponse,
            timeout=rpc_deadline_seconds(),
        )
        if response.result != pm.FRAME_QUEUE_ADD_RESULT_ADDED:
            raise RuntimeError(
                f"Worker rejected frame {frame_index}: {response.error_reason}"
            )
        # The ack can arrive AFTER this worker was evicted (or after the
        # frame finished elsewhere): the eviction already requeued the
        # frame and swept the mirror, so completing the assignment here
        # would stomp the live record and open a Perfetto flow nothing
        # ever closes. The worker may still render its ghost copy; the
        # finished-event dedup path absorbs that result. A job cancelled
        # mid-RPC counts as superseded too — compared by state IDENTITY,
        # so a same-named job resubmitted during the RPC window cannot
        # adopt (and then wedge on) the old submission's dispatch.
        if self._state_for(job.job_name) is not state:
            raise RuntimeError(
                f"Assignment of unit {unit.label} was superseded "
                f"mid-dispatch (job {job.job_name!r} was cancelled/replaced)."
            )
        record = state.frames.get(unit)
        if (
            self.is_dead
            or record is None
            or record.status is FrameStatus.FINISHED
        ):
            raise RuntimeError(
                f"Assignment of unit {unit.label} was superseded "
                f"mid-dispatch ({'worker died' if self.is_dead else 'frame finished or job gone'})."
            )
        rpc_seconds = time.perf_counter() - rpc_started
        if self.metrics is not None:
            strategy = state.job.frame_distribution_strategy.strategy_type
            self.metrics.histogram(
                "master_assignment_latency_seconds",
                "queue-add RPC round-trip (request sent to ack received)",
                labels=("strategy",),
            ).observe(rpc_seconds, strategy=strategy)
            # Attribution phase: dispatch send->ack (lazy import, see
            # _send_message for the sched<->master cycle note).
            from tpu_render_cluster.sched.tickprof import observe_dispatch_phase

            observe_dispatch_phase(self.metrics, "dispatch_rpc_await", rpc_seconds)
        if self.span_tracer is not None:
            # Constant span name (frame index in args) so viewers and the
            # analysis roll-up aggregate all assignments into one stat.
            args = {"frame": frame_index, "flow": trace.flow_id}
            if unit.tile is not None:
                args["tile"] = unit.tile
            if stolen_from is not None:
                args["stolen_from"] = stolen_from
            track = f"worker-{self._worker_label()}"
            self.span_tracer.complete(
                "assign frame",
                cat="master",
                start_wall=rpc_started_wall,
                duration=rpc_seconds,
                track=track,
                args=args,
            )
            # Flow source, mid-span so it binds inside the assign slice;
            # the worker's queue_wait/read/render/write spans route it and
            # the result-received span terminates it.
            flow_args = {"frame": frame_index}
            if unit.tile is not None:
                flow_args["tile"] = unit.tile
            self.span_tracer.flow_start(
                "frame",
                id=trace.flow_id,
                ts=rpc_started_wall + rpc_seconds / 2.0,
                cat="frame",
                track=track,
                args=flow_args,
            )
        now = time.time()
        self.queue.add(
            FrameOnWorker(
                frame_index,
                queued_at=now,
                stolen_from=stolen_from,
                trace=trace,
                job_name=job.job_name,
                job_id=job_id,
                tile=unit.tile,
            )
        )
        self._update_queue_depth_gauge()
        if self._on_protocol_event is not None:
            self._on_protocol_event(
                "dispatch",
                {
                    "worker": self._worker_label(),
                    "job": job.job_name,
                    "unit": unit.label,
                    "speculative": speculative,
                    "stolen_from": stolen_from,
                },
            )
        if not speculative:
            state.mark_frame_as_queued(
                unit,
                self.worker_id,
                now,
                stolen_from=stolen_from,
                stolen_at=now if stolen_from is not None else None,
            )

    async def unqueue_frame(self, job_name: str, unit: WorkUnit | int) -> str:
        """RPC-remove a work unit (the steal primitive); returns the result
        enum.

        Tolerates the remove-vs-render races (``already-rendering`` /
        ``already-finished`` — reference: strategies.rs:347-373 leaves those
        to the caller).
        """
        if isinstance(unit, int):
            unit = WorkUnit(unit)
        request = pm.MasterFrameQueueRemoveRequest.new(
            job_name, unit.frame_index, tile=unit.tile
        )
        rpc_started_wall = time.time()
        rpc_started = time.perf_counter()
        response = await request_response(
            self.sender,
            self.router,
            request,
            pm.WorkerFrameQueueRemoveResponse,
            timeout=rpc_deadline_seconds(),
        )
        if response.result == pm.FRAME_QUEUE_REMOVE_RESULT_REMOVED:
            removed = self.queue.remove(unit.frame_index, job_name, unit.tile)
            self._update_queue_depth_gauge()
            # A successful steal ends this assignment's causal chain (the
            # thief's queue_frame opens a fresh one) — terminate the flow
            # here so no dangling flow-start survives a stolen frame.
            if self.span_tracer is not None:
                self._complete_frame_flow(
                    "frame stolen",
                    unit,
                    removed.trace if removed is not None else None,
                    start_wall=rpc_started_wall,
                    duration=time.perf_counter() - rpc_started,
                    extra_args={"result": response.result},
                )
        return response.result

    def has_empty_queue(self) -> bool:
        return len(self.queue) == 0

    def sweep_finished_units(self, state_for) -> int:
        """Drop mirror entries whose unit already FINISHED, closing their
        Perfetto flows. These are ghost copies left by accepted LATE
        results: the evicted original's result finished the unit while
        the re-dispatched twin still sat queued here — if the job ends
        before the twin renders, nothing else would ever pop the entry
        (or terminate its flow), and the mirror would keep offering a
        finished unit to steal passes. Called at job finalization; racing
        events for swept entries are absorbed by the dedup seam as usual.
        """
        removed = 0
        now = time.time()
        for frame in self.queue.all_frames():
            state = state_for(frame.job_name)
            if state is None:
                continue
            record = state.frames.get(frame.unit)
            if record is not None and record.status is FrameStatus.FINISHED:
                self.queue.remove(frame.frame_index, frame.job_name, frame.tile)
                self._complete_frame_flow(
                    "frame superseded",
                    frame.unit,
                    frame.trace,
                    start_wall=now,
                    duration=0.0,
                    extra_args={"reason": "finished elsewhere"},
                )
                removed += 1
        if removed:
            self._update_queue_depth_gauge()
        return removed

    def drain_completion_observations(
        self,
    ) -> list[tuple[str, WorkUnit, float]]:
        """Take (job_name, unit, seconds) samples observed since the last
        call (consumed by the shared CostModelService — exactly once no
        matter which scheduler loop ticks first)."""
        observations, self._completion_observations = self._completion_observations, []
        return observations

    # -- job lifecycle RPCs --------------------------------------------------

    async def send_job_started(
        self, *, trace_id: int | None = None, job_id: str | None = None
    ) -> None:
        """Announce a job start. Single-job callers pass nothing (the one
        state's trace id is used); the multi-job scheduler passes each
        admitted job's (trace_id, job_id) — including replays to late
        joiners, one event per active job."""
        if trace_id is None and self.state is not None:
            trace_id = self.state.trace_id
        await self.sender.send_message(
            pm.MasterJobStartedEvent(trace_id=trace_id, job_id=job_id)
        )

    async def send_migrate(
        self, host: str, port: int, *, reason: str | None = None
    ) -> None:
        """Ask this worker to re-home to another shard master: it drains
        gracefully (goodbye reason ``"migrate"``, queued frames returned
        and requeued here) and reconnects there with a fresh announce.
        Fire-and-forget like the drain protocol — a reference worker
        ignores the unknown tag and stays."""
        await self.sender.send_message(
            pm.MasterWorkerMigrateEvent(host=host, port=port, reason=reason)
        )

    async def finish_job_and_get_trace(self):
        """Request the worker's trace; 600 s budget for huge traces."""
        request = pm.MasterJobFinishedRequest.new()
        response = await request_response(
            self.sender,
            self.router,
            request,
            pm.WorkerJobFinishedResponse,
            timeout=JOB_FINISH_TRACE_TIMEOUT,
        )
        # Keep the piggybacked span timeline (None from a C++ worker) for
        # the merged cluster timeline export.
        self.collected_span_events = response.span_events
        return response.trace

    # -- background loops ----------------------------------------------------

    def _count_anomaly(
        self,
        name: str,
        help_text: str,
        *,
        state: ClusterManagerState | None = None,
        ledger_key: str | None = None,
    ) -> None:
        if self.metrics is not None:
            self.metrics.counter(name, help_text).inc()
        if state is not None and ledger_key is not None:
            state.ledger[ledger_key] += 1

    def _refuse_stale_epoch(
        self, event: "pm.WorkerFrameQueueItemRenderingEvent | pm.WorkerFrameQueueItemFinishedEvent", kind: str
    ) -> bool:
        """True when the event is fenced out: it echoes an epoch that is
        not this master incarnation's. The result/render DID happen under
        a predecessor, but this master holds no assignment context for it
        (the worker re-announced fresh and its old session's queue state
        was dropped), so applying it would corrupt the frame table; the
        ledger-replayed finished set plus re-dispatch of the remainder is
        the recovery path. Counted in the metrics AND the owning job's
        in-memory ledger, exactly like the other dedup-seam refusals.
        This runs on the master's hottest path (every worker event), so
        everything beyond the three comparisons — including the log
        label — is built only on the rare refusal."""
        if (
            self.epoch is None
            or event.epoch is None
            or event.epoch == self.epoch
        ):
            return False
        if self.metrics is not None:
            self.metrics.counter(
                "master_stale_epoch_events_total",
                "Worker frame events refused because they echo a previous "
                "master incarnation's epoch",
            ).inc()
        state = self._state_for(event.job_name)
        if state is not None:
            state.ledger["stale_epoch_results"] += 1
        if self._on_protocol_event is not None:
            self._on_protocol_event(
                "stale_epoch_refusal",
                {
                    "worker": self._worker_label(),
                    "job": event.job_name,
                    "unit": WorkUnit(event.frame_index, event.tile).label,
                    "event": kind,
                    "epoch": event.epoch,
                    "current_epoch": self.epoch,
                },
            )
        self.logger.warning(
            "Refused %s event for unit %s with stale epoch %d "
            "(current epoch %d).",
            kind,
            WorkUnit(event.frame_index, event.tile).label,
            event.epoch,
            self.epoch,
        )
        return True

    def _is_current_assignment(self, record) -> bool:
        """Does this worker own the frame's LIVE assignment right now?

        False for events from the past: the worker was evicted (record
        re-pointed by requeue), the frame was stolen, or it already
        finished. Events failing this check are accounted, not applied —
        the exactly-once seam.
        """
        return (
            not self.is_dead
            and record is not None
            and record.status
            in (FrameStatus.QUEUED_ON_WORKER, FrameStatus.RENDERING_ON_WORKER)
            and record.worker_id == self.worker_id
        )

    def _mirror_entry_for_event(
        self, unit: WorkUnit, job_name: str, event_job_id: str | None
    ):
        """The mirror entry an incoming event may touch, or None.

        Generation guard: after a cancel + same-name resubmit, the mirror
        key (job_name, frame_index, tile) can be occupied by the NEW
        submission's dispatch while a late event from the OLD one is
        still in flight — only an entry whose job_id matches (or where
        either side is anonymous) belongs to this event.
        """
        entry = self.queue.get(unit.frame_index, job_name, unit.tile)
        if (
            entry is not None
            and entry.job_id is not None
            and event_job_id is not None
            and entry.job_id != event_job_id
        ):
            return None
        return entry

    def _apply_rendering_event(
        self, event: pm.WorkerFrameQueueItemRenderingEvent
    ) -> None:
        if self._refuse_stale_epoch(event, "rendering"):
            return
        unit = WorkUnit(event.frame_index, event.tile)
        state = self._state_for(event.job_name)
        # Keep the mirror honest even for a defunct job: a unit that
        # started rendering must stop looking like a steal candidate —
        # but never touch a same-keyed entry of a NEWER generation.
        if (
            self._mirror_entry_for_event(unit, event.job_name, event.job_id)
            is not None
        ):
            self.queue.set_rendering(unit.frame_index, event.job_name, unit.tile)
        if self._job_generation_mismatch(state, event.job_id):
            state = None
        record = state.frames.get(unit) if state is not None else None
        speculation = (
            state.speculations.get(unit) if state is not None else None
        )
        if (
            speculation is not None
            and self.worker_id == speculation.twin_worker_id
        ):
            # A speculative twin starting to render is BY DESIGN, not an
            # anomaly: record its render-start clock on this handle (the
            # cost observation measures render time if the twin wins) but
            # leave the frame record pointed at the primary — the dedup
            # seam arbitrates the race by first result, not by state.
            self.logger.debug(
                "Speculative twin of unit %s started rendering.", unit.label
            )
            self._rendering_started_at[(event.job_name, unit)] = time.time()
            return
        if state is None or not self._is_current_assignment(record):
            # E.g. the queue-add ack timed out (frame requeued elsewhere)
            # but the add had landed, and the superseded copy now renders;
            # or the job was cancelled while the frame sat on the worker.
            self._count_anomaly(
                "master_stale_results_total",
                "Worker events ignored because the frame's live assignment "
                "moved on (eviction, steal, requeue, cancel, or already "
                "finished)",
                state=state,
                ledger_key="stale_results",
            )
            self.logger.debug(
                "Stale rendering event for unit %s ignored.", unit.label
            )
            return
        self.logger.debug("Unit %s started rendering.", unit.label)
        self._rendering_started_at[(event.job_name, unit)] = time.time()
        state.mark_frame_as_rendering(unit, self.worker_id)

    def _apply_finished_event(
        self, event: pm.WorkerFrameQueueItemFinishedEvent
    ) -> None:
        # Fencing runs before ANY accounting or mirror mutation: a
        # stale-epoch result must not touch the ok/duplicate counters (the
        # exactly-once equation is per incarnation) and must not close a
        # flow this incarnation never opened.
        if self._refuse_stale_epoch(event, "finished"):
            return
        received_wall = time.time()
        received_mono = time.perf_counter()
        unit = WorkUnit(event.frame_index, event.tile)
        state = self._state_for(event.job_name)
        if self._job_generation_mismatch(state, event.job_id):
            state = None
        record = state.frames.get(unit) if state is not None else None
        # Popped unconditionally — the duplicate/late/stale returns below
        # must not leave a ghost in-flight entry on this handle — EXCEPT
        # when the same-keyed entry belongs to a newer generation of a
        # reused job name: that entry is another submission's live
        # assignment, not this event's.
        frame_on_worker = None
        if (
            self._mirror_entry_for_event(unit, event.job_name, event.job_id)
            is not None
        ):
            frame_on_worker = self.queue.remove(
                unit.frame_index, event.job_name, unit.tile
            )
        started = self._rendering_started_at.pop((event.job_name, unit), None)
        self._update_queue_depth_gauge()
        if self.metrics is not None:
            self.metrics.counter(
                "master_frame_results_total",
                "Frame finished events received from workers, by wire result",
                labels=("result",),
            ).inc(result=event.result)
        if state is None:
            # The job is gone (cancelled, or a stale generation of a
            # reused name): account the event, close the assignment's
            # Perfetto flow IF this handle still held it open (an earlier
            # unqueue/evict already terminated it otherwise), apply
            # nothing. This is how a cancelled job's mid-render frames
            # release their workers with no ghost assignments.
            self._count_anomaly(
                "master_stale_results_total",
                "Worker events ignored because the frame's live assignment "
                "moved on (eviction, steal, requeue, cancel, or already "
                "finished)",
            )
            self._complete_frame_flow(
                "frame result",
                unit,
                frame_on_worker.trace if frame_on_worker is not None else None,
                start_wall=received_wall,
                duration=time.perf_counter() - received_mono,
                extra_args={"result": event.result, "job_gone": True},
            )
            self.logger.debug(
                "Result for unit %s of defunct job %r ignored.",
                unit.label,
                event.job_name,
            )
            return
        finished_already = record is None or record.status is FrameStatus.FINISHED
        current = self._is_current_assignment(record)
        # Terminal span of the assignment's causal chain on the master
        # timeline: the flow arrow from "assign frame" through the
        # worker's phases ends here. Prefer the trace the event echoed
        # (exact even across re-queues); fall back to the mirror's record
        # (a C++ worker echoes nothing). The arrowhead belongs to the
        # event that POPPED the mirror entry: a still-mirrored assignment
        # is a still-open chain (eviction, steals, drains, and sweeps all
        # close the flow exactly when they remove the entry), so a late
        # WINNING result — e.g. a speculative twin beating its straggling
        # primary — terminates its own chain, while a result whose entry
        # was already swept must not double-terminate it.
        trace = event.trace
        if trace is None and frame_on_worker is not None:
            trace = frame_on_worker.trace
        self._complete_frame_flow(
            "frame result",
            unit,
            trace if frame_on_worker is not None else None,
            start_wall=received_wall,
            duration=time.perf_counter() - received_mono,
            extra_args={"result": event.result},
        )
        if event.result == pm.FRAME_QUEUE_ITEM_FINISHED_OK:
            state.ledger["ok_results"] += 1
            if finished_already:
                # The duplicate-result race: a duplicated delivery, or the
                # re-render of an evicted frame lost to the original's late
                # result (or vice versa). ``mark_frame_as_finished``'s
                # idempotence keeps ``_finished_count`` exact; this ledger
                # proves the collision happened.
                self._count_anomaly(
                    "master_duplicate_results_total",
                    "Ok results received for frames that were already finished",
                    state=state,
                    ledger_key="duplicate_results",
                )
                self.logger.warning(
                    "Duplicate result for unit %s ignored.", unit.label
                )
                return
            if not current:
                # Late result from a superseded assignment (this worker was
                # evicted / the frame requeued after a timed-out add RPC):
                # the render DID happen and the output exists — accept it.
                # The currently-assigned copy will account as a duplicate.
                self._count_anomaly(
                    "master_late_results_total",
                    "Ok results accepted from superseded assignments",
                    state=state,
                    ledger_key="late_results",
                )
                self.logger.warning(
                    "Late result for unit %s accepted from a superseded "
                    "assignment.",
                    unit.label,
                )
                # The late result IS the unit's winning (first) result —
                # a speculative twin racing a straggling primary lands
                # here by design — so it carries the latency and cost
                # observation the schedulers learn from.
                self._record_winning_result(
                    state, event.job_name, unit, started, frame_on_worker
                )
                self._finish_unit(state, unit)
                return
            self.logger.debug("Unit %s finished.", unit.label)
            self._record_winning_result(
                state, event.job_name, unit, started, frame_on_worker
            )
            self._finish_unit(state, unit)
        else:
            state.ledger["errored_results"] += 1
            if not current:
                # An errored result for a unit this worker no longer owns
                # must NOT requeue it: the live assignment is
                # authoritative, and a second pending entry would render
                # the unit twice.
                self._count_anomaly(
                    "master_stale_results_total",
                    "Worker events ignored because the frame's live assignment "
                    "moved on (eviction, steal, requeue, cancel, or already "
                    "finished)",
                    state=state,
                    ledger_key="stale_results",
                )
                self.logger.warning(
                    "Stale errored result for unit %s ignored.",
                    unit.label,
                )
                return
            # Reference workers swallow render errors and the master
            # hangs (worker/src/rendering/queue.rs:169-174); we
            # reschedule the unit instead — up to the error budget, past
            # which the failure is evidently deterministic and the job
            # fails rather than livelocking on redispatch.
            record.errored_count += 1
            if record.errored_count >= unit_error_limit():
                state.failed_reason = (
                    f"unit {unit.label} errored {record.errored_count} "
                    f"times (last: {event.error_reason}); giving up"
                )
                self.logger.error("Job failed: %s", state.failed_reason)
                return
            self.logger.warning(
                "Unit %s errored on worker (%s); rescheduling "
                "(attempt %d/%d).",
                unit.label,
                event.error_reason,
                record.errored_count,
                unit_error_limit(),
            )
            state.return_frame_to_pending(unit)

    def _record_winning_result(
        self,
        state: ClusterManagerState,
        job_name: str,
        unit: WorkUnit,
        started: float | None,
        frame_on_worker,
    ) -> None:
        """Account the unit's FIRST accepted ok result: the cost-model
        observation, the exact per-unit latency log, and its histogram.
        Duplicate copies (the speculation loser, a re-delivered send)
        never reach here — they return through the dedup branches.

        Two different clocks on purpose: the COST observation measures
        processing time (render start when the rendering event was seen)
        — what the predictors model — while the LATENCY log measures
        dispatch-to-result (queue-add to result received) — what a unit
        actually waited, the tail the speculation bench is judged on. The
        latency clock starts at the unit's EARLIEST live dispatch, not
        the winning copy's: a hedged unit that waited on a straggler
        before its twin was even launched must carry that wait, or the
        speculation A/B would compare incommensurable clocks."""
        now = time.time()
        queued_at = (
            frame_on_worker.queued_at if frame_on_worker is not None else None
        )
        processing_from = started if started is not None else queued_at
        if processing_from is None:
            return  # mirror already swept and no rendering event seen
        self._completion_observations.append(
            (job_name, unit, max(1e-4, now - processing_from))
        )
        record = state.frames.get(unit)
        dispatch_times = [
            t
            for t in (
                queued_at,
                record.queued_at if record is not None else None,
            )
            if t is not None
        ]
        latency_from = min(dispatch_times) if dispatch_times else processing_from
        latency = max(1e-4, now - latency_from)
        state.unit_seconds.append(latency)
        if self._on_protocol_event is not None:
            self._on_protocol_event(
                "unit_finished",
                {
                    "worker": self._worker_label(),
                    "job": job_name,
                    "unit": unit.label,
                    "latency_seconds": round(latency, 6),
                },
            )
        if self.metrics is not None:
            self.metrics.histogram(
                "master_unit_latency_seconds",
                "Dispatch-to-result latency of each unit's winning "
                "assignment (queue-add to result received)",
            ).observe(latency)
        if self._on_unit_latency is not None:
            self._on_unit_latency(state, unit, latency)

    def _finish_unit(self, state: ClusterManagerState, unit: WorkUnit) -> None:
        """Mark a unit finished; when it completes its whole frame, fire
        the master's frame-complete hook (assembly of tiled frames). The
        transition returns True exactly once per frame, so a duplicate or
        late copy of the final tile can never assemble a frame twice.
        Also stamps a live speculation's winner — the speculation loop
        resolves the loser off this mark."""
        speculation = state.speculations.get(unit)
        if speculation is not None and speculation.winner_worker_id is None:
            speculation.winner_worker_id = self.worker_id
        frame_completed = state.mark_frame_as_finished(unit)
        if (
            frame_completed
            and state.job.tile_grid is not None
            and self._on_frame_complete is not None
        ):
            self._on_frame_complete(state, unit.frame_index)

    async def _handle_goodbye(self, event: pm.WorkerGoodbyeEvent) -> None:
        """Graceful drain: requeue the returned frames without an eviction.

        The goodbye's frame list is advisory — anything still mirrored
        here is swept too — and each frame is requeued only if this worker
        still owns its live assignment, so a goodbye racing an eviction
        (or a steal) can never double-pend a frame.
        """
        if self.is_dead:
            return  # eviction won the race; frames are already requeued
        self.is_dead = True
        self.drained = True
        self.cancel_heartbeat()
        now = time.time()
        # Mirror entries carry their owning job; the advisory units the
        # goodbye shipped are attributed to its (single) job_name — in a
        # multi-job cluster the mirror sweep is authoritative anyway,
        # since everything the master credits to this worker is mirrored.
        items = {(f.job_name, f.unit) for f in self.queue.all_frames()}
        tiles = event.returned_tiles or (None,) * len(event.returned_frames)
        items |= {
            (event.job_name, WorkUnit(index, tile))
            for index, tile in zip(event.returned_frames, tiles)
        }
        requeued = 0
        for job_name, unit in sorted(
            items, key=lambda item: (item[0] or "", item[1].sort_key)
        ):
            state = self._state_for(job_name)
            record = state.frames.get(unit) if state is not None else None
            frame = self.queue.remove(unit.frame_index, job_name, unit.tile)
            if frame is not None:
                self._complete_frame_flow(
                    "frame returned",
                    unit,
                    frame.trace,
                    start_wall=now,
                    duration=0.0,
                    extra_args={"reason": event.reason},
                )
            if (
                record is not None
                and record.status is not FrameStatus.FINISHED
                and record.worker_id == self.worker_id
            ):
                state.return_frame_to_pending(unit)
                requeued += 1
        self._update_queue_depth_gauge()
        if self.metrics is not None:
            if event.reason == "migrate":
                # A rebalance re-home is not an operator drain: counted
                # apart so the chaos audits' drain ledger stays exact.
                self.metrics.counter(
                    "master_worker_migrations_total",
                    "Workers that departed via a master-requested migrate "
                    "goodbye (shard rebalancing)",
                ).inc()
            else:
                self.metrics.counter(
                    "master_worker_drains_total",
                    "Workers that departed gracefully via the goodbye message",
                ).inc()
        self.logger.info(
            "Worker drained gracefully (%s); %d frame(s) requeued.",
            event.reason,
            requeued,
        )

    async def _manage_incoming_events(self) -> None:
        """Apply rendering/finished/goodbye events to the mirror + state.

        Reference: master/src/connection/mod.rs:240-326 (the goodbye
        branch is the drain extension).
        """
        rendering_queue = self.router.subscribe(pm.WorkerFrameQueueItemRenderingEvent)
        finished_queue = self.router.subscribe(pm.WorkerFrameQueueItemFinishedEvent)
        goodbye_queue = self.router.subscribe(pm.WorkerGoodbyeEvent)

        async def handle_rendering() -> None:
            while True:
                self._apply_rendering_event(await rendering_queue.get())

        async def handle_finished() -> None:
            while True:
                self._apply_finished_event(await finished_queue.get())

        async def handle_goodbye() -> None:
            while True:
                await self._handle_goodbye(await goodbye_queue.get())

        # gather instead of asyncio.TaskGroup so the master still runs on
        # Python 3.10; first failure cancels the sibling loop the same way.
        tasks = [
            asyncio.ensure_future(handle_rendering()),
            asyncio.ensure_future(handle_finished()),
            asyncio.ensure_future(handle_goodbye()),
        ]
        try:
            await asyncio.gather(*tasks)
        except asyncio.CancelledError:
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        except Exception as e:  # noqa: BLE001 - loop death is a worker failure
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            await self._mark_dead(f"event loop failed: {e}")

    async def _maintain_heartbeat(self) -> None:
        """Ping every 10 s; heartbeat failure marks the worker dead.

        Reference: master/src/connection/mod.rs:327-423, except failure
        triggers eviction instead of only killing the heartbeat task, and
        the two failure modes are separated: a SEND failure (socket gone,
        reconnect window expired) evicts immediately, while a missed PONG
        gets ``heartbeat_pong_retries()`` re-pings first — a pong lost to
        a transient partition that healed must not evict a live worker.
        """
        pong_queue = self.router.subscribe(pm.WorkerHeartbeatResponse)
        missed = 0
        try:
            while True:
                # Ping FIRST, then sleep (the reference sleeps first): the
                # immediate first exchange seeds the clock-offset estimator
                # at registration time, so even short jobs get their worker
                # rows rebased in the merged cluster timeline. Safe against
                # drops because the worker subscribes its heartbeat queue
                # before starting its receive loop.
                request = pm.MasterHeartbeatRequest.new_now()
                sent_at = time.perf_counter()
                try:
                    await self.sender.send_message(request)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 - socket definitively gone
                    await self._mark_dead(f"heartbeat send failed: {e}")
                    return
                try:
                    # The predicate discards stale pongs (answers to an
                    # earlier, timed-out ping): matching one to THIS ping
                    # would feed the clock estimator a sample whose four
                    # timestamps span two exchanges. Anonymous pongs (C++
                    # workers echo nothing) always match — they carry no
                    # clock timestamps, so nothing can be corrupted.
                    pong = await self.router.wait_for_message(
                        pm.WorkerHeartbeatResponse,
                        predicate=lambda p: p.echo_request_time is None
                        or p.echo_request_time == request.request_time,
                        timeout=HEARTBEAT_RESPONSE_TIMEOUT,
                        queue=pong_queue,
                    )
                except asyncio.CancelledError:
                    raise
                except asyncio.TimeoutError:
                    missed += 1
                    if missed > heartbeat_pong_retries():
                        await self._mark_dead(
                            f"no heartbeat response after {missed} pings"
                        )
                        return
                    self.logger.warning(
                        "Heartbeat pong missed (%d); re-pinging.", missed
                    )
                    continue
                except Exception as e:  # noqa: BLE001
                    await self._mark_dead(f"heartbeat failed: {e}")
                    return
                correlated = pong.echo_request_time is not None or missed == 0
                missed = 0
                pong_wall = time.time()
                if self.metrics is not None and correlated:
                    # An ANONYMOUS pong right after a miss may be the
                    # timed-out ping's late answer (C++ workers echo no
                    # request time), so its RTT against THIS ping is
                    # meaningless — skip the observation.
                    self.metrics.histogram(
                        "transport_heartbeat_rtt_seconds",
                        "Heartbeat ping->pong round-trip per worker",
                        labels=("worker",),
                    ).observe(
                        time.perf_counter() - sent_at,
                        worker=self._worker_label(),
                    )
                if pong.received_at is not None and pong.responded_at is not None:
                    self._observe_clock_sample(
                        request.request_time,
                        pong.received_at,
                        pong.responded_at,
                        pong_wall,
                    )
                if pong.metrics is not None:
                    self.latest_worker_metrics = pong.metrics
                await asyncio.sleep(HEARTBEAT_INTERVAL_SECONDS)
        except asyncio.CancelledError:
            raise
        finally:
            self.router.unsubscribe(pm.WorkerHeartbeatResponse, pong_queue)

    def _observe_clock_sample(
        self, t1: float, t2: float, t3: float, t4: float
    ) -> None:
        """Fold one NTP exchange into the estimator and export the gauges."""
        self.clock_offset.add_ping(t1, t2, t3, t4)
        if self.metrics is None:
            return
        label = self._worker_label()
        self.metrics.gauge(
            "master_worker_clock_offset_seconds",
            "Estimated worker-minus-master wall clock offset in SECONDS "
            "(median of the heartbeat NTP window; positive = the worker "
            "clock reads ahead of the master)",
            labels=("worker",),
        ).set(self.clock_offset.offset(), worker=label)
        self.metrics.gauge(
            "master_worker_clock_drift_ppm",
            "Estimated worker clock drift rate vs the master in "
            "parts-per-million (microseconds of divergence per elapsed "
            "second; positive = the worker clock runs fast)",
            labels=("worker",),
        ).set(self.clock_offset.drift_ppm(), worker=label)
