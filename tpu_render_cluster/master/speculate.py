"""Straggler-aware speculative re-execution of predicted tail units.

On tail-heavy workloads the makespan is gated by the last straggling unit
(the per-worker straggler scores in analysis/critical_path.py prove it):
once the pending pool is dry, every other worker idles while one slow (or
silently degraded) worker grinds through its final unit, and stealing
cannot help — a unit that is already RENDERING cannot be unqueued.

This module closes that gap with duplicate-dispatch hedging, which the
exactly-once dedup ledger (PR 4) makes safe by construction:

- when the predicted completion of a job's tail unit exceeds
  ``TRC_SPEC_THRESHOLD`` x the p50 predicted unit time of the in-flight
  set (or the unit is overdue by the same factor — the model cannot
  predict a hang) AND an idle worker exists, a byte-identical TWIN of the
  ``(frame, tile)`` unit is dispatched to the fastest idle worker;
- the first accepted ok result wins: the frame record still points at the
  PRIMARY assignment, so a twin that finishes first lands through the
  existing late-result acceptance path and the primary's copy is absorbed
  as a duplicate (or vice versa) — ``ok_results - duplicate_results ==
  units_total`` keeps holding under every interleaving;
- the loser is unqueued through the same frame-queue-remove RPC steals
  and preemption use (``already-rendering``/``already-finished`` races
  silently tolerated — a loser that raced past removal resolves as an
  absorbed duplicate).

Everything is master-internal: the wire never learns a dispatch was
speculative, C++ workers run unmodified, and speculation-off clusters are
byte-identical to before.

Outcomes (``sched_speculations_total{outcome}``):

- ``won``   — the twin delivered first: the hedge cut the tail;
- ``lost``  — the primary delivered first and the twin was cancelled
  before it started rendering (the hedge cost one queue slot);
- ``wasted``— the primary delivered first but the twin had already
  rendered (or its result raced in): full duplicate work, absorbed by
  the ledger.

Configuration (env, read at master construction):

- ``TRC_SPECULATION``       — enable (default 0/off);
- ``TRC_SPEC_THRESHOLD``    — tail trigger multiple over the p50
  predicted in-flight unit time (default 2.0);
- ``TRC_SPEC_MIN_SAMPLES``  — cost-model observations required before
  prediction-triggered speculation (overdue-triggered speculation works
  from the first tick; default 3);
- ``TRC_SPEC_MAX_ACTIVE``   — concurrent speculative twins per job
  (default 2).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, NamedTuple, Sequence

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.jobs.tiles import WorkUnit
from tpu_render_cluster.master.state import (
    ClusterManagerState,
    FrameStatus,
    SpeculationRecord,
)
from tpu_render_cluster.utils.cancellation import CancellationToken
from tpu_render_cluster.utils.env import env_float, env_int

if TYPE_CHECKING:
    # Type-only: importing sched.cost_model at runtime here would cycle
    # (sched/__init__ -> sched.manager -> master.cluster -> this module).
    from tpu_render_cluster.master.worker_handle import WorkerHandle
    from tpu_render_cluster.obs import MetricsRegistry, Tracer
    from tpu_render_cluster.sched.cost_model import CostModelService

logger = logging.getLogger(__name__)

SPECULATION_TICK = 0.05  # matches the strategy/scheduler tick cadence

OUTCOME_WON = "won"
OUTCOME_LOST = "lost"
OUTCOME_WASTED = "wasted"


@dataclass(frozen=True)
class SpeculationConfig:
    """Tuning knobs, each with a ``TRC_SPEC*`` environment override."""

    enabled: bool = False
    threshold: float = 2.0
    min_samples: int = 3
    max_active: int = 2

    @classmethod
    def from_env(cls) -> "SpeculationConfig":
        return cls(
            enabled=env_int("TRC_SPECULATION", 0) != 0,
            threshold=env_float("TRC_SPEC_THRESHOLD", cls.threshold),
            min_samples=env_int("TRC_SPEC_MIN_SAMPLES", cls.min_samples),
            max_active=env_int("TRC_SPEC_MAX_ACTIVE", cls.max_active),
        )


class InFlightUnit(NamedTuple):
    """One in-flight unit's speculation inputs (pure selection row)."""

    unit: WorkUnit
    worker_id: int
    predicted_s: float
    elapsed_s: float

    @property
    def tail_score(self) -> float:
        """How long this unit plausibly still gates the job: the model's
        prediction, or how long it has ALREADY run when that exceeds the
        prediction (an overdue unit is evidence the prediction is wrong —
        a hang or an unmodeled straggler)."""
        return max(self.predicted_s, self.elapsed_s)


def select_speculation_candidate(
    units: Sequence[InFlightUnit], *, threshold: float
) -> InFlightUnit | None:
    """The tail unit worth hedging, or None.

    Pure so the trigger's decision structure is unit-testable without a
    cluster (the same design rule as fair_share.py / the makespan gate):
    the worst tail score must exceed ``threshold`` x the p50 PREDICTED
    unit time of the in-flight set — with a single in-flight unit the p50
    is that unit's own prediction, so only overdue-ness (elapsed) can
    trigger, never the prediction against itself.
    """
    if not units:
        return None
    predictions = sorted(u.predicted_s for u in units)
    p50 = predictions[len(predictions) // 2]
    best: InFlightUnit | None = None
    for unit in units:
        if unit.tail_score <= threshold * max(p50, 1e-9):
            continue
        if best is None or unit.tail_score > best.tail_score:
            best = unit
    return best


class SpeculationService:
    """Per-master speculation engine shared by every scheduler loop.

    The live twin table lives on each job's ``ClusterManagerState``
    (``state.speculations``) so result handling (worker_handle stamps the
    winner) and this service's resolution never disagree about which job
    a twin belongs to.
    """

    def __init__(
        self,
        config: SpeculationConfig | None = None,
        *,
        cost: "CostModelService",
        metrics: "MetricsRegistry | None" = None,
        span_tracer: "Tracer | None" = None,
    ) -> None:
        self.config = config if config is not None else SpeculationConfig.from_env()
        self.cost = cost
        self.metrics = metrics
        self.span_tracer = span_tracer
        self.launched_total = 0
        self.outcomes: dict[str, int] = {
            OUTCOME_WON: 0,
            OUTCOME_LOST: 0,
            OUTCOME_WASTED: 0,
        }

    # -- accounting ----------------------------------------------------------

    def _count_outcome(self, outcome: str, record: SpeculationRecord) -> None:
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if self.metrics is not None:
            self.metrics.counter(
                "sched_speculations_total",
                "Resolved speculative twin dispatches by outcome "
                "(won = twin delivered first, lost = twin cancelled "
                "unrendered, wasted = duplicate work absorbed)",
                labels=("outcome",),
            ).inc(outcome=outcome)
        if self.span_tracer is not None:
            self.span_tracer.instant(
                "speculation resolved",
                cat="sched",
                track="speculation",
                args={
                    "frame": record.unit.frame_index,
                    **({"tile": record.unit.tile} if record.unit.tile is not None else {}),
                    "outcome": outcome,
                    "primary": f"{record.primary_worker_id:08x}",
                    "twin": f"{record.twin_worker_id:08x}",
                },
            )

    def view(self) -> dict:
        """Live section for cluster_view / chaos reports."""
        return {
            "enabled": self.config.enabled,
            "threshold": self.config.threshold,
            "launched": self.launched_total,
            "outcomes": dict(self.outcomes),
        }

    # -- resolution ----------------------------------------------------------

    async def resolve(
        self,
        job: BlenderJob,
        state: ClusterManagerState,
        workers: Sequence["WorkerHandle"],
    ) -> None:
        """Settle every speculation whose race is decided (or broken)."""
        if not state.speculations:
            return
        by_id = {worker.worker_id: worker for worker in workers}
        for unit, record in list(state.speculations.items()):
            frame_record = state.frames.get(unit)
            if frame_record is None:
                state.speculations.pop(unit, None)
                continue
            if frame_record.status is FrameStatus.FINISHED:
                state.speculations.pop(unit, None)
                await self._settle_finished(job, state, record, by_id)
                continue
            # Races that break the speculation before any result lands.
            twin = by_id.get(record.twin_worker_id)
            twin_entry = (
                twin.queue.get(unit.frame_index, job.job_name, unit.tile)
                if twin is not None and not twin.is_dead
                else None
            )
            if frame_record.status is FrameStatus.PENDING:
                # The primary died: eviction requeued the unit while the
                # still-live twin already holds a copy — PROMOTE the twin
                # to the live assignment instead of throwing the hedge
                # away (and instead of letting dispatch put a third copy
                # in play). Counted as a win: the hedge is what kept the
                # unit warm through the primary's death.
                state.speculations.pop(unit, None)
                if twin_entry is not None:
                    state.mark_frame_as_queued(
                        unit, record.twin_worker_id, twin_entry.queued_at
                    )
                    self._count_outcome(OUTCOME_WON, record)
                else:
                    self._count_outcome(OUTCOME_LOST, record)
                continue
            # Twin died/was swept, or the primary assignment moved to a
            # third worker (steal, or a re-dispatch that beat this tick):
            # the unit is back in the ordinary dispatch machinery's hands
            # and the dedup seam owns whatever the twin still does.
            primary_moved = frame_record.worker_id not in (
                record.primary_worker_id,
                record.twin_worker_id,
            )
            if twin_entry is None or primary_moved:
                state.speculations.pop(unit, None)
                if twin_entry is not None:
                    await self._unqueue_loser(job, twin, unit)
                self._count_outcome(OUTCOME_LOST, record)

    async def _settle_finished(
        self,
        job: BlenderJob,
        state: ClusterManagerState,
        record: SpeculationRecord,
        by_id: dict[int, "WorkerHandle"],
    ) -> None:
        winner = record.winner_worker_id
        if winner == record.twin_worker_id:
            loser_id, outcome = record.primary_worker_id, OUTCOME_WON
        else:
            # Unknown winner (e.g. the unit was finished by resume or a
            # third late result) settles conservatively as primary-won.
            loser_id = record.twin_worker_id
            outcome = OUTCOME_LOST
        loser = by_id.get(loser_id)
        wasted = False
        if loser is not None and not loser.is_dead:
            entry = loser.queue.get(
                record.unit.frame_index, job.job_name, record.unit.tile
            )
            if entry is None:
                # The loser's copy already delivered (absorbed as a
                # duplicate) or was swept: the work happened.
                wasted = True
            else:
                if entry.is_rendering:
                    wasted = True
                removed = await self._unqueue_loser(job, loser, record.unit)
                if not removed:
                    wasted = True
        else:
            # A dead loser rendered nothing further; its mirror was
            # cleared by eviction. The race simply ended.
            wasted = False
        if outcome != OUTCOME_WON and wasted:
            outcome = OUTCOME_WASTED
        self._count_outcome(outcome, record)

    @staticmethod
    async def _unqueue_loser(
        job: BlenderJob, worker: "WorkerHandle", unit: WorkUnit
    ) -> bool:
        """Remove the losing copy; tolerant of the remove-vs-render races
        exactly like steals/preemption (an already-rendering loser keeps
        going and its result is absorbed as a duplicate)."""
        from tpu_render_cluster.protocol import messages as pm

        try:
            result = await worker.unqueue_frame(job.job_name, unit)
        except Exception as e:  # noqa: BLE001 - worker failure mid-RPC
            logger.warning(
                "Speculation loser unqueue failed on %08x: %s",
                worker.worker_id,
                e,
            )
            return False
        return result == pm.FRAME_QUEUE_REMOVE_RESULT_REMOVED

    # -- launching -----------------------------------------------------------

    def _in_flight_rows(
        self,
        job: BlenderJob,
        state: ClusterManagerState,
        live_ids: set[int],
        now: float,
    ) -> list[InFlightUnit]:
        rows: list[InFlightUnit] = []
        for unit, record in state.frames.items():
            if record.status not in (
                FrameStatus.QUEUED_ON_WORKER,
                FrameStatus.RENDERING_ON_WORKER,
            ):
                continue
            if record.worker_id not in live_ids or unit in state.speculations:
                continue
            rows.append(
                InFlightUnit(
                    unit=unit,
                    worker_id=record.worker_id,
                    predicted_s=self.cost.predict_unit_seconds(
                        record.worker_id, unit, job
                    ),
                    elapsed_s=max(0.0, now - (record.queued_at or now)),
                )
            )
        return rows

    async def maybe_launch(
        self,
        job: BlenderJob,
        state: ClusterManagerState,
        workers: Sequence["WorkerHandle"],
        *,
        job_id: str | None = None,
    ) -> bool:
        """Dispatch at most one speculative twin; True when one launched.

        Only fires at the job tail: dispatching pending work always takes
        priority over hedging (an idle worker with pending frames should
        receive a fresh frame, not a duplicate), so callers tick this
        after their normal dispatch pass.
        """
        if not self.config.enabled:
            return False
        # O(1) amortized tail gate (pending_count() would scan the whole
        # deque every 50 ms tick for the life of the job).
        if state.next_pending_unit() is not None:
            return False
        if len(state.speculations) >= max(1, self.config.max_active):
            return False
        live = [w for w in workers if not w.is_dead]
        idle = [w for w in live if len(w.queue) == 0]
        if not idle:
            return False
        now = time.time()
        live_ids = {w.worker_id for w in live}
        rows = self._in_flight_rows(job, state, live_ids, now)
        candidate = select_speculation_candidate(
            rows, threshold=self.config.threshold
        )
        if candidate is None:
            return False
        if (
            self.cost.model.samples_observed < self.config.min_samples
            and candidate.elapsed_s < candidate.predicted_s
        ):
            # The PREDICTION trigger needs a minimally-warm model; the
            # overdue trigger (elapsed dominating the prediction) works
            # from the first tick — a hang needs no history to be real.
            return False
        targets = [w for w in idle if w.worker_id != candidate.worker_id]
        if not targets:
            return False
        target = min(
            targets,
            key=lambda w: self.cost.model.worker_speed.predict(w.worker_id),
        )
        predicted_twin = self.cost.predict_unit_seconds(
            target.worker_id, candidate.unit, job
        )
        if predicted_twin >= candidate.tail_score:
            return False  # the hedge cannot beat the incumbent
        record = SpeculationRecord(
            unit=candidate.unit,
            primary_worker_id=candidate.worker_id,
            twin_worker_id=target.worker_id,
            started_at=now,
            predicted_primary_s=candidate.predicted_s,
            predicted_twin_s=predicted_twin,
        )
        # Register BEFORE the dispatch await: a result racing the add-RPC
        # must find the record to stamp its winner on.
        state.speculations[candidate.unit] = record
        try:
            await target.queue_frame(
                job, candidate.unit, job_id=job_id, speculative=True
            )
        except Exception as e:  # noqa: BLE001 - dispatch raced death/finish
            state.speculations.pop(candidate.unit, None)
            logger.debug(
                "Speculative dispatch of unit %s to %08x aborted: %s",
                candidate.unit.label,
                target.worker_id,
                e,
            )
            return False
        self.launched_total += 1
        if self.metrics is not None:
            self.metrics.counter(
                "sched_speculations_launched_total",
                "Speculative twin dispatches issued",
            ).inc()
        if self.span_tracer is not None:
            self.span_tracer.instant(
                "speculate",
                cat="sched",
                track="speculation",
                args={
                    "frame": candidate.unit.frame_index,
                    **(
                        {"tile": candidate.unit.tile}
                        if candidate.unit.tile is not None
                        else {}
                    ),
                    "primary": f"{candidate.worker_id:08x}",
                    "twin": f"{target.worker_id:08x}",
                    "predicted_primary_s": round(candidate.predicted_s, 6),
                    "predicted_twin_s": round(predicted_twin, 6),
                    "elapsed_s": round(candidate.elapsed_s, 6),
                },
            )
        logger.info(
            "Speculating unit %s: primary %08x (predicted %.3fs, elapsed "
            "%.3fs) -> twin on %08x (predicted %.3fs).",
            candidate.unit.label,
            candidate.worker_id,
            candidate.predicted_s,
            candidate.elapsed_s,
            target.worker_id,
            predicted_twin,
        )
        return True

    async def tick(
        self,
        job: BlenderJob,
        state: ClusterManagerState,
        workers: Sequence["WorkerHandle"],
        *,
        job_id: str | None = None,
    ) -> None:
        await self.resolve(job, state, workers)
        await self.maybe_launch(job, state, workers, job_id=job_id)


async def speculation_loop(
    job: BlenderJob,
    state: ClusterManagerState,
    workers_fn: Callable[[], Sequence["WorkerHandle"]],
    cancellation: CancellationToken,
    service: SpeculationService,
) -> None:
    """The single-job master's speculation sidecar.

    Runs beside ``run_strategy`` (any strategy — the tail-hedging logic
    is strategy-agnostic) at the shared tick cadence: ingest fresh
    completion observations into the shared cost model (for strategies
    that don't feed it themselves), resolve decided races, maybe hedge
    the tail. Exits with the job; a final resolve pass settles
    still-open races so every launched twin gets an outcome and losers'
    mirror entries are removed before the finalization sweep audits them.
    """
    if not service.config.enabled:
        return
    job_for = lambda _job_name: job  # noqa: E731 - single-job loop
    while not cancellation.is_cancelled() and not state.all_frames_finished():
        workers = [w for w in workers_fn() if not w.is_dead]
        service.cost.ingest(workers, job_for)
        await service.tick(job, state, workers, job_id=state.sched_job_id)
        await asyncio.sleep(SPECULATION_TICK)
    service.cost.ingest(
        [w for w in workers_fn() if not w.is_dead], job_for
    )
    await service.resolve(job, state, list(workers_fn()))
