"""Master-side replica of each worker's frame queue.

Reference: ``WorkerQueue`` / ``FrameOnWorker``
(master/src/connection/queue.rs:10-122). The mirror lets the scheduler sort
workers by load and pick steal candidates without a network round-trip; the
atomic size counter of the reference collapses to ``len()`` because all
mutation happens on one event loop.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from tpu_render_cluster.protocol.messages import TraceContext


@dataclass
class FrameOnWorker:
    frame_index: int
    queued_at: float
    is_rendering: bool = False
    stolen_from: int | None = None
    # Trace context of this assignment, kept so the master can close the
    # frame's Perfetto flow even when the terminating event (a
    # reference-shaped C++ worker's, a steal, an eviction) doesn't echo it.
    trace: "TraceContext | None" = None


class WorkerQueueMirror:
    """Insertion-ordered mirror of a worker's remote queue."""

    def __init__(self) -> None:
        self._frames: dict[int, FrameOnWorker] = {}

    def __len__(self) -> int:
        return len(self._frames)

    def __contains__(self, frame_index: int) -> bool:
        return frame_index in self._frames

    def add(self, frame: FrameOnWorker) -> None:
        self._frames[frame.frame_index] = frame

    def remove(self, frame_index: int) -> FrameOnWorker | None:
        return self._frames.pop(frame_index, None)

    def clear(self) -> None:
        """Drop every mirrored frame (eviction/drain: the worker is gone
        and keeping its mirror would leave ghost assignments a later steal
        pass could try to act on)."""
        self._frames.clear()

    def set_rendering(self, frame_index: int) -> None:
        frame = self._frames.get(frame_index)
        if frame is not None:
            frame.is_rendering = True

    def queued_frames_in_order(self) -> list[FrameOnWorker]:
        """Frames not yet rendering, oldest first (steal-candidate order)."""
        return [f for f in self._frames.values() if not f.is_rendering]

    def all_frames(self) -> list[FrameOnWorker]:
        return list(self._frames.values())

    def pending_size(self) -> int:
        """Queue entries that have not started rendering."""
        return sum(1 for f in self._frames.values() if not f.is_rendering)
