"""Master-side replica of each worker's work-unit queue.

Reference: ``WorkerQueue`` / ``FrameOnWorker``
(master/src/connection/queue.rs:10-122). The mirror lets the scheduler sort
workers by load and pick steal candidates without a network round-trip; the
atomic size counter of the reference collapses to ``len()`` because all
mutation happens on one event loop.

Keying: entries are keyed ``(job_name, frame_index, tile)`` through the
single ``mirror_key`` normalizer — a worker's queue can hold units from
SEVERAL jobs (sched/manager.py multiplexes them), two jobs may contain the
same frame index, and a tiled job legitimately parks several tiles of ONE
frame on one worker. The index-only legacy fallback scan that predated the
multi-job mirror is gone: every mutating caller names the owning job (the
single-job paths included — their one job's name is always at hand), so a
fallback could only ever mask a routing bug.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

from tpu_render_cluster.jobs.tiles import WorkUnit

if TYPE_CHECKING:
    from tpu_render_cluster.protocol.messages import TraceContext

MirrorKey = tuple[str | None, int, int | None]


def mirror_key(
    job_name: str | None, frame_index: int, tile: int | None = None
) -> MirrorKey:
    """THE mirror key normalizer: every lookup and every insertion goes
    through here, so frame-keyed callers cannot drift from tile-keyed
    ones (``tile=None`` IS the whole-frame key, not a wildcard)."""
    return (job_name, int(frame_index), tile if tile is None else int(tile))


@dataclass
class FrameOnWorker:
    frame_index: int
    queued_at: float
    is_rendering: bool = False
    stolen_from: int | None = None
    # Trace context of this assignment, kept so the master can close the
    # frame's Perfetto flow even when the terminating event (a
    # reference-shaped C++ worker's, a steal, an eviction) doesn't echo it.
    trace: "TraceContext | None" = None
    # Owning job (multi-job masters; None on the legacy single-job path).
    job_name: str | None = None
    job_id: str | None = None
    # Sub-frame tile index (None = whole frame).
    tile: int | None = None

    @property
    def unit(self) -> WorkUnit:
        return WorkUnit(self.frame_index, self.tile)


class WorkerQueueMirror:
    """Insertion-ordered mirror of a worker's remote queue."""

    def __init__(self) -> None:
        self._frames: dict[MirrorKey, FrameOnWorker] = {}

    def __len__(self) -> int:
        return len(self._frames)

    def add(self, frame: FrameOnWorker) -> None:
        self._frames[
            mirror_key(frame.job_name, frame.frame_index, frame.tile)
        ] = frame

    def get(
        self, frame_index: int, job_name: str | None = None,
        tile: int | None = None,
    ) -> FrameOnWorker | None:
        return self._frames.get(mirror_key(job_name, frame_index, tile))

    def remove(
        self, frame_index: int, job_name: str | None = None,
        tile: int | None = None,
    ) -> FrameOnWorker | None:
        return self._frames.pop(mirror_key(job_name, frame_index, tile), None)

    def clear(self) -> None:
        """Drop every mirrored unit (eviction/drain: the worker is gone
        and keeping its mirror would leave ghost assignments a later steal
        pass could try to act on)."""
        self._frames.clear()

    def set_rendering(
        self, frame_index: int, job_name: str | None = None,
        tile: int | None = None,
    ) -> None:
        entry = self._frames.get(mirror_key(job_name, frame_index, tile))
        if entry is not None:
            entry.is_rendering = True

    def queued_frames_in_order(self) -> list[FrameOnWorker]:
        """Units not yet rendering, oldest first (steal-candidate order)."""
        return [f for f in self._frames.values() if not f.is_rendering]

    def all_frames(self) -> list[FrameOnWorker]:
        return list(self._frames.values())

    def frames_for_job(self, job_name: str) -> list[FrameOnWorker]:
        """This job's mirrored units, insertion order (sched/cancel path)."""
        return [f for f in self._frames.values() if f.job_name == job_name]

    def pending_size(self) -> int:
        """Queue entries that have not started rendering."""
        return sum(1 for f in self._frames.values() if not f.is_rendering)
