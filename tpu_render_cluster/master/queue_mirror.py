"""Master-side replica of each worker's frame queue.

Reference: ``WorkerQueue`` / ``FrameOnWorker``
(master/src/connection/queue.rs:10-122). The mirror lets the scheduler sort
workers by load and pick steal candidates without a network round-trip; the
atomic size counter of the reference collapses to ``len()`` because all
mutation happens on one event loop.

Multi-job extension: a worker's queue can hold frames from SEVERAL jobs
(sched/manager.py multiplexes them), and two jobs may legitimately contain
the same frame index, so entries are keyed by ``(job_name, frame_index)``.
Callers that don't pass a job name (single-job code paths, older tests)
fall back to an index-only scan — with one job on the queue that is the
exact pre-multi-job behavior.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from tpu_render_cluster.protocol.messages import TraceContext


@dataclass
class FrameOnWorker:
    frame_index: int
    queued_at: float
    is_rendering: bool = False
    stolen_from: int | None = None
    # Trace context of this assignment, kept so the master can close the
    # frame's Perfetto flow even when the terminating event (a
    # reference-shaped C++ worker's, a steal, an eviction) doesn't echo it.
    trace: "TraceContext | None" = None
    # Owning job (multi-job masters; None on the legacy single-job path).
    job_name: str | None = None
    job_id: str | None = None


class WorkerQueueMirror:
    """Insertion-ordered mirror of a worker's remote queue."""

    def __init__(self) -> None:
        self._frames: dict[tuple[str | None, int], FrameOnWorker] = {}

    def __len__(self) -> int:
        return len(self._frames)

    def __contains__(self, frame_index: int) -> bool:
        return self._find_key(frame_index) is not None

    def _find_key(
        self, frame_index: int, job_name: str | None = None
    ) -> tuple[str | None, int] | None:
        """Exact ``(job_name, frame_index)`` hit, else a LEGACY-only scan.

        The fallback keeps pre-multi-job callers working (entries added
        without a job_name, single-job mirrors) but must never cross
        jobs: a caller that names a job may only fall back to entries
        that were added WITHOUT one — otherwise a duplicate event for
        job A's already-popped frame could pop job B's same-index entry.
        """
        if (job_name, frame_index) in self._frames:
            return (job_name, frame_index)
        for key in self._frames:
            if key[1] == frame_index and (job_name is None or key[0] is None):
                return key
        return None

    def add(self, frame: FrameOnWorker) -> None:
        self._frames[(frame.job_name, frame.frame_index)] = frame

    def get(
        self, frame_index: int, job_name: str | None = None
    ) -> FrameOnWorker | None:
        key = self._find_key(frame_index, job_name)
        return self._frames[key] if key is not None else None

    def remove(
        self, frame_index: int, job_name: str | None = None
    ) -> FrameOnWorker | None:
        key = self._find_key(frame_index, job_name)
        if key is None:
            return None
        return self._frames.pop(key)

    def clear(self) -> None:
        """Drop every mirrored frame (eviction/drain: the worker is gone
        and keeping its mirror would leave ghost assignments a later steal
        pass could try to act on)."""
        self._frames.clear()

    def set_rendering(self, frame_index: int, job_name: str | None = None) -> None:
        key = self._find_key(frame_index, job_name)
        if key is not None:
            self._frames[key].is_rendering = True

    def queued_frames_in_order(self) -> list[FrameOnWorker]:
        """Frames not yet rendering, oldest first (steal-candidate order)."""
        return [f for f in self._frames.values() if not f.is_rendering]

    def all_frames(self) -> list[FrameOnWorker]:
        return list(self._frames.values())

    def frames_for_job(self, job_name: str) -> list[FrameOnWorker]:
        """This job's mirrored frames, insertion order (sched/cancel path)."""
        return [f for f in self._frames.values() if f.job_name == job_name]

    def pending_size(self) -> int:
        """Queue entries that have not started rendering."""
        return sum(1 for f in self._frames.values() if not f.is_rendering)
