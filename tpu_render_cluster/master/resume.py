"""Resume-by-scanning-output-dir.

The reference has no job-level checkpointing — a killed master loses all
frame state and a rerun re-renders everything, relying only on each frame
being an independent, cleanly-overwritten output file
(reference: SURVEY.md §5.4, scripts/render-timing-script.py:69-82). This
module adds the trivial-but-useful resume the reference suggests: before
scheduling, scan the job's output directory for frames that already exist
and mark them finished, so a restarted master only renders the remainder.

Enabled with ``master ... run-job <job.toml> --resume [--baseDirectory D]``.
"""

from __future__ import annotations

import logging
import re
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.state import ClusterManagerState
from tpu_render_cluster.utils.paths import parse_with_base_directory_prefix

logger = logging.getLogger(__name__)


def _output_pattern(job: BlenderJob) -> re.Pattern[str]:
    """Regex matching rendered file names, with the frame number captured.

    ``#####`` runs in ``output_file_name_format`` become zero-padded frame
    numbers (same placeholder contract as the render script —
    scripts/render-timing-script.py / reference R1).
    """
    name_format = job.output_file_name_format
    match = re.search(r"#+", name_format)
    extension = job.output_file_format.lower()
    if extension == "jpeg":
        extension = "jpg"
    if match is None:
        # No placeholder: the renderer appends the frame number to the
        # fixed name (image_io.format_frame_placeholders), so accept
        # "<name><digits>.<ext>"; a bare "<name>.<ext>" hit maps to the one
        # frame of a single-frame job (group stays empty in that case).
        return re.compile(
            re.escape(name_format) + r"(\d+)?\." + re.escape(extension) + r"$"
        )
    width = len(match.group(0))
    prefix = re.escape(name_format[: match.start()])
    suffix = re.escape(name_format[match.end() :])
    return re.compile(
        rf"{prefix}(\d{{{width},}}){suffix}\.{re.escape(extension)}$"
    )


def scan_rendered_frames(
    job: BlenderJob, base_directory: Path | str | None = None
) -> set[int]:
    """Frame indices whose output files already exist (and are non-empty)."""
    try:
        output_directory = parse_with_base_directory_prefix(
            job.output_directory_path, base_directory
        )
    except ValueError as e:
        logger.warning("Cannot resolve output directory for resume: %s", e)
        return set()
    if not output_directory.is_dir():
        return set()
    pattern = _output_pattern(job)
    valid = set(job.frame_indices())
    found: set[int] = set()
    for entry in output_directory.iterdir():
        match = pattern.fullmatch(entry.name)
        if match is None:
            continue
        try:
            if entry.stat().st_size == 0:
                continue  # truncated output from a killed render
        except OSError:
            continue
        digits = match.group(1) if match.groups() else None
        if digits:
            frame_index = int(digits)
        elif job.frame_count() == 1:
            # Fixed-name output: the one file IS the one frame.
            frame_index = job.frame_range_from
        else:
            continue  # ambiguous: fixed name cannot cover multiple frames
        if frame_index in valid:
            found.add(frame_index)
    return found


def load_cost_model(
    job: BlenderJob, results_directory: Path | str, *, respect_env: bool = True
):
    """Restore the job's snapshotted ``JointCostModel``, or None.

    The other half of resume: a restarted master re-learns which frames
    are DONE by scanning the output directory (below), and re-learns how
    fast each worker renders which frames from the cost-model snapshot
    the previous run persisted (master/persist.save_cost_model) — instead
    of cold-starting the predictors and re-paying the warmup misschedules.

    An explicit ``TRC_COST_MODEL`` wins over the snapshot (it was already
    loaded at master construction): with ``respect_env`` (the default)
    this returns None whenever the variable is set.
    """
    from tpu_render_cluster.master.persist import cost_model_snapshot_path
    from tpu_render_cluster.sched.cost_model import (
        explicit_model_configured,
        load_model_snapshot,
    )

    if respect_env and explicit_model_configured():
        return None
    model = load_model_snapshot(cost_model_snapshot_path(job, Path(results_directory)))
    if model is not None:
        logger.info(
            "Resume: cost model restored (%d samples).", model.samples_observed
        )
    return model


def apply_resume(
    state: ClusterManagerState,
    job: BlenderJob,
    base_directory: Path | str | None = None,
    *,
    ledger_replay=None,
) -> int:
    """Marks already-rendered work finished; returns how many units were
    restored.

    Unified with the write-ahead ledger (ha/ledger.py): when a ledger
    replay holds finished-unit records for this job, the LEDGER wins —
    it is exact (per unit, per tile, fsync'd at result time) where the
    output scan is approximate (frame-level, fooled by half-written or
    stale files). The directory scan remains the fallback for jobs that
    ran before any ledger existed.
    """
    from tpu_render_cluster.jobs.tiles import WorkUnit

    if ledger_replay is not None and ledger_replay.finished_units(job.job_name):
        from tpu_render_cluster.ha.failover import apply_ledger_to_state

        replayed, _ = apply_ledger_to_state(
            state, ledger_replay, include_closed=True
        )
        logger.info(
            "Resume: %d/%d unit(s) restored from the job ledger "
            "(output-directory scan skipped — the ledger is authoritative).",
            replayed,
            len(state.frames),
        )
        return replayed

    rendered = scan_rendered_frames(job, base_directory)
    for frame_index in sorted(rendered):
        # A finished FRAME file covers every unit of that frame: under a
        # tile grid the assembled output only exists once all tiles landed
        # and were stitched, so all of them are safe to skip.
        if job.tile_grid is None:
            state.mark_frame_as_finished(WorkUnit(frame_index))
        else:
            for tile in range(job.tiles_per_frame()):
                if state.mark_frame_as_finished(WorkUnit(frame_index, tile)):
                    state.note_frame_assembled(frame_index)
    if rendered:
        logger.info(
            "Resume: %d/%d frames already rendered; %d remain.",
            len(rendered),
            job.frame_count(),
            job.frame_count() - len(rendered),
        )
    return len(rendered)
