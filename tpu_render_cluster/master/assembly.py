"""Master-side frame assembly: stitch finished tiles into frame images.

The tile-sharded pipeline (PR 7) makes the unit of distribution a
``(frame, tile)`` work unit: each worker renders its tile region and
writes ``<frame>.tile_rRcC.png`` next to where the whole frame would go.
The master's exactly-once ledger (``ClusterManagerState``) knows the
moment the LAST tile of a frame reaches FINISHED — that transition fires
exactly once per frame — and this service then scatters the tile images
into the frame buffer: reads the grid's tiles, concatenates rows/columns,
writes the final frame file, and removes the tile intermediates.

Design constraints:

- **Exactly once**: the scheduling hook is only reachable through
  ``ClusterManagerState.mark_frame_as_finished``'s one-shot frame-complete
  transition, so duplicate/late copies of the final tile can never
  stitch a frame twice.
- **Off the event loop**: stitching is file I/O over potentially-megabyte
  images; it runs in a thread (``asyncio.to_thread``) and the master's
  event handling never blocks on it. ``drain()`` awaits every scheduled
  stitch — the job is not complete until its frames exist on disk.
- **Mock-tolerant**: integration/chaos clusters run backends that render
  nothing (worker/backends/mock.py). A frame whose tile files are absent
  is counted assembled in the ledger (the bookkeeping — what the chaos
  invariants audit — is exact) and the image pass is skipped.
"""

from __future__ import annotations

import asyncio
import logging
import time
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.state import ClusterManagerState
from tpu_render_cluster.utils.paths import parse_with_base_directory_prefix

logger = logging.getLogger(__name__)


def tile_file_path(
    output_directory: Path,
    name_format: str,
    file_format: str,
    frame_index: int,
    tile: int,
    grid: tuple[int, int],
) -> Path:
    """Alias of ``render.image_io.output_path_for_tile`` (the single
    naming definition workers write through)."""
    from tpu_render_cluster.render.image_io import output_path_for_tile

    return output_path_for_tile(
        output_directory, name_format, file_format, frame_index, tile, grid
    )


def assemble_frame_files(
    job: BlenderJob,
    frame_index: int,
    *,
    base_directory: str | Path | None = None,
) -> Path | None:
    """Stitch one frame's tile files into its final image (sync).

    Returns the written frame path, or None when no tile files exist
    (mock-backend clusters render no pixels — the ledger still counts the
    frame assembled). Raises when tiles exist but are inconsistent: a
    partially-written grid is a bug worth surfacing, not papering over.
    """
    import numpy as np
    from PIL import Image

    from tpu_render_cluster.render.image_io import (
        output_path_for_frame,
        write_image,
    )

    assert job.tile_grid is not None
    rows, cols = job.tile_grid
    try:
        output_directory = parse_with_base_directory_prefix(
            job.output_directory_path, base_directory
        )
    except ValueError:
        # %BASE% with no base directory on this master: nothing was (or
        # could have been) written where we can see it — mock/synthetic
        # clusters land here; the "no-tiles" outcome keeps it visible.
        return None
    tile_paths = [
        tile_file_path(
            output_directory,
            job.output_file_name_format,
            job.output_file_format,
            frame_index,
            tile,
            job.tile_grid,
        )
        for tile in range(rows * cols)
    ]
    existing = [p.exists() for p in tile_paths]
    if not any(existing):
        return None
    if not all(existing):
        missing = [str(p) for p, e in zip(tile_paths, existing) if not e]
        raise FileNotFoundError(
            f"Frame {frame_index}: {len(missing)} of {rows * cols} tile "
            f"file(s) missing at assembly time: {missing[:4]}"
        )
    tiles = [np.asarray(Image.open(p).convert("RGB")) for p in tile_paths]
    bands = [
        np.concatenate(tiles[r * cols : (r + 1) * cols], axis=1)
        for r in range(rows)
    ]
    pixels = np.concatenate(bands, axis=0)
    frame_path = output_path_for_frame(
        output_directory,
        job.output_file_name_format,
        job.output_file_format,
        frame_index,
    )
    write_image(frame_path, pixels, job.output_file_format)
    for path in tile_paths:
        try:
            path.unlink()
        except OSError:  # a vanished intermediate is not worth failing over
            pass
    return frame_path


class FrameAssemblyService:
    """Schedules and tracks per-frame assembly on the master's loop.

    ``schedule`` is the sync hook WorkerHandle fires from the finished-
    event path (exactly once per frame); ``drain`` is the completion
    barrier the job/scheduler awaits before declaring a tiled job done.
    """

    def __init__(
        self,
        *,
        metrics=None,
        span_tracer=None,
        base_directory: str | Path | None = None,
    ) -> None:
        self.metrics = metrics
        self.span_tracer = span_tracer
        self.base_directory = base_directory
        # task -> owning job_name, so per-job completion (the scheduler's
        # finalize gate) can be answered without touching other jobs'
        # in-flight stitches.
        self._tasks: dict[asyncio.Task, str] = {}

    def schedule(self, state: ClusterManagerState, frame_index: int) -> None:
        """All tiles of ``frame_index`` landed: stitch it in the background."""
        task = asyncio.create_task(
            self._assemble(state, frame_index),
            name=f"assemble-{state.job.job_name}-{frame_index}",
        )
        self._tasks[task] = state.job.job_name
        task.add_done_callback(lambda t: self._tasks.pop(t, None))

    def has_pending(self, job_name: str) -> bool:
        """Stitches of ``job_name`` still in flight — a job must not be
        declared FINISHED (nor its name released for reuse) before they
        land."""
        return any(name == job_name for name in self._tasks.values())

    async def drain(self) -> None:
        """Await every scheduled assembly (the tiled-job completion barrier)."""
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    async def drain_job(self, job_name: str) -> None:
        """Await one job's in-flight stitches (the cancel path: the job's
        name must not be released for reuse while its stitcher can still
        read/write/unlink files under the shared output path)."""
        while True:
            tasks = [t for t, name in self._tasks.items() if name == job_name]
            if not tasks:
                return
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _assemble(
        self, state: ClusterManagerState, frame_index: int
    ) -> None:
        started_wall = time.time()
        started = time.perf_counter()
        result = "ok"
        try:
            path = await asyncio.to_thread(
                assemble_frame_files,
                state.job,
                frame_index,
                base_directory=self.base_directory,
            )
        except Exception as e:  # noqa: BLE001 - account, don't kill the loop
            result = "errored"
            path = None
            logger.error(
                "Assembly of frame %d (%r) failed: %s",
                frame_index,
                state.job.job_name,
                e,
            )
        else:
            if path is None:
                result = "no-tiles"
        # The LEDGER transition is unconditional: the frame's tiles all
        # reached FINISHED exactly once, which is what the chaos
        # invariants audit; the image pass is reported separately.
        state.note_frame_assembled(frame_index)
        duration = time.perf_counter() - started
        if self.metrics is not None:
            self.metrics.counter(
                "master_frames_assembled_total",
                "Tiled frames whose tiles all landed, by stitch outcome",
                labels=("result",),
            ).inc(result=result)
            self.metrics.histogram(
                "master_frame_assembly_seconds",
                "Tile-stitch duration per assembled frame",
            ).observe(duration)
        if self.span_tracer is not None:
            self.span_tracer.complete(
                "frame assembled",
                cat="master",
                start_wall=started_wall,
                duration=duration,
                track="assembly",
                args={
                    "frame": frame_index,
                    "job": state.job.job_name,
                    "tiles": state.job.tiles_per_frame(),
                    "result": result,
                },
            )
