"""Line-JSON assignment service for the C++ master daemon (trc-master).

Keeps the tpu-batch scheduler's *math* in JAX on the accelerator while the
control plane is native: the C++ master (native/master_daemon.cpp) launches
this module as a persistent subprocess and streams one JSON object per line
on stdin, receiving one per line on stdout:

    -> {"id": N, "cost": [[...], ...]}            an [items, slots] cost matrix
    <- {"id": N, "assignment": [s0, s1, ...]}     slot index per item
    -> {"op": "exit"}                             clean shutdown

Requests carry an ``id`` echoed back in the response so a caller that timed
out on one solve can discard the stale line instead of mis-pairing it with
the next request (the same correlation idea as the wire protocol's
``message_request_context_id``).

On startup the service warms the auction solver across the power-of-two
shape buckets real clusters hit (XLA compiles once per bucket; a cold
compile can take tens of seconds) and then prints ``{"ready": true}``;
until that line arrives the C++ side uses its greedy host fallback,
mirroring how tpu_render_cluster/master/tpu_batch.py degrades.

This replaces the reference's in-process scheduler math (reference:
master/src/cluster/strategies.rs:16-405) with an out-of-process TPU solve;
only frame->worker assignments travel back over the pipe (SURVEY.md §5.8).
"""

from __future__ import annotations

import json
import sys


def main() -> int:
    import numpy as np

    from tpu_render_cluster.ops.assignment import (
        greedy_fallback_count,
        reset_greedy_fallback_count,
        solve_assignment,
    )

    # Warm the solver across shape buckets so scheduling ticks never absorb
    # an XLA compile: solve_assignment pads to square power-of-two buckets
    # (ops/assignment.py _next_bucket), so one solve per bucket caches the
    # compiled kernel. 8..128 covers up to 128 simultaneous queue slots.
    for bucket in (8, 16, 32, 64, 128):
        warmup = np.ones((bucket // 2, bucket), dtype=np.float32)
        solve_assignment(warmup)
    # Warmup solves don't count toward the job's fallback telemetry.
    reset_greedy_fallback_count()
    sys.stdout.write(json.dumps({"ready": True}) + "\n")
    sys.stdout.flush()

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            request = json.loads(line)
        except json.JSONDecodeError:
            sys.stdout.write(json.dumps({"error": "malformed request"}) + "\n")
            sys.stdout.flush()
            continue
        if request.get("op") == "exit":
            break
        request_id = request.get("id")
        cost = np.asarray(request.get("cost", []), dtype=np.float32)
        if cost.ndim != 2 or cost.size == 0:
            sys.stdout.write(json.dumps({"id": request_id, "assignment": []}) + "\n")
            sys.stdout.flush()
            continue
        assignment = solve_assignment(cost)
        # Cumulative non-convergence fallback count rides every response so
        # the C++ master can surface it in its processed-results scheduler
        # section without an extra request.
        sys.stdout.write(
            json.dumps(
                {
                    "id": request_id,
                    "assignment": [int(s) for s in assignment],
                    "greedy_fallbacks": greedy_fallback_count(),
                }
            )
            + "\n"
        )
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
