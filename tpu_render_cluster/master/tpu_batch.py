"""The `tpu-batch` scheduler: cost-matrix assignment solved on TPU.

New in this build (the north-star scheduler from BASELINE.md): each
scheduling tick gathers every worker's queue deficit into a pool of *slots*
(worker x queue position), predicts the completion time of putting a frame
into each slot from a joint cost model — a per-worker speed EMA times a
per-frame complexity factor interpolated over frame index (scenes are
animated, so cost varies smoothly with the frame) — and solves the
frame->slot min-cost assignment with the JAX auction kernel
(tpu_render_cluster/ops/assignment.py). An opportunity-cost gate drops
assignments the rest of the cluster could finish sooner than the chosen
slot, which keeps the job tail off the slowest worker. Assignments are
issued as the same ``request_frame-queue_add`` RPCs the reference
strategies use, so workers can't tell the schedulers apart.

When the pending pool runs dry it degrades to dynamic-strategy stealing
(reference semantics: master/src/cluster/strategies.rs:250-405), which also
covers the cold-start case where no frame-time history exists yet.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Sequence

import numpy as np

from tpu_render_cluster.jobs.models import (
    BlenderJob,
    DynamicStrategyOptions,
    TpuBatchStrategyOptions,
)
from tpu_render_cluster.jobs.tiles import WorkUnit, unit_pixel_fraction
from tpu_render_cluster.master.state import ClusterManagerState
from tpu_render_cluster.master.strategies import (
    check_job_failed,
    find_busiest_worker_and_frame_to_steal,
    steal_frame,
)

# The model classes grew into a first-class subsystem (offline training,
# persistence, the shared online service) and moved to sched/cost_model.py;
# re-exported here because this was their original definition site.
from tpu_render_cluster.sched.cost_model import (  # noqa: F401 (re-exports)
    DEFAULT_FRAME_TIME_GUESS,
    CostModelService,
    FrameComplexityModel,
    JointCostModel,
    WorkerCostModel,
    load_cost_model_from_env,
)
from tpu_render_cluster.utils.cancellation import CancellationToken

if TYPE_CHECKING:
    from tpu_render_cluster.master.worker_handle import WorkerHandle

logger = logging.getLogger(__name__)

TPU_BATCH_TICK = 0.05
# Each worker's queue is sized to cover this many seconds of predicted work
# (bounded below by 1 and above by RATE_TARGET_CAP), so a fast worker's
# queue holds several ticks of frames while a slow worker holds one or two.
# A uniform target starves fast workers: they drain the whole queue within
# a tick and idle until the next one.
RATE_TARGET_LOOKAHEAD = 0.25
RATE_TARGET_CAP = 16
# Hard bound on slots considered per tick: keeps the auction matrix inside
# the pre-compiled bucket sizes (ClusterManager warms up to this many) and
# bounds per-tick work on huge clusters; later workers simply get topped up
# on the next tick.
MAX_SLOTS_PER_TICK = 128


def unit_complexity_map(
    units: Sequence[WorkUnit],
    complexity_model: FrameComplexityModel,
    tile_grid: tuple[int, int] | None,
) -> dict[WorkUnit, float]:
    """Per-UNIT complexity: the frame's predicted factor scaled by the
    unit's pixel fraction.

    The complexity model stays keyed by FRAME index (tiles of one frame
    share the scene, so they share the frame's factor), but a quarter-
    frame tile is a quarter of the work — pricing a ``(frame, tile)``
    unit at the whole frame's cost uniformly overpriced tiled jobs (and
    distorted the makespan gate's unit arithmetic).
    """
    frame_predictions = complexity_model.predict_many(
        sorted({unit.frame_index for unit in units})
    )
    return {
        unit: frame_predictions[unit.frame_index]
        * unit_pixel_fraction(unit, tile_grid)
        for unit in units
    }


def build_cost_matrix(
    frames: Sequence[int],
    slots: Sequence[tuple["WorkerHandle", int]],
    cost_model: WorkerCostModel,
    *,
    frame_complexity: dict[int, float] | None = None,
) -> np.ndarray:
    """cost[i, j] = predicted completion time of frame i in slot j.

    A slot is (worker, position-in-queue): completion = (current queue length
    + position + 1) * predicted frame time on that worker, scaled by the
    frame's complexity factor when a per-frame predictor is available.
    """
    cost = np.zeros((len(frames), len(slots)), dtype=np.float32)
    slot_base = np.array(
        [
            (len(worker.queue) + position + 1) * cost_model.predict(worker.worker_id)
            for worker, position in slots
        ],
        dtype=np.float32,
    )
    for i, frame_index in enumerate(frames):
        scale = 1.0
        if frame_complexity is not None:
            scale = frame_complexity.get(frame_index, 1.0)
        cost[i] = slot_base * scale
    return cost


def scaled_slot_cap(worker_count: int) -> int:
    """Per-tick slot budget for a cluster of ``worker_count`` workers.

    A fixed cap becomes the assignment throughput ceiling on many-worker
    clusters. Shared by the tick loop (which clamps it to the warmed
    auction buckets) and the ClusterManager's barrier-time warmup (which
    must compile buckets covering it, or warmed_max_slots() clamps the
    tick right back to the fixed cap)."""
    return max(MAX_SLOTS_PER_TICK, 2 * max(1, worker_count))


def makespan_horizon(
    rest_units: float, others_rate: float, fastest_speed: float, frame_complexity: float
) -> float:
    """Latest acceptable completion time for a candidate assignment.

    ``rest_units`` is everything the REST of the cluster still has to chew
    through (pending pool + other queues, in complexity units) and
    ``others_rate`` their combined rate; an assignment whose predicted
    completion exceeds this drain window (plus one fastest-worker frame of
    slack) would make its worker the job's tail, so the gate skips it.
    Pure so the gate's decision structure is unit-testable without a
    cluster (tests/test_tpu_batch_model.py).
    """
    rest_seconds = rest_units / others_rate if others_rate > 0 else float("inf")
    return rest_seconds + fastest_speed * frame_complexity


def _as_dynamic_options(options: TpuBatchStrategyOptions) -> DynamicStrategyOptions:
    return DynamicStrategyOptions(
        target_queue_size=options.target_queue_size,
        min_queue_size_to_steal=options.min_queue_size_to_steal,
        min_seconds_before_resteal_to_elsewhere=options.min_seconds_before_resteal_to_elsewhere,
        min_seconds_before_resteal_to_original_worker=options.min_seconds_before_resteal_to_original_worker,
    )


async def tpu_batch_strategy(
    job: BlenderJob,
    state: ClusterManagerState,
    workers_fn,
    cancellation: CancellationToken,
    options: TpuBatchStrategyOptions,
    *,
    cost_service: CostModelService | None = None,
) -> None:
    from tpu_render_cluster.ops.assignment import solve_assignment

    # The model is shared master state now (sched/cost_model.py): the
    # manager passes its service so the speculation loop and a persisted
    # TRC_COST_MODEL snapshot warm-start the auction; standalone callers
    # (tests) still get a private cold instance.
    if cost_service is None:
        cost_service = CostModelService(
            load_cost_model_from_env(), alpha=options.cost_ema_alpha
        )
    cost_model = cost_service.model
    scene = CostModelService.scene_key(job)
    complexity_model = cost_model.complexity_model(scene)
    # This loop runs one job: every completion observation is priced
    # against it (the service keys scene + tile grid off the job).
    job_for = lambda _job_name: job  # noqa: E731
    dynamic_options = _as_dynamic_options(options)
    starved_since: float | None = None  # first fully-gated tick of a streak
    # A tiled job's pending pool is counted in UNITS; the model-wide mean
    # complexity is frame-equivalent, so pool work scales by the fraction.
    pool_unit_fraction = 1.0 / job.tiles_per_frame()

    while not cancellation.is_cancelled():
        if state.all_frames_finished():
            return
        check_job_failed(state)
        workers = [w for w in workers_fn() if not w.is_dead]
        if not workers:
            await asyncio.sleep(TPU_BATCH_TICK)
            continue

        # Feed the cost model with fresh completions (the shared service
        # consumes each observation exactly once, normalizes tile pixel
        # fractions, and accounts prediction error).
        cost_service.ingest(workers, job_for)

        # Collect slots from queue deficits, with per-worker targets scaled
        # to each worker's predicted rate (uniform targets until history
        # arrives — the cold-start case falls back to eager-coarse shape).
        # Units are (frame, tile) under a tile grid; the complexity model
        # stays keyed by FRAME index (tiles of one frame share the scene,
        # so they share the frame's complexity factor), scaled per unit by
        # its pixel fraction (unit_complexity_map).
        upcoming = state.pending_units(limit=2 * RATE_TARGET_CAP)
        upcoming_complexity = unit_complexity_map(
            upcoming, complexity_model, job.tile_grid
        )
        batch_mean_complexity = (
            float(np.mean(list(upcoming_complexity.values())))
            if upcoming
            else 1.0
        )
        # Slots are interleaved breadth-first by position (every worker's
        # front slot before any second slot): the slot-cap truncation below
        # must never hide an idle worker's front slot behind another
        # worker's deep queue positions — at the job tail that starves the
        # scheduler (only deep slots survive, the makespan gate rejects
        # every assignment, and the job hangs with frames pending).
        deficits: list[tuple["WorkerHandle", int]] = []
        for worker in workers:
            if cost_model.worker_speed.has_history(worker.worker_id):
                frame_seconds = max(
                    1e-6,
                    cost_model.worker_speed.predict(worker.worker_id)
                    * batch_mean_complexity,
                )
                # The configured target is a floor: a worker must always
                # hold at least one buffered frame beyond the one it is
                # rendering, or it idles for a full master round-trip after
                # every frame (utilization collapses to ~50% on fast
                # backends). Rate-scaling only ever deepens the queue for
                # workers that drain faster than the lookahead window.
                target = min(
                    max(
                        options.target_queue_size,
                        int(np.ceil(RATE_TARGET_LOOKAHEAD / frame_seconds)),
                    ),
                    max(options.target_queue_size, RATE_TARGET_CAP),
                )
            else:
                # Cold start: commit conservatively until the model has seen
                # this worker render — dumping a full target_queue_size onto
                # a worker of unknown speed parks frames on what may be the
                # slowest node, and short jobs never recover via stealing.
                target = min(2, options.target_queue_size)
            deficits.append((worker, max(0, target - len(worker.queue))))
        slots: list[tuple["WorkerHandle", int]] = []
        max_deficit = max((d for _, d in deficits), default=0)
        for position in range(max_deficit):
            for worker, deficit in deficits:
                if position < deficit:
                    slots.append((worker, position))
        # Stay within pre-compiled auction buckets (late-joining workers can
        # push the slot count past what the barrier-time warmup covered);
        # excess workers are topped up on later ticks.
        from tpu_render_cluster.ops.assignment import warmed_max_slots

        # Scale the per-tick budget with the cluster (C++ twin: slot_cap
        # in tpu_batch_loop). Warmed auction buckets still bound it: an
        # unwarmed size would compile mid-job.
        slot_cap = scaled_slot_cap(len(workers))
        if 0 < warmed_max_slots() < slot_cap:
            slot_cap = warmed_max_slots()
        del slots[slot_cap:]

        if slots:
            units = state.pending_units(limit=len(slots))
            if units:
                complexity = unit_complexity_map(
                    units, complexity_model, job.tile_grid
                )
                cost = build_cost_matrix(
                    units,
                    slots,
                    cost_model.worker_speed,
                    frame_complexity=complexity,
                )
                assignment = solve_assignment(cost)

                # Makespan-balance gate: skip an assignment whose predicted
                # completion exceeds the time the OTHER workers need to
                # drain the rest of the pool — queueing it there can only
                # lengthen the makespan. A slow worker still receives
                # frames it can finish within the others' drain window
                # (keeping tail delay low), but never a frame that would
                # make it the job's tail. The fastest worker's own front
                # slot always passes (completion == slack term), so the job
                # always makes progress.
                speeds = {
                    worker.worker_id: cost_model.worker_speed.predict(worker.worker_id)
                    for worker in workers
                }
                cluster_rate = sum(1.0 / max(1e-6, s) for s in speeds.values())
                # Work is measured in complexity units throughout: the pool
                # via the model-wide mean (pools can be 14400 frames — too
                # many to predict individually each tick), queues via the
                # sum of per-frame predictions (queues are small), and the
                # candidate frame via its own prediction — so the
                # subtraction in rest_units below is unit-consistent.
                pool_units = (
                    state.pending_count()
                    * complexity_model.mean_observed()
                    * pool_unit_fraction
                )
                mirrored_complexity = unit_complexity_map(
                    [
                        f.unit
                        for worker in workers
                        for f in worker.queue.all_frames()
                    ],
                    complexity_model,
                    job.tile_grid,
                )
                queued_units = {
                    worker.worker_id: sum(
                        mirrored_complexity[f.unit]
                        for f in worker.queue.all_frames()
                    )
                    for worker in workers
                }
                total_queued_units = sum(queued_units.values())
                fastest_speed = min(speeds.values())

                # Claim frames synchronously, then issue the add-RPCs
                # concurrently (the reference queues serially in the tick
                # loop; batching the RPCs keeps tick latency flat as the
                # cluster grows).
                async def assign(unit, worker: "WorkerHandle") -> None:
                    try:
                        await worker.queue_frame(job, unit)
                    except Exception as e:  # noqa: BLE001
                        logger.warning(
                            "tpu-batch: failed to queue unit %s on %08x: %s",
                            unit.label,
                            worker.worker_id,
                            e,
                        )
                        state.return_frame_to_pending(unit)

                tasks = []
                for i, unit in enumerate(units):
                    worker, _position = slots[int(assignment[i])]
                    others_rate = cluster_rate - 1.0 / max(
                        1e-6, speeds[worker.worker_id]
                    )
                    # Everything the rest of the cluster still has to chew
                    # through: the pending pool plus their own queues.
                    rest_units = max(
                        0.0, pool_units - complexity[unit]
                    ) + (total_queued_units - queued_units[worker.worker_id])
                    horizon = makespan_horizon(
                        rest_units, others_rate, fastest_speed, complexity[unit]
                    )
                    if cost[i, int(assignment[i])] > horizon:
                        continue  # leave pending; a better slot will open
                    state.mark_frame_as_queued(unit, worker.worker_id, time.time())
                    tasks.append(assign(unit, worker))
                if not tasks and units:
                    # Forced progress: the gate's invariant is that the
                    # fastest worker's front slot always passes, but the
                    # auction may return an epsilon-suboptimal matching
                    # that never proposes that pair — gating the whole
                    # tick, every tick (observed in the C++ master at the
                    # tail of a 14400f x 40w run). Queue the cheapest
                    # frame on the GLOBALLY fastest worker (the one the
                    # invariant is about — cannot lengthen the makespan).
                    # When that worker's queue is full the gate may be
                    # right to wait for it to drain, so a slower worker
                    # is only settled for after the starvation persists —
                    # transient gate rejections stay respected.
                    if starved_since is None:
                        starved_since = time.time()
                    eligible = [
                        w for w in workers
                        if len(w.queue) < max(1, options.target_queue_size)
                    ]
                    if eligible:
                        fastest = min(
                            eligible, key=lambda w: speeds[w.worker_id]
                        )
                        fastest_overall = min(
                            workers, key=lambda w: speeds[w.worker_id]
                        )
                        if (
                            fastest is fastest_overall
                            or time.time() - starved_since > 1.0
                        ):
                            unit = min(units, key=lambda u: complexity[u])
                            state.mark_frame_as_queued(
                                unit, fastest.worker_id, time.time()
                            )
                            tasks.append(assign(unit, fastest))
                if tasks:
                    # The streak is CONSECUTIVE fully-gated ticks only; any
                    # tick that queues work (and, below, any tick with
                    # nothing to assign) resets it — a stale timestamp from
                    # an earlier streak must not let the fallback fire
                    # instantly and park a tail frame on a slow worker.
                    starved_since = None
                await asyncio.gather(*tasks)
                await asyncio.sleep(TPU_BATCH_TICK)
                continue

            starved_since = None
            # Pending pool dry -> steal like the dynamic strategy.
            workers_sorted = sorted(workers, key=lambda w: len(w.queue))
            for thief in workers_sorted:
                if len(thief.queue) >= options.target_queue_size:
                    continue
                found = find_busiest_worker_and_frame_to_steal(
                    thief, workers_sorted, dynamic_options
                )
                if found is None:
                    break
                victim, frame = found
                await steal_frame(job, state, thief, victim, frame.unit)

        if not slots:
            starved_since = None  # no slots this tick: not a gated streak
        await asyncio.sleep(TPU_BATCH_TICK)
