"""The `tpu-batch` scheduler: cost-matrix assignment solved on TPU.

New in this build (the north-star scheduler from BASELINE.md): each
scheduling tick gathers every worker's queue deficit into a pool of *slots*
(worker x queue position), predicts the completion time of putting a frame
into each slot from a per-worker EMA of observed frame times, and solves the
frame->slot min-cost assignment with the JAX auction kernel
(tpu_render_cluster/ops/assignment.py). Assignments are issued as the same
``request_frame-queue_add`` RPCs the reference strategies use, so workers
can't tell the schedulers apart.

When the pending pool runs dry it degrades to dynamic-strategy stealing
(reference semantics: master/src/cluster/strategies.rs:250-405), which also
covers the cold-start case where no frame-time history exists yet.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Sequence

import numpy as np

from tpu_render_cluster.jobs.models import (
    BlenderJob,
    DynamicStrategyOptions,
    TpuBatchStrategyOptions,
)
from tpu_render_cluster.master.state import ClusterManagerState
from tpu_render_cluster.master.strategies import (
    find_busiest_worker_and_frame_to_steal,
    steal_frame,
)
from tpu_render_cluster.utils.cancellation import CancellationToken

if TYPE_CHECKING:
    from tpu_render_cluster.master.worker_handle import WorkerHandle

logger = logging.getLogger(__name__)

TPU_BATCH_TICK = 0.1
DEFAULT_FRAME_TIME_GUESS = 5.0  # seconds, until history arrives


class WorkerCostModel:
    """Per-worker EMA frame-time predictor fed by finished events."""

    def __init__(self, alpha: float) -> None:
        self.alpha = alpha
        self._ema: dict[int, float] = {}

    def observe(self, worker_id: int, frame_seconds: float) -> None:
        previous = self._ema.get(worker_id)
        if previous is None:
            self._ema[worker_id] = frame_seconds
        else:
            self._ema[worker_id] = (
                self.alpha * frame_seconds + (1 - self.alpha) * previous
            )

    def predict(self, worker_id: int) -> float:
        if self._ema:
            default = float(np.median(list(self._ema.values())))
        else:
            default = DEFAULT_FRAME_TIME_GUESS
        return self._ema.get(worker_id, default)


def build_cost_matrix(
    frames: Sequence[int],
    slots: Sequence[tuple["WorkerHandle", int]],
    cost_model: WorkerCostModel,
    *,
    frame_complexity: dict[int, float] | None = None,
) -> np.ndarray:
    """cost[i, j] = predicted completion time of frame i in slot j.

    A slot is (worker, position-in-queue): completion = (current queue length
    + position + 1) * predicted frame time on that worker, scaled by the
    frame's complexity factor when a per-frame predictor is available.
    """
    cost = np.zeros((len(frames), len(slots)), dtype=np.float32)
    slot_base = np.array(
        [
            (len(worker.queue) + position + 1) * cost_model.predict(worker.worker_id)
            for worker, position in slots
        ],
        dtype=np.float32,
    )
    for i, frame_index in enumerate(frames):
        scale = 1.0
        if frame_complexity is not None:
            scale = frame_complexity.get(frame_index, 1.0)
        cost[i] = slot_base * scale
    return cost


def _as_dynamic_options(options: TpuBatchStrategyOptions) -> DynamicStrategyOptions:
    return DynamicStrategyOptions(
        target_queue_size=options.target_queue_size,
        min_queue_size_to_steal=options.min_queue_size_to_steal,
        min_seconds_before_resteal_to_elsewhere=options.min_seconds_before_resteal_to_elsewhere,
        min_seconds_before_resteal_to_original_worker=options.min_seconds_before_resteal_to_original_worker,
    )


async def tpu_batch_strategy(
    job: BlenderJob,
    state: ClusterManagerState,
    workers_fn,
    cancellation: CancellationToken,
    options: TpuBatchStrategyOptions,
) -> None:
    from tpu_render_cluster.ops.assignment import solve_assignment

    cost_model = WorkerCostModel(options.cost_ema_alpha)
    dynamic_options = _as_dynamic_options(options)
    observed_frames: set[tuple[int, int]] = set()

    while not cancellation.is_cancelled():
        if state.all_frames_finished():
            return
        workers = [w for w in workers_fn() if not w.is_dead]
        if not workers:
            await asyncio.sleep(TPU_BATCH_TICK)
            continue

        # Feed the cost model with fresh completions.
        for worker in workers:
            for frame_index, seconds in worker.drain_completion_observations():
                key = (worker.worker_id, frame_index)
                if key not in observed_frames:
                    observed_frames.add(key)
                    cost_model.observe(worker.worker_id, seconds)

        # Collect slots from queue deficits.
        slots: list[tuple["WorkerHandle", int]] = []
        for worker in workers:
            deficit = options.target_queue_size - len(worker.queue)
            for position in range(max(0, deficit)):
                slots.append((worker, position))

        if slots:
            frames = state.pending_frames(limit=len(slots))
            if frames:
                cost = build_cost_matrix(frames, slots, cost_model)
                assignment = solve_assignment(cost)
                # Claim frames synchronously, then issue the add-RPCs
                # concurrently (the reference queues serially in the tick
                # loop; batching the RPCs keeps tick latency flat as the
                # cluster grows).
                async def assign(frame_index: int, worker: "WorkerHandle") -> None:
                    try:
                        await worker.queue_frame(job, frame_index)
                    except Exception as e:  # noqa: BLE001
                        logger.warning(
                            "tpu-batch: failed to queue frame %d on %08x: %s",
                            frame_index,
                            worker.worker_id,
                            e,
                        )
                        state.return_frame_to_pending(frame_index)

                tasks = []
                for i, frame_index in enumerate(frames):
                    worker, _position = slots[int(assignment[i])]
                    state.mark_frame_as_queued(frame_index, worker.worker_id, time.time())
                    tasks.append(assign(frame_index, worker))
                await asyncio.gather(*tasks)
                await asyncio.sleep(TPU_BATCH_TICK)
                continue

            # Pending pool dry -> steal like the dynamic strategy.
            workers_sorted = sorted(workers, key=lambda w: len(w.queue))
            for thief in workers_sorted:
                if len(thief.queue) >= options.target_queue_size:
                    continue
                found = find_busiest_worker_and_frame_to_steal(
                    thief, workers_sorted, dynamic_options
                )
                if found is None:
                    break
                victim, frame = found
                await steal_frame(job, state, thief, victim, frame.frame_index)

        await asyncio.sleep(TPU_BATCH_TICK)
