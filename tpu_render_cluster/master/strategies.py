"""Frame-distribution strategies (the scheduler).

Behavioral contract from the reference (master/src/cluster/strategies.rs):

- **naive-fine** (strategies.rs:16-68): 50 ms tick; any worker with an empty
  queue receives exactly one pending frame.
- **eager-naive-coarse** (strategies.rs:70-150): 100 ms tick; every worker's
  queue is topped up to ``target_queue_size``.
- **dynamic** (strategies.rs:155-405): 50 ms tick; workers sorted by queue
  size ascending; each below-target worker gets one pending frame, or — when
  the pending pool is dry — steals one from the busiest worker. The steal
  candidate skips the first ``min_queue_size_to_steal`` entries (nearest to
  rendering), respects both anti-thrash resteal timers, and prefers the
  longest-queued frame; remove-vs-render races (``already-rendering`` /
  ``already-finished``) are tolerated by skipping the steal.

The selection helpers are pure functions over the queue mirrors so they are
unit-testable without a cluster (the reference never had such tests —
SURVEY.md §4).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Sequence

from tpu_render_cluster.jobs.models import (
    BlenderJob,
    DynamicStrategyOptions,
)
from tpu_render_cluster.jobs.tiles import WorkUnit
from tpu_render_cluster.master.queue_mirror import FrameOnWorker
from tpu_render_cluster.master.state import ClusterManagerState, FrameStatus
from tpu_render_cluster.protocol import messages as pm
from tpu_render_cluster.utils.cancellation import CancellationToken

if TYPE_CHECKING:
    from tpu_render_cluster.master.worker_handle import WorkerHandle

logger = logging.getLogger(__name__)

NAIVE_FINE_TICK = 0.05
EAGER_COARSE_TICK = 0.1
DYNAMIC_TICK = 0.05


# ---------------------------------------------------------------------------
# Pure steal-candidate selection (reference: strategies.rs:155-248)


def select_best_frame_to_steal(
    thief_worker_id: int,
    victim_queue: Sequence[FrameOnWorker],
    options: DynamicStrategyOptions,
    *,
    now: float | None = None,
) -> FrameOnWorker | None:
    """Pick the steal candidate from a victim's queue mirror.

    ``victim_queue`` must be the not-yet-rendering frames in queue order.
    Returns the oldest eligible frame at position >= ``min_queue_size_to_steal``,
    where eligibility requires the frame to have sat on the victim for at
    least the resteal-to-elsewhere timer (or the longer resteal-to-original
    timer when the thief is the worker it was originally stolen from).
    """
    now = time.time() if now is None else now
    best: FrameOnWorker | None = None
    for frame in victim_queue[options.min_queue_size_to_steal :]:
        since_queued = now - frame.queued_at
        if frame.stolen_from is not None and frame.stolen_from == thief_worker_id:
            if since_queued >= options.min_seconds_before_resteal_to_original_worker:
                if best is None or frame.queued_at < best.queued_at:
                    best = frame
            continue
        if since_queued >= options.min_seconds_before_resteal_to_elsewhere:
            if best is None or frame.queued_at < best.queued_at:
                best = frame
    return best


def find_busiest_worker_and_frame_to_steal(
    thief: "WorkerHandle",
    workers: Sequence["WorkerHandle"],
    options: DynamicStrategyOptions,
    *,
    now: float | None = None,
) -> tuple["WorkerHandle", FrameOnWorker] | None:
    """Find (victim, frame) — the biggest queue holding an eligible frame.

    Only queues strictly larger than ``min_queue_size_to_steal`` are
    considered (reference: strategies.rs:193-248).
    """
    best: tuple["WorkerHandle", int, FrameOnWorker] | None = None
    for victim in workers:
        if victim.worker_id == thief.worker_id or victim.is_dead:
            continue
        queue_size = len(victim.queue)
        if queue_size <= options.min_queue_size_to_steal:
            continue
        if best is not None and queue_size <= best[1]:
            continue
        candidate = select_best_frame_to_steal(
            thief.worker_id, victim.queue.queued_frames_in_order(), options, now=now
        )
        if candidate is not None:
            best = (victim, queue_size, candidate)
    if best is None:
        return None
    return best[0], best[2]


# ---------------------------------------------------------------------------
# Strategy loops


def check_job_failed(state: ClusterManagerState) -> None:
    """Raise when the job crossed its unit-error budget — called once
    per tick by every strategy loop so a deterministically-failing unit
    ends the job with a clear error instead of an endless redispatch
    spin (the scheduler's loop cancels the job instead of raising)."""
    if state.failed_reason is not None:
        raise RuntimeError(f"Job failed: {state.failed_reason}")


async def dispatch_one_pending(
    worker: "WorkerHandle",
    job: BlenderJob,
    state: ClusterManagerState,
    *,
    job_id: str | None = None,
) -> bool:
    """Claim + RPC-dispatch one pending frame of ``state`` onto ``worker``.

    The shared dispatch primitive: every single-job strategy and the
    multi-job fair-share loop (sched/manager.py) go through here, so the
    claim-before-RPC double-queue guard and the failure-requeue path have
    exactly one definition. ``job_id`` is the scheduler's submission id,
    piggybacked on the wire (None on the single-job path).
    """
    unit = state.next_pending_unit()
    if unit is None:
        return False
    # Claim immediately so concurrent assignment in the same tick can't
    # double-queue the unit, then confirm via RPC.
    state.mark_frame_as_queued(unit, worker.worker_id, time.time())
    try:
        await worker.queue_frame(job, unit, job_id=job_id)
    except Exception as e:  # noqa: BLE001 - worker failure mid-RPC
        logger.warning(
            "Failed to queue unit %s on %08x: %s", unit.label, worker.worker_id, e
        )
        state.return_frame_to_pending(unit)
        return False
    return True


async def _queue_one_pending(
    worker: "WorkerHandle", job: BlenderJob, state: ClusterManagerState
) -> bool:
    return await dispatch_one_pending(worker, job, state)


async def naive_fine_strategy(
    job: BlenderJob,
    state: ClusterManagerState,
    workers_fn,
    cancellation: CancellationToken,
) -> None:
    """One frame at a time per idle worker (reference: strategies.rs:16-68)."""
    while not cancellation.is_cancelled():
        if state.all_frames_finished():
            return
        check_job_failed(state)
        for worker in workers_fn():
            if worker.is_dead or not worker.has_empty_queue():
                continue
            await _queue_one_pending(worker, job, state)
        await asyncio.sleep(NAIVE_FINE_TICK)


async def eager_naive_coarse_strategy(
    job: BlenderJob,
    state: ClusterManagerState,
    workers_fn,
    cancellation: CancellationToken,
    target_queue_size: int,
) -> None:
    """Top every queue up to the target (reference: strategies.rs:70-150)."""
    while not cancellation.is_cancelled():
        if state.all_frames_finished():
            return
        check_job_failed(state)
        for worker in workers_fn():
            if worker.is_dead:
                continue
            deficit = target_queue_size - len(worker.queue)
            for _ in range(max(0, deficit)):
                if not await _queue_one_pending(worker, job, state):
                    break
        await asyncio.sleep(EAGER_COARSE_TICK)


async def dynamic_strategy(
    job: BlenderJob,
    state: ClusterManagerState,
    workers_fn,
    cancellation: CancellationToken,
    options: DynamicStrategyOptions,
) -> None:
    """Target-size top-up with work stealing (reference: strategies.rs:250-405)."""
    while not cancellation.is_cancelled():
        if state.all_frames_finished():
            return
        check_job_failed(state)
        workers = [w for w in workers_fn() if not w.is_dead]
        workers.sort(key=lambda w: len(w.queue))
        for worker in workers:
            if len(worker.queue) >= options.target_queue_size:
                continue
            if await _queue_one_pending(worker, job, state):
                continue
            # Pending pool dry: steal from the busiest worker.
            found = find_busiest_worker_and_frame_to_steal(worker, workers, options)
            if found is None:
                break  # nobody has anything stealable; next tick
            victim, frame = found
            await steal_frame(job, state, worker, victim, frame.unit)
        await asyncio.sleep(DYNAMIC_TICK)


async def steal_frame(
    job: BlenderJob,
    state: ClusterManagerState,
    thief: "WorkerHandle",
    victim: "WorkerHandle",
    unit: WorkUnit | int,
) -> bool:
    """Unqueue from victim, requeue on thief with provenance.

    Tolerates the distributed races exactly like the reference
    (strategies.rs:340-396): if the victim already started rendering or
    finished the unit, the steal silently aborts.
    """
    if isinstance(unit, int):
        unit = WorkUnit(unit)
    try:
        result = await victim.unqueue_frame(job.job_name, unit)
    except Exception as e:  # noqa: BLE001
        logger.warning("Steal unqueue RPC failed on %08x: %s", victim.worker_id, e)
        return False
    if result in (
        pm.FRAME_QUEUE_REMOVE_RESULT_ALREADY_RENDERING,
        pm.FRAME_QUEUE_REMOVE_RESULT_ALREADY_FINISHED,
    ):
        return False
    if result != pm.FRAME_QUEUE_REMOVE_RESULT_REMOVED:
        logger.warning("Steal unqueue errored on %08x: %s", victim.worker_id, result)
        return False
    # The victim can be marked dead between steal selection and here (the
    # unqueue RPC is an await point — heartbeat eviction interleaves).
    # Three cases, each leaving the frame pending-or-owned EXACTLY once:
    # - eviction already requeued it (record no longer points at the
    #   victim): do nothing — requeueing on the thief as well would put
    #   the frame in play twice;
    # - the victim died but eviction can no longer see the frame (the
    #   unqueue above removed it from the mirror eviction sweeps): requeue
    #   it HERE or it would be lost forever;
    # - victim alive and still owning the record: proceed with the steal.
    record = state.frames.get(unit)
    owned_by_victim = (
        record is not None
        and record.status is FrameStatus.QUEUED_ON_WORKER
        and record.worker_id == victim.worker_id
    )
    if victim.is_dead or not owned_by_victim:
        if owned_by_victim:
            state.return_frame_to_pending(unit)
        logger.warning(
            "Steal of unit %s aborted: victim %08x %s mid-steal.",
            unit.label,
            victim.worker_id,
            "died" if victim.is_dead else "lost the assignment",
        )
        return False
    victim.frames_stolen_count += 1
    try:
        await thief.queue_frame(job, unit, stolen_from=victim.worker_id)
    except Exception as e:  # noqa: BLE001
        logger.warning("Steal requeue failed on %08x: %s", thief.worker_id, e)
        state.return_frame_to_pending(unit)
        return False
    logger.debug(
        "Stole unit %s: %08x -> %08x", unit.label, victim.worker_id, thief.worker_id
    )
    return True


async def preempt_frame(
    job: BlenderJob,
    state: ClusterManagerState,
    victim: "WorkerHandle",
    unit: WorkUnit | int,
) -> bool:
    """Unqueue a not-yet-rendering frame back to its job's pending pool.

    The fair-share scheduler's preemption primitive: the first half of a
    steal (the same frame-queue-remove RPC with the same race tolerance —
    ``already-rendering`` / ``already-finished`` silently abort), except
    the frame returns to ITS OWN job's pending pool instead of moving to a
    thief, freeing the worker slot for an under-share job's next dispatch.
    """
    if isinstance(unit, int):
        unit = WorkUnit(unit)
    try:
        result = await victim.unqueue_frame(job.job_name, unit)
    except Exception as e:  # noqa: BLE001
        logger.warning(
            "Preempt unqueue RPC failed on %08x: %s", victim.worker_id, e
        )
        return False
    if result != pm.FRAME_QUEUE_REMOVE_RESULT_REMOVED:
        return False
    # Same await-point races as steal_frame: the victim may have died (or
    # the assignment moved) while the RPC was in flight. Requeue the unit
    # here exactly when this worker still owns its live assignment —
    # eviction already requeued it otherwise.
    record = state.frames.get(unit)
    owned_by_victim = (
        record is not None
        and record.status is FrameStatus.QUEUED_ON_WORKER
        and record.worker_id == victim.worker_id
    )
    if not owned_by_victim:
        logger.warning(
            "Preemption of unit %s aborted: victim %08x lost the "
            "assignment mid-RPC.",
            unit.label,
            victim.worker_id,
        )
        return False
    state.return_frame_to_pending(unit)
    return True


async def run_strategy(
    job: BlenderJob,
    state: ClusterManagerState,
    workers_fn,
    cancellation: CancellationToken,
    *,
    cost_service=None,
) -> None:
    """Dispatch on the job's strategy (reference: master/src/cluster/mod.rs:622-654).

    ``cost_service`` is the master's shared predictive cost model
    (sched/cost_model.CostModelService); the tpu-batch strategy prices
    its auction off it (warm-started from ``TRC_COST_MODEL`` snapshots
    and shared with the speculation loop). The reference strategies
    ignore it — their dispatch order is fixed by contract.
    """
    strategy = job.frame_distribution_strategy
    if strategy.strategy_type == "naive-fine":
        await naive_fine_strategy(job, state, workers_fn, cancellation)
    elif strategy.strategy_type == "eager-naive-coarse":
        await eager_naive_coarse_strategy(
            job, state, workers_fn, cancellation, strategy.eager.target_queue_size
        )
    elif strategy.strategy_type == "dynamic":
        await dynamic_strategy(job, state, workers_fn, cancellation, strategy.dynamic)
    elif strategy.strategy_type == "tpu-batch":
        from tpu_render_cluster.master.tpu_batch import tpu_batch_strategy

        await tpu_batch_strategy(
            job,
            state,
            workers_fn,
            cancellation,
            strategy.tpu_batch,
            cost_service=cost_service,
        )
    else:
        raise ValueError(f"Unknown strategy: {strategy.strategy_type}")
