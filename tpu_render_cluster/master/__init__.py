from tpu_render_cluster.master.cluster import ClusterManager

__all__ = ["ClusterManager"]
