"""Results persistence + human report.

Writes the two JSON artifacts the reference master produces
(reference: master/src/main.rs:26-272):

- ``<ts>_job-<name>_raw-trace.json`` — ``{job, master_trace, worker_traces}``
  with worker keys ``<worker_id:08x>-<addr>`` — the file the analysis suite
  consumes (analysis/core/models.py:251-313);
- ``<ts>_job-<name>_processed-results.json`` — per-worker ``WorkerPerformance``.

Timestamp prefix format matches the reference: ``%Y-%m-%d_%H-%M-%S`` local
time (master/src/main.rs:71-75).
"""

from __future__ import annotations

import json
import logging
from datetime import datetime
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.master_trace import MasterTrace
from tpu_render_cluster.traces.performance import WorkerPerformance
from tpu_render_cluster.traces.worker_trace import WorkerTrace

logger = logging.getLogger(__name__)


def run_file_prefix(start_time: datetime, job: BlenderJob) -> str:
    """The shared ``<timestamp>_job-<name>`` artifact prefix — public so
    the CLI's failure path can name obs artifacts BEFORE the raw trace
    (whose writer derives the same prefix) exists."""
    return (
        f"{start_time.strftime('%Y-%m-%d_%H-%M-%S')}"
        f"_job-{job.job_name.replace(' ', '_')}"
    )


# Internal alias kept for the writers below.
_file_prefix = run_file_prefix


def cost_model_snapshot_path(job: BlenderJob, output_directory: Path) -> Path:
    """Where a job's learned cost model is snapshotted.

    Deliberately UNtimestamped (unlike the trace artifacts): a resumed or
    re-run master of the same job must find the newest model without
    knowing the previous run's start time — each run overwrites it.
    """
    return (
        Path(output_directory)
        / f"job-{job.job_name.replace(' ', '_')}_cost-model.json"
    )


def save_cost_model(job: BlenderJob, output_directory: Path, model) -> Path | None:
    """Snapshot the run's learned ``JointCostModel`` next to the results
    (``sched/cost_model.save_model_snapshot`` semantics: cold models
    skipped, failures warn instead of failing the completed job)."""
    from tpu_render_cluster.sched.cost_model import save_model_snapshot

    return save_model_snapshot(
        model, cost_model_snapshot_path(job, output_directory)
    )


def save_raw_traces(
    start_time: datetime,
    job: BlenderJob,
    output_directory: Path,
    master_trace: MasterTrace,
    worker_traces: list[tuple[str, WorkerTrace]],
) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    path = output_directory / f"{_file_prefix(start_time, job)}_raw-trace.json"
    payload = {
        "job": job.to_dict(),
        "master_trace": master_trace.to_dict(),
        "worker_traces": {name: trace.to_dict() for name, trace in worker_traces},
    }
    path.write_text(json.dumps(payload, indent=2), encoding="utf-8")
    logger.info("Raw traces saved to %s", path)
    return path


def parse_worker_traces(
    worker_traces: list[tuple[str, WorkerTrace]],
) -> list[tuple[str, WorkerPerformance]]:
    return [
        (name, WorkerPerformance.from_worker_trace(trace))
        for name, trace in worker_traces
    ]


def save_processed_results(
    start_time: datetime,
    job: BlenderJob,
    output_directory: Path,
    worker_performance: list[tuple[str, WorkerPerformance]],
    scheduler_stats: dict | None = None,
) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    path = output_directory / f"{_file_prefix(start_time, job)}_processed-results.json"
    payload = {
        "worker_performance": {
            name: performance.to_dict() for name, performance in worker_performance
        }
    }
    if scheduler_stats is not None:
        # e.g. {"auction_greedy_fallbacks": 0} — how often the tpu-batch
        # auction degraded to the greedy host solve this job (the C++
        # master writes the same section; asserted zero in the northstar
        # populations).
        payload["scheduler"] = scheduler_stats
    path.write_text(json.dumps(payload, indent=2), encoding="utf-8")
    logger.info("Processed results saved to %s", path)
    return path


def print_results(
    master_trace: MasterTrace,
    worker_performance: list[tuple[str, WorkerPerformance]],
) -> str:
    """Per-worker + cumulative report (reference: master/src/main.rs:148-272)."""
    lines: list[str] = []
    lines.append("=" * 60)
    lines.append("Job complete.")
    lines.append(f"  Total job duration: {master_trace.job_duration():.2f} s")
    lines.append("")
    total_frames = 0
    for name, perf in worker_performance:
        total_frames += perf.total_frames_rendered
        lines.append(f"Worker {name}:")
        lines.append(f"  frames rendered : {perf.total_frames_rendered}")
        lines.append(f"  frames queued   : {perf.total_frames_queued}")
        lines.append(f"  frames stolen   : {perf.total_frames_stolen_from_queue}")
        lines.append(f"  reconnects      : {perf.total_times_reconnected}")
        lines.append(f"  total time      : {perf.total_time:.2f} s")
        lines.append(f"  reading time    : {perf.total_blend_file_reading_time:.2f} s")
        lines.append(f"  rendering time  : {perf.total_rendering_time:.2f} s")
        lines.append(f"  saving time     : {perf.total_image_saving_time:.2f} s")
        lines.append(f"  idle time       : {perf.total_idle_time:.2f} s")
        if perf.total_time > 0:
            utilization = 1.0 - perf.total_idle_time / perf.total_time
            lines.append(f"  utilization     : {utilization:.3f}")
        lines.append("")
    lines.append(f"Cumulative frames rendered: {total_frames}")
    duration = master_trace.job_duration()
    if duration > 0:
        lines.append(f"Throughput: {total_frames / duration:.3f} frames/s")
    lines.append("=" * 60)
    report = "\n".join(lines)
    print(report)
    return report
