"""Cluster manager state: the global frame table + worker registry.

Semantics follow the reference's ``ClusterManagerState`` frame status machine
(Pending -> QueuedOnWorker -> RenderingOnWorker -> Finished, with steal
transitions back to Queued — reference: master/src/cluster/state.rs:13-130),
but the data structures are scale-fixed: the reference linearly scans a
``Vec`` of 14 400 frames on every 50 ms tick (state.rs:63-80, flagged in
SURVEY.md §5.7); here pending frames live in a deque and finished frames in
a counter, making ``next_pending_frame``/``all_frames_finished`` O(1).
"""

from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass, field

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.protocol.messages import generate_trace_id


class FrameStatus(enum.Enum):
    PENDING = "pending"
    QUEUED_ON_WORKER = "queued"
    RENDERING_ON_WORKER = "rendering"
    FINISHED = "finished"


@dataclass
class FrameRecord:
    frame_index: int
    status: FrameStatus = FrameStatus.PENDING
    worker_id: int | None = None
    queued_at: float | None = None
    # Worker the frame was last stolen FROM (provenance for the
    # resteal-to-original-worker anti-thrash timer, reference:
    # master/src/cluster/state.rs:13-24, strategies.rs:155-191).
    stolen_from: int | None = None
    stolen_at: float | None = None


class ClusterManagerState:
    """Per-job frame table; single event loop, so no locking is needed.

    One instance per RUNNING job: the single-job master owns exactly one,
    the multi-job scheduler (sched/manager.py) one per admitted job, with
    WorkerHandle routing worker events to the right instance by the
    reference ``job_name`` field every event already carries.
    """

    def __init__(self, job: BlenderJob) -> None:
        self.job = job
        # One trace id per job run: every assignment span and worker echo
        # carries it, so artifacts from different runs never alias
        # (protocol/messages.py TraceContext rides on this).
        self.trace_id: int = generate_trace_id()
        # Scheduler job id (sched/ only; None on the single-job path).
        # Guards job-name reuse: a late result stamped with a PREVIOUS
        # submission's job_id must not count against a new job that
        # happens to share the name.
        self.sched_job_id: str | None = None
        self.frames: dict[int, FrameRecord] = {
            index: FrameRecord(index) for index in job.frame_indices()
        }
        self._pending: deque[int] = deque(job.frame_indices())
        self._finished_count = 0
        # Per-job exactly-once ledger, updated by WorkerHandle at the same
        # points as the global ``master_*_results_total`` counters so the
        # PR-4 chaos invariant (ok - duplicates == frames_total) can be
        # audited PER JOB when several share the worker pool.
        self.ledger: dict[str, int] = {
            "ok_results": 0,
            "errored_results": 0,
            "duplicate_results": 0,
            "late_results": 0,
            "stale_results": 0,
        }

    # -- queries -----------------------------------------------------------

    def next_pending_frame(self) -> int | None:
        """Peek the next pending frame index (O(1))."""
        while self._pending:
            index = self._pending[0]
            if self.frames[index].status is FrameStatus.PENDING:
                return index
            self._pending.popleft()  # stale entry
        return None

    def all_frames_finished(self) -> bool:
        return self._finished_count >= len(self.frames)

    def finished_count(self) -> int:
        return self._finished_count

    def pending_count(self) -> int:
        return sum(
            1 for i in self._pending if self.frames[i].status is FrameStatus.PENDING
        )

    def in_flight_count(self) -> int:
        """Frames currently queued-on or rendering-on some worker — the
        quantity the fair-share scheduler meters per job."""
        return sum(
            1
            for record in self.frames.values()
            if record.status
            in (FrameStatus.QUEUED_ON_WORKER, FrameStatus.RENDERING_ON_WORKER)
        )

    def pending_frames(self, limit: int | None = None) -> list[int]:
        out = []
        for index in self._pending:
            if self.frames[index].status is FrameStatus.PENDING:
                out.append(index)
                if limit is not None and len(out) >= limit:
                    break
        return out

    # -- transitions -------------------------------------------------------

    def mark_frame_as_queued(
        self,
        frame_index: int,
        worker_id: int,
        queued_at: float,
        *,
        stolen_from: int | None = None,
        stolen_at: float | None = None,
    ) -> None:
        record = self.frames[frame_index]
        if record.status is FrameStatus.FINISHED:
            raise ValueError(f"BUG: frame {frame_index} is already finished.")
        record.status = FrameStatus.QUEUED_ON_WORKER
        record.worker_id = worker_id
        record.queued_at = queued_at
        if stolen_from is not None:
            record.stolen_from = stolen_from
            record.stolen_at = stolen_at
        if self._pending and self._pending[0] == frame_index:
            self._pending.popleft()

    def mark_frame_as_rendering(self, frame_index: int, worker_id: int) -> None:
        record = self.frames[frame_index]
        if record.status is FrameStatus.FINISHED:
            return  # late event after a race; harmless
        record.status = FrameStatus.RENDERING_ON_WORKER
        record.worker_id = worker_id

    def mark_frame_as_finished(self, frame_index: int) -> None:
        record = self.frames[frame_index]
        if record.status is FrameStatus.FINISHED:
            return
        record.status = FrameStatus.FINISHED
        self._finished_count += 1

    def return_frame_to_pending(self, frame_index: int) -> None:
        """Frame comes back to the pool (steal succeeded, render errored,
        or its worker died). Unlike the reference — where a dead worker's
        frames stay QueuedOnWorker forever (SURVEY.md §5.3) — this makes
        eviction recoverable. Idempotent: under fault races (an eviction
        and a failed dispatch both returning the same frame) the second
        call must not add a second pending entry."""
        record = self.frames[frame_index]
        if record.status in (FrameStatus.FINISHED, FrameStatus.PENDING):
            return
        record.status = FrameStatus.PENDING
        record.worker_id = None
        record.queued_at = None
        self._pending.append(frame_index)
