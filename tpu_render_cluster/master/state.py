"""Cluster manager state: the global work-unit table + worker registry.

Semantics follow the reference's ``ClusterManagerState`` frame status machine
(Pending -> QueuedOnWorker -> RenderingOnWorker -> Finished, with steal
transitions back to Queued — reference: master/src/cluster/state.rs:13-130),
but the data structures are scale-fixed: the reference linearly scans a
``Vec`` of 14 400 frames on every 50 ms tick (state.rs:63-80, flagged in
SURVEY.md §5.7); here pending units live in a deque and finished units in
a counter, making ``next_pending_unit``/``all_frames_finished`` O(1).

PR 7 extends the unit of distribution from a whole frame to
``WorkUnit(frame_index, tile)`` (jobs/tiles.py): for a tiled job every
frame splits into grid tiles that dispatch, steal, evict, and dedup
independently, and a per-frame ASSEMBLY ledger tracks which tiles have
landed so the frame-level result (the stitched image, the "frame done"
event) fires exactly once — when the last tile lands. Whole-frame jobs
(``tile is None``) behave exactly as before.
"""

from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.jobs.tiles import WorkUnit
from tpu_render_cluster.protocol.messages import generate_trace_id


class FrameStatus(enum.Enum):
    PENDING = "pending"
    QUEUED_ON_WORKER = "queued"
    RENDERING_ON_WORKER = "rendering"
    FINISHED = "finished"


@dataclass
class FrameRecord:
    unit: WorkUnit
    status: FrameStatus = FrameStatus.PENDING
    worker_id: int | None = None
    queued_at: float | None = None
    # Worker the unit was last stolen FROM (provenance for the
    # resteal-to-original-worker anti-thrash timer, reference:
    # master/src/cluster/state.rs:13-24, strategies.rs:155-191).
    stolen_from: int | None = None
    stolen_at: float | None = None
    # Errored results received for this unit across all its assignments.
    # A deterministic failure (a backend that cannot render the unit at
    # all) would otherwise requeue-and-error forever; the cap turns the
    # livelock into a job failure (worker_handle -> failed_reason).
    errored_count: int = 0

    @property
    def frame_index(self) -> int:
        return self.unit.frame_index

    @property
    def tile(self) -> int | None:
        return self.unit.tile


@dataclass
class SpeculationRecord:
    """One live speculative twin of an in-flight unit (master-internal).

    The PRIMARY assignment owns the frame record as usual; the TWIN is a
    byte-identical duplicate dispatch to a second worker, tracked only
    here (the wire and the C++ workers cannot tell a twin from any other
    assignment). ``winner_worker_id`` is stamped by the first accepted ok
    result — the dedup ledger absorbs the loser's copy — and the
    speculation loop (master/speculate.py) unqueues the loser and
    accounts the outcome.
    """

    unit: WorkUnit
    primary_worker_id: int
    twin_worker_id: int
    started_at: float
    predicted_primary_s: float
    predicted_twin_s: float
    winner_worker_id: int | None = None


class ClusterManagerState:
    """Per-job work-unit table; single event loop, so no locking is needed.

    One instance per RUNNING job: the single-job master owns exactly one,
    the multi-job scheduler (sched/manager.py) one per admitted job, with
    WorkerHandle routing worker events to the right instance by the
    reference ``job_name`` field every event already carries.
    """

    def __init__(self, job: BlenderJob) -> None:
        self.job = job
        # One trace id per job run: every assignment span and worker echo
        # carries it, so artifacts from different runs never alias
        # (protocol/messages.py TraceContext rides on this).
        self.trace_id: int = generate_trace_id()
        # Scheduler job id (sched/ only; None on the single-job path).
        # Guards job-name reuse: a late result stamped with a PREVIOUS
        # submission's job_id must not count against a new job that
        # happens to share the name.
        self.sched_job_id: str | None = None
        # Set when a unit exhausts its error budget: the strategy loops
        # surface it as a job failure (the scheduler cancels the job)
        # instead of spinning redispatch RPCs forever.
        self.failed_reason: str | None = None
        self.frames: dict[WorkUnit, FrameRecord] = {
            unit: FrameRecord(unit) for unit in job.work_units()
        }
        self._pending: deque[WorkUnit] = deque(job.work_units())
        self._finished_count = 0
        # Mutation counter, bumped by every frame transition (status OR
        # worker reassignment). The incremental WFQ (sched/wfq.py) keys
        # its per-job resync off this: a job whose version is unchanged
        # since the last tick cannot have changed demand, load, or the
        # worker placement its cost prediction depends on, so the tick
        # skips it entirely. Evictions, goodbyes, steals, late results,
        # and ledger replay all funnel through these transitions, so no
        # event source needs separate instrumentation.
        self.version: int = 0
        # O(1) mirrors of the status population. ``_pending_live`` counts
        # frames whose STATUS is PENDING (the deque may briefly hold
        # stale or duplicate entries; status is the truth);
        # ``_in_flight_units`` maps each QUEUED/RENDERING unit to the
        # worker currently holding it — exactly the set the cost model
        # prices for a job's in-flight load, without an O(frames) scan.
        self._pending_live: int = len(self.frames)
        self._in_flight_units: dict[WorkUnit, int] = {}
        # Per-job exactly-once ledger, updated by WorkerHandle at the same
        # points as the global ``master_*_results_total`` counters so the
        # PR-4 chaos invariant (ok - duplicates == units_total) can be
        # audited PER JOB when several share the worker pool.
        self.ledger: dict[str, int] = {
            "ok_results": 0,
            "errored_results": 0,
            "duplicate_results": 0,
            "late_results": 0,
            "stale_results": 0,
            # Results refused because they carry a PREVIOUS master
            # incarnation's epoch (ha/: the fencing half of failover).
            "stale_epoch_results": 0,
        }
        # Write-ahead ledger sinks (ha/ledger.py, wired by a ledger-backed
        # master AFTER replay application so replayed units are not
        # re-journaled): called exactly once per unit/frame, on the same
        # transitions the in-memory ledger meters.
        self.on_unit_finished = None
        self.on_frame_assembled = None
        # Per-frame assembly ledger (tiled jobs): frame -> the set of tile
        # indices whose units reached FINISHED. A frame is assembly-ready
        # when the set reaches ``tiles_per_frame`` — each tile lands in it
        # exactly once because ``mark_frame_as_finished`` transitions each
        # unit to FINISHED exactly once (duplicates are absorbed upstream).
        self._tiles_per_frame = job.tiles_per_frame()
        self._assembly: dict[int, set[int]] = {}
        self.frames_assembled = 0
        # Live speculative twins keyed by unit (master/speculate.py): a
        # unit under speculation is dispatched on TWO workers at once;
        # first accepted ok result wins through the dedup ledger.
        self.speculations: dict[WorkUnit, SpeculationRecord] = {}
        # Per-unit queue-to-result latency of each unit's WINNING result
        # (exact, one float per unit): the p99 the predictive scheduler is
        # judged on (bench.py --speculation, chaos report stats).
        self.unit_seconds: list[float] = []

    # -- queries -----------------------------------------------------------

    def next_pending_unit(self) -> WorkUnit | None:
        """Peek the next pending work unit (O(1))."""
        while self._pending:
            unit = self._pending[0]
            if self.frames[unit].status is FrameStatus.PENDING:
                return unit
            self._pending.popleft()  # stale entry
        return None

    def all_frames_finished(self) -> bool:
        return self._finished_count >= len(self.frames)

    def finished_count(self) -> int:
        return self._finished_count

    def pending_count(self) -> int:
        """Frames whose status is PENDING (O(1): maintained counter)."""
        return self._pending_live

    def in_flight_count(self) -> int:
        """Units currently queued-on or rendering-on some worker — the
        quantity the fair-share scheduler meters per job (O(1))."""
        return len(self._in_flight_units)

    def in_flight_units(self) -> dict[WorkUnit, int]:
        """Live view of queued/rendering units -> holding worker id.
        Callers must not mutate it; the transitions below own it."""
        return self._in_flight_units

    def pending_units(self, limit: int | None = None) -> list[WorkUnit]:
        out = []
        for unit in self._pending:
            if self.frames[unit].status is FrameStatus.PENDING:
                out.append(unit)
                if limit is not None and len(out) >= limit:
                    break
        return out

    # -- assembly ledger (tiled jobs) --------------------------------------

    def tiles_landed(self, frame_index: int) -> int:
        """Tiles of ``frame_index`` that have reached FINISHED."""
        if self._tiles_per_frame == 1:
            # One unit per frame — but its KEY is tile 0 for a (valid)
            # 1x1 tiled job and tile None for an untiled one.
            unit = WorkUnit(
                frame_index, None if self.job.tile_grid is None else 0
            )
            record = self.frames.get(unit)
            return int(
                record is not None and record.status is FrameStatus.FINISHED
            )
        return len(self._assembly.get(frame_index, ()))

    def partially_assembled_frames(self) -> list[int]:
        """Frames with SOME but not all tiles landed — must be empty after
        any completed run (the no-ghost-frame chaos invariant; a cancelled
        job may legitimately hold some)."""
        return sorted(
            frame
            for frame, tiles in self._assembly.items()
            if 0 < len(tiles) < self._tiles_per_frame
        )

    def assembly_view(self) -> dict:
        """The ``assembly`` section of the per-job live view."""
        return {
            "tiles_per_frame": self._tiles_per_frame,
            "frames_assembled": self.frames_assembled,
            "frames_partial": len(self.partially_assembled_frames()),
        }

    # -- transitions -------------------------------------------------------
    #
    # Every transition accepts a bare int as a WHOLE-FRAME unit (the
    # pre-tiling call shape): normalization goes through one helper so
    # frame-keyed callers and tile-keyed callers cannot drift.

    @staticmethod
    def _as_unit(unit: "WorkUnit | int") -> WorkUnit:
        return WorkUnit(unit) if isinstance(unit, int) else unit

    def _retrack(self, record: FrameRecord, old: FrameStatus) -> None:
        """Fold one applied transition into the O(1) mirrors + version.

        Called AFTER the record's status/worker fields are updated. Every
        transition must come through here — the scheduler's incremental
        structures trust ``version`` to cover all demand/load/placement
        changes, including worker reassignments that keep the status.
        """
        new = record.status
        if old is FrameStatus.PENDING:
            if new is not FrameStatus.PENDING:
                self._pending_live -= 1
        elif new is FrameStatus.PENDING:
            self._pending_live += 1
        if (
            new
            in (FrameStatus.QUEUED_ON_WORKER, FrameStatus.RENDERING_ON_WORKER)
            and record.worker_id is not None
        ):
            self._in_flight_units[record.unit] = record.worker_id
        else:
            self._in_flight_units.pop(record.unit, None)
        self.version += 1

    def mark_frame_as_queued(
        self,
        unit: "WorkUnit | int",
        worker_id: int,
        queued_at: float,
        *,
        stolen_from: int | None = None,
        stolen_at: float | None = None,
    ) -> None:
        unit = self._as_unit(unit)
        record = self.frames[unit]
        if record.status is FrameStatus.FINISHED:
            raise ValueError(f"BUG: unit {unit.label} is already finished.")
        old = record.status
        record.status = FrameStatus.QUEUED_ON_WORKER
        record.worker_id = worker_id
        record.queued_at = queued_at
        if stolen_from is not None:
            record.stolen_from = stolen_from
            record.stolen_at = stolen_at
        if self._pending and self._pending[0] == unit:
            self._pending.popleft()
        self._retrack(record, old)

    def mark_frame_as_rendering(
        self, unit: "WorkUnit | int", worker_id: int
    ) -> None:
        unit = self._as_unit(unit)
        record = self.frames[unit]
        if record.status is FrameStatus.FINISHED:
            return  # late event after a race; harmless
        old = record.status
        record.status = FrameStatus.RENDERING_ON_WORKER
        record.worker_id = worker_id
        self._retrack(record, old)

    def mark_frame_as_finished(self, unit: "WorkUnit | int") -> bool:
        """Transition a unit to FINISHED; returns True when this call
        completed its whole FRAME (every tile landed) — the exactly-once
        assembly trigger. Idempotent: repeated calls return False.
        """
        unit = self._as_unit(unit)
        record = self.frames[unit]
        if record.status is FrameStatus.FINISHED:
            return False
        old = record.status
        record.status = FrameStatus.FINISHED
        self._retrack(record, old)
        self._finished_count += 1
        if self.on_unit_finished is not None:
            self.on_unit_finished(unit)
        if self._tiles_per_frame == 1:
            return True
        landed = self._assembly.setdefault(unit.frame_index, set())
        landed.add(unit.tile if unit.tile is not None else 0)
        return len(landed) >= self._tiles_per_frame

    def note_frame_assembled(self, frame_index: int) -> None:
        self.frames_assembled += 1
        # Fully-landed frames leave the partial map so the ghost-frame
        # audit is O(frames in flight), not O(job).
        self._assembly.pop(frame_index, None)
        if self.on_frame_assembled is not None:
            self.on_frame_assembled(frame_index)

    def return_frame_to_pending(self, unit: "WorkUnit | int") -> None:
        """Unit comes back to the pool (steal succeeded, render errored,
        or its worker died). Unlike the reference — where a dead worker's
        frames stay QueuedOnWorker forever (SURVEY.md §5.3) — this makes
        eviction recoverable. Idempotent: under fault races (an eviction
        and a failed dispatch both returning the same unit) the second
        call must not add a second pending entry."""
        unit = self._as_unit(unit)
        record = self.frames[unit]
        if record.status in (FrameStatus.FINISHED, FrameStatus.PENDING):
            return
        old = record.status
        record.status = FrameStatus.PENDING
        record.worker_id = None
        record.queued_at = None
        self._pending.append(unit)
        self._retrack(record, old)
