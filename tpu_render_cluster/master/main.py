"""Master CLI entry point.

Flag surface matches the reference's clap parser (reference:
master/src/cli.rs:5-40, master/src/main.rs:275-338):
``master --host H --port P [--logFilePath F] run-job <job.toml>
--resultsDirectory D`` — plus the NEW ``serve`` subcommand running the
multi-job scheduler service (sched/manager.py): workers connect on
``--port`` as usual, jobs arrive over the JSON-lines control plane on
``--controlPort`` (``python -m tpu_render_cluster.sched.submit``).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from datetime import datetime
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.master.cluster import ClusterManager
from tpu_render_cluster.master.persist import (
    parse_worker_traces,
    print_results,
    run_file_prefix,
    save_cost_model,
    save_processed_results,
    save_raw_traces,
)
from tpu_render_cluster.obs import export_cluster_trace, write_metrics_snapshot
from tpu_render_cluster.utils.logging import initialize_console_and_file_logging


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="trc-master", description="Render cluster master")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9901)
    parser.add_argument("--logFilePath", dest="log_file_path", default=None)
    parser.add_argument(
        "--ledger",
        dest="ledger_directory",
        default=None,
        help="Write-ahead job ledger directory (replicated control plane): "
        "job lifecycle + unit-finished transitions are journaled (fsync'd, "
        "segmented, snapshot-compacted) so a restarted or standby master "
        "replays them, re-adopts live workers, and fences stale traffic "
        "with a monotonic epoch. Defaults to the TRC_HA_LEDGER environment "
        "variable; omit both to run ledger-less (reference behavior).",
    )
    parser.add_argument(
        "--replicationPort",
        dest="replication_port",
        type=int,
        default=None,
        help="Stream the ledger's committed records to follower processes "
        "(python -m tpu_render_cluster.ha.replicate) on this TCP port, so "
        "a standby on ANOTHER host holds a promotable replica — no shared "
        "filesystem. 0 picks an ephemeral port. Requires --ledger (or "
        "TRC_HA_LEDGER); defaults to the TRC_HA_REPL_PORT environment "
        "variable; omit both to disable.",
    )
    parser.add_argument(
        "--telemetryPort",
        dest="telemetry_port",
        type=int,
        default=None,
        help="Serve live pull-based telemetry over HTTP on this port: "
        "/metrics (Prometheus text exposition), /healthz, /clusterz "
        "(the live cluster_view). 0 picks an ephemeral port (printed). "
        "Defaults to the TRC_OBS_PORT environment variable; omit both to "
        "disable. This is the live path — metrics-live.json stays for "
        "file-based consumers.",
    )
    subparsers = parser.add_subparsers(dest="command", required=True)
    run_job = subparsers.add_parser("run-job", help="Run a job to completion")
    run_job.add_argument("job_file_path")
    run_job.add_argument(
        "--resultsDirectory",
        dest="results_directory",
        default=None,
        help="Where raw traces + processed results are written. Defaults to "
        "the canonical results/cluster-runs directory "
        "(tpu_render_cluster/analysis/paths.py), which run_all reads with "
        "no arguments.",
    )
    run_job.add_argument(
        "--resume",
        action="store_true",
        help="Skip frames whose output files already exist (resume-by-scan; "
        "beyond-reference, SURVEY.md §5.4).",
    )
    run_job.add_argument(
        "--baseDirectory",
        dest="base_directory",
        default=".",
        help="%%BASE%% root used to resolve the output directory for --resume.",
    )
    serve = subparsers.add_parser(
        "serve",
        help="Run the multi-job scheduler service: jobs are submitted over "
        "the JSON-lines control port (python -m tpu_render_cluster.sched.submit) "
        "and multiplexed over the shared worker pool with weighted "
        "fair-share + preemption; the service exits after a drain request "
        "once every job has finished.",
    )
    serve.add_argument(
        "--controlPort",
        dest="control_port",
        type=int,
        default=9902,
        help="TCP port of the JSON-lines control plane (submit/status/cancel/drain).",
    )
    serve.add_argument(
        "--resultsDirectory",
        dest="results_directory",
        default=None,
        help="Where the service's obs artifacts + metrics-live.json land "
        "(defaults to the canonical results/cluster-runs directory).",
    )
    serve.add_argument(
        "--baseDirectory",
        dest="base_directory",
        default=None,
        help="%%BASE%% root for resolving tiled jobs' output directories "
        "on the MASTER (the assembly stitcher reads tile files and writes "
        "the final frames there).",
    )
    return parser


def resolved_telemetry_port(args: argparse.Namespace) -> int | None:
    """The CLI flag, else the ``TRC_OBS_PORT`` env default, else disabled."""
    from tpu_render_cluster.obs.http import resolve_telemetry_port

    return resolve_telemetry_port(args.telemetry_port, "TRC_OBS_PORT")


def open_ledger(args: argparse.Namespace):
    """``--ledger`` flag, else ``TRC_HA_LEDGER``, else None (no journal).

    Opening claims the directory for this incarnation: the epoch is
    bumped + persisted and any torn tail from a previous crash repaired
    before the first append."""
    from tpu_render_cluster.ha.ledger import JobLedger
    from tpu_render_cluster.obs import get_registry
    from tpu_render_cluster.utils.env import env_str

    directory = args.ledger_directory or env_str("TRC_HA_LEDGER")
    if not directory:
        return None
    # The CLI's managers default to the process-global registry, so the
    # ledger's append-latency histogram lands in the same /metrics.
    ledger = JobLedger.open(directory, metrics=get_registry())
    print(
        f"Job ledger at {directory}: epoch {ledger.epoch}, "
        f"{ledger.replay.records} record(s) replayed."
    )
    return ledger


async def start_replication(ledger, args: argparse.Namespace):
    """Start the ledger streaming-replication endpoint when configured
    (``--replicationPort`` flag, else ``TRC_HA_REPL_PORT``), or None."""
    from tpu_render_cluster.utils.env import env_int

    port = args.replication_port
    if port is None:
        port = env_int("TRC_HA_REPL_PORT", -1)
        if port < 0:
            return None
    if ledger is None:
        print(
            "warning: --replicationPort ignored: no ledger to replicate "
            "(pass --ledger or set TRC_HA_LEDGER).",
            file=sys.stderr,
        )
        return None
    from tpu_render_cluster.ha.replicate import ReplicationServer
    from tpu_render_cluster.obs import get_registry

    replication = ReplicationServer(
        ledger, host=args.host, port=port, metrics=get_registry()
    )
    await replication.start()
    print(
        f"Ledger replication streaming on {args.host}:{replication.port} "
        f"(epoch {ledger.epoch}); attach followers with "
        f"python -m tpu_render_cluster.ha.replicate --primary "
        f"{args.host}:{replication.port} --directory <replica-dir>."
    )
    return replication


async def serve_command(args: argparse.Namespace) -> int:
    from tpu_render_cluster.sched.control import ControlServer
    from tpu_render_cluster.sched.manager import JobManager

    if args.results_directory is None:
        from tpu_render_cluster.analysis.paths import DEFAULT_RESULTS_DIR

        args.results_directory = str(DEFAULT_RESULTS_DIR)
    results_directory = Path(args.results_directory)
    ledger = await asyncio.to_thread(open_ledger, args)
    manager = JobManager(
        args.host,
        args.port,
        metrics_snapshot_path=results_directory / "metrics-live.json",
        output_base_directory=args.base_directory,
        telemetry_port=resolved_telemetry_port(args),
        ledger=ledger,
    )
    if ledger is not None:
        # Re-admit what a previous incarnation left unfinished: the jobs
        # re-enter the admission queue with their recorded weight/priority
        # and pick up at the ledger's finished set when admitted.
        from tpu_render_cluster.sched.models import JobSpec

        for entry in ledger.replay.unfinished_jobs():
            if entry.job is None:
                print(
                    f"warning: ledger job {entry.job_name!r} has no recorded "
                    "spec; cannot re-admit it.",
                    file=sys.stderr,
                )
                continue
            job_id = manager.submit(
                JobSpec(
                    job=BlenderJob.from_dict(entry.job),
                    weight=entry.weight,
                    priority=entry.priority,
                )
            )
            print(
                f"Ledger: re-admitted unfinished job {entry.job_name!r} "
                f"as {job_id} ({len(entry.finished_units)} unit(s) already "
                "finished)."
            )
    # A restarted service re-learns worker speeds from its own previous
    # shutdown snapshot (explicit TRC_COST_MODEL wins; saved again below).
    from tpu_render_cluster.sched.cost_model import (
        explicit_model_configured,
        load_model_snapshot,
        save_model_snapshot,
    )

    sched_model_path = results_directory / "sched_cost-model.json"
    if not explicit_model_configured():
        restored = load_model_snapshot(sched_model_path)
        if restored is not None:
            manager.cost_service.model = restored
    replication = await start_replication(ledger, args)
    control = ControlServer(manager, args.host, args.control_port)
    await control.start()
    print(
        f"Scheduler serving: workers on {args.host}:{args.port}, "
        f"control on {args.host}:{control.port}. Submit with "
        f"python -m tpu_render_cluster.sched.submit --host {args.host} "
        f"--controlPort {control.port} submit <job.toml>."
    )
    if manager.telemetry is not None:
        # The resolved (possibly ephemeral) port is logged by
        # TelemetryServer.start() once serve() binds.
        print(
            "Telemetry endpoints (once bound): /metrics /healthz /clusterz "
            f"on port {manager.telemetry.port or '<ephemeral>'}"
        )
    try:
        await manager.serve()
    finally:
        await control.stop()
        if replication is not None:
            await replication.stop()

        # Artifact export runs on FAILURE paths too (same pattern as the
        # assembly drain): a service that died mid-run is exactly the one
        # whose partial timeline and final ledger snapshot matter most.
        # Guarded per step so an export failure can neither mask the
        # service's real exception nor take the later writers down.
        def _save_model() -> None:
            # Final drain of completion observations (the last frames'
            # results can land after the scheduler loop's last ingest
            # tick).
            manager.cost_service.ingest(
                manager.workers.values(), manager._job_for_name
            )
            save_model_snapshot(manager.cost_service.model, sched_model_path)

        def _export_obs_artifacts() -> None:
            prefix = f"sched-{datetime.now().strftime('%Y-%m-%d_%H-%M-%S')}"
            manager.span_tracer.export(
                results_directory / f"{prefix}_trace-events.json"
            )
            export_cluster_trace(
                results_directory / f"{prefix}_cluster_trace-events.json",
                manager.cluster_timeline_processes(),
                extra_other_data=manager.timeline_other_data(),
            )
            write_metrics_snapshot(
                results_directory / f"{prefix}_metrics.json",
                manager.metrics,
                extra={
                    **manager.cluster_view(),
                    "history": manager.history.summary_dict(),
                },
            )

        for step in (_save_model, _export_obs_artifacts):
            try:
                step()
            except Exception as e:  # noqa: BLE001 - obs must not mask the run error
                print(
                    f"warning: obs artifact export failed: {e}",
                    file=sys.stderr,
                )
    view = manager.scheduler_view()
    print(json.dumps({"jobs": view["jobs"]}, indent=2, default=str))
    return 0


async def run_job_command(args: argparse.Namespace) -> int:
    if args.results_directory is None:
        from tpu_render_cluster.analysis.paths import DEFAULT_RESULTS_DIR

        args.results_directory = str(DEFAULT_RESULTS_DIR)
    job = BlenderJob.load_from_file(args.job_file_path)
    start_time = datetime.now()
    ledger = await asyncio.to_thread(open_ledger, args)
    manager = ClusterManager(
        args.host,
        args.port,
        job,
        metrics_snapshot_path=Path(args.results_directory) / "metrics-live.json",
        # Tiled jobs: the assembly stitcher resolves the job's %BASE%
        # output prefix with the same base directory resume does.
        output_base_directory=args.base_directory,
        telemetry_port=resolved_telemetry_port(args),
        ledger=ledger,
        ledger_resume=args.resume,
    )
    if args.resume:
        from tpu_render_cluster.master.resume import apply_resume, load_cost_model

        # Ledger wins (exact per-unit journal); the output-directory scan
        # is the fallback for jobs that predate the ledger. The manager
        # already applied any open-generation replay at construction;
        # apply_resume is idempotent over it.
        apply_resume(
            manager.state,
            job,
            args.base_directory,
            ledger_replay=ledger.replay if ledger is not None else None,
        )
        # Restore the previous run's learned predictors too (an explicit
        # TRC_COST_MODEL wins over the snapshot — load_cost_model
        # returns None when it is set).
        restored = load_cost_model(job, args.results_directory)
        if restored is not None:
            manager.cost_service.model = restored
        if manager.state.all_frames_finished():
            # Fully-resumed job: don't block on the worker barrier.
            from tpu_render_cluster.traces.master_trace import MasterTrace

            if ledger is not None:
                # Close the journal's lifecycle too: the crash this run
                # resumed from may have hit between the last unit append
                # and job_finished — leaving the entry "started" would
                # make every later replay re-admit a completed job.
                # Settle anything the manager's construction scheduled
                # BEFORE reading the lifecycle entry: a fresh generation's
                # job_started may still sit in the appender queue, and
                # reading first would skip the close below, leaving the
                # ledger "started" forever.
                if manager.ledger_appender is not None:
                    await manager.ledger_appender.stop()
                entry = ledger.replay.job(job.job_name)
                if entry is not None and entry.status == "started":
                    await asyncio.to_thread(
                        ledger.append_job_finished, job.job_name
                    )
                await asyncio.to_thread(ledger.close)
            print("All frames already rendered; nothing to do.")
            now = time.time()
            trace = MasterTrace(job_start_time=now, job_finish_time=now)
            results_directory = Path(args.results_directory)
            await asyncio.to_thread(
                save_raw_traces, start_time, job, results_directory, trace, []
            )
            # Keep the scheduler section present on every processed-results
            # file (consumers index it unconditionally); a fully-resumed
            # job scheduled nothing, so the count is trivially zero.
            await asyncio.to_thread(
                save_processed_results,
                start_time, job, results_directory, [],
                scheduler_stats={"auction_greedy_fallbacks": 0},
            )
            return 0
    from tpu_render_cluster.ops import assignment as assignment_ops

    assignment_ops.reset_greedy_fallback_count()
    results_directory = Path(args.results_directory)
    prefix = run_file_prefix(start_time, job)
    replication = await start_replication(ledger, args)
    try:
        master_trace, worker_traces = await manager.initialize_server_and_run_job()
    finally:
        if replication is not None:
            await replication.stop()
        # Obs artifacts are written even when the job RAISES (worker-pool
        # collapse, unit error budget, operator interrupt): the partial
        # span timeline, merged cluster trace, and final metrics/ledger
        # snapshot matter most in exactly those runs. Same pattern as the
        # assembly drain-on-failure. The prefix matches the raw trace the
        # success path writes below. Each writer is guarded independently:
        # an export failure (full disk, revoked permissions) must neither
        # mask the job's real exception nor take the later writers down.
        def _export_obs_artifacts() -> None:
            manager.span_tracer.export(
                results_directory / f"{prefix}_trace-events.json"
            )
            # Merged cluster timeline: the workers' span events
            # (piggybacked on their job-finished responses) rebased onto
            # the master clock by the heartbeat clock-offset estimates —
            # one Perfetto file with a process row per worker and flow
            # arrows per frame lifecycle.
            export_cluster_trace(
                results_directory / f"{prefix}_cluster_trace-events.json",
                manager.cluster_timeline_processes(),
            )
            write_metrics_snapshot(
                results_directory / f"{prefix}_metrics.json",
                manager.metrics,
                extra={
                    **manager.cluster_view(),
                    "history": manager.history.summary_dict(),
                },
            )

        for step in (
            _export_obs_artifacts,
            # Snapshot the run's learned cost model so --resume (or a
            # plain re-run of the same job) starts with warm predictors
            # instead of re-learning worker speeds from scratch. Failure
            # paths keep it too — exactly what a resume restores.
            lambda: save_cost_model(
                job, results_directory, manager.cost_service.model
            ),
        ):
            try:
                step()
            except Exception as e:  # noqa: BLE001 - obs must not mask the run error
                print(
                    f"warning: obs artifact export failed: {e}",
                    file=sys.stderr,
                )

    await asyncio.to_thread(
        save_raw_traces,
        start_time, job, results_directory, master_trace, worker_traces,
    )
    performance = parse_worker_traces(worker_traces)
    await asyncio.to_thread(
        save_processed_results,
        start_time,
        job,
        results_directory,
        performance,
        scheduler_stats={
            "auction_greedy_fallbacks": assignment_ops.greedy_fallback_count(),
        },
    )
    print_results(master_trace, performance)
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    initialize_console_and_file_logging(args.log_file_path)
    if args.command == "run-job":
        return asyncio.run(run_job_command(args))
    if args.command == "serve":
        return asyncio.run(serve_command(args))
    return 2


if __name__ == "__main__":
    sys.exit(main())
