"""ctypes bindings for the C++ WebSocket codec (native/wscodec.cpp).

Builds the shared library on first use (g++ -O2, cached next to the source)
and degrades gracefully to the pure-Python codec when unavailable —
``load_codec()`` returns None and callers keep their Python fallback.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path

logger = logging.getLogger(__name__)

_SOURCE = Path(__file__).resolve().parent.parent.parent / "native" / "wscodec.cpp"
_LIBRARY = _SOURCE.parent / "libwscodec.so"

_lock = threading.Lock()
_codec: "NativeCodec | None" = None
_load_attempted = False


class NativeCodec:
    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib
        lib.trc_accept_key.restype = ctypes.c_size_t
        lib.trc_accept_key.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.trc_mask_payload.restype = None
        lib.trc_mask_payload.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
        ]
        lib.trc_encode_header.restype = ctypes.c_size_t
        lib.trc_encode_header.argtypes = [
            ctypes.c_uint8,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]

    def accept_key(self, key: str) -> str:
        out = ctypes.create_string_buffer(32)
        written = self._lib.trc_accept_key(key.encode("ascii"), out, 32)
        if written == 0:
            raise ValueError("accept_key failed")
        return out.value.decode("ascii")

    def mask_payload(self, payload: bytes, mask: bytes) -> bytes:
        buffer = ctypes.create_string_buffer(payload, len(payload))
        self._lib.trc_mask_payload(buffer, len(payload), mask)
        return buffer.raw

    def encode_header(
        self, opcode: int, fin: bool, masked: bool, payload_len: int, mask: bytes
    ) -> bytes:
        out = ctypes.create_string_buffer(14)
        written = self._lib.trc_encode_header(
            opcode, int(fin), int(masked), payload_len, mask or b"\0\0\0\0", out, 14
        )
        return out.raw[:written]


def _build() -> bool:
    if not _SOURCE.is_file():
        return False
    if _LIBRARY.is_file() and _LIBRARY.stat().st_mtime >= _SOURCE.stat().st_mtime:
        return True
    try:
        subprocess.run(
            [
                "g++",
                "-O2",
                "-shared",
                "-fPIC",
                "-o",
                str(_LIBRARY),
                str(_SOURCE),
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, OSError) as e:
        logger.debug("Native codec build failed (%s); using Python codec.", e)
        return False


_COMMON_HEADER = _SOURCE.parent / "trc_common.hpp"


def _build_daemon(
    source: Path, binary: Path, sanitize: str | None = None
) -> Path | None:
    """Builds a standalone C++ daemon (worker or master) against the codec.

    ``sanitize`` selects an instrumented variant ("thread" or "address" —
    SURVEY.md §5.2: the C++ side needs TSAN/ASAN precisely because we lose
    Rust's borrow checker). Returns the binary path, or None when the
    toolchain/source is missing.
    """
    if not source.is_file() or not _SOURCE.is_file():
        return None
    newest_source = max(source.stat().st_mtime, _SOURCE.stat().st_mtime)
    if _COMMON_HEADER.is_file():
        newest_source = max(newest_source, _COMMON_HEADER.stat().st_mtime)
    if binary.is_file() and binary.stat().st_mtime >= newest_source:
        return binary
    flags = ["-O2"]
    if sanitize is not None:
        # -O1 -g keeps sanitizer reports readable and stacks accurate.
        flags = [f"-fsanitize={sanitize}", "-O1", "-g", "-fno-omit-frame-pointer"]
    try:
        subprocess.run(
            [
                "g++",
                "-std=gnu++17",
                *flags,
                "-pthread",
                "-o",
                str(binary),
                str(source),
                str(_SOURCE),
            ],
            check=True,
            capture_output=True,
            timeout=600,
        )
        return binary
    except (subprocess.SubprocessError, OSError) as e:
        logger.debug("Daemon build failed (%s): %s", source.name, e)
        return None


def build_worker_daemon(sanitize: str | None = None) -> Path | None:
    """Builds the standalone C++ worker daemon (native/worker_daemon.cpp)."""
    suffix = f"-{sanitize[0]}san" if sanitize else ""
    return _build_daemon(
        _SOURCE.parent / "worker_daemon.cpp",
        _SOURCE.parent / f"trc-worker{suffix}",
        sanitize,
    )


def build_master_daemon(sanitize: str | None = None) -> Path | None:
    """Builds the standalone C++ master daemon (native/master_daemon.cpp)."""
    suffix = f"-{sanitize[0]}san" if sanitize else ""
    return _build_daemon(
        _SOURCE.parent / "master_daemon.cpp",
        _SOURCE.parent / f"trc-master{suffix}",
        sanitize,
    )


def load_codec() -> NativeCodec | None:
    """The built codec, or None when the toolchain/source is unavailable."""
    global _codec, _load_attempted
    with _lock:
        if _load_attempted:
            return _codec
        _load_attempted = True
        if not _build():
            return None
        try:
            _codec = NativeCodec(ctypes.CDLL(str(_LIBRARY)))
        except OSError as e:
            logger.debug("Native codec load failed: %s", e)
            _codec = None
        return _codec
