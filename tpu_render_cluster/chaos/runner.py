"""The chaos harness: run a real in-process cluster under a fault plan.

Wraps ``harness/local.py`` — the full production stack (accepting server,
3-step handshake, heartbeats, real distribution strategies, real
WebSockets on localhost) — with the plan's fault executors wired into the
three seams: ``FaultyConnection`` under each worker's reconnecting client,
``FaultyBackend`` around each mock renderer, and the dispatch-delay shim
inside the master's worker handles. After the job completes (and it MUST
complete — that is invariant #1) the run is audited by
``chaos/invariants.py`` and its obs artifacts are exported like any other
run's, so the merged cluster timeline of a faulted job can be validated
and eyeballed in Perfetto.

Timeout compression: production heartbeat/backoff budgets (10 s pings,
60 s pong windows) would stretch each scenario to minutes, so the run
executes under the plan's ``ChaosTimings`` via the same ``TRC_*``
overrides a deployment would use, restored afterwards.

CLI::

    python -m tpu_render_cluster.chaos.runner --seed 7 --workers 3 \
        [--frames 24] [--plan plan.toml] [--results-directory DIR]

exits non-zero if any invariant is violated, and prints the report (plan
fingerprint, injected faults, the master's exactly-once ledger) as JSON.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from tpu_render_cluster.chaos.inject import MasterChaosHooks, WorkerChaosController
from tpu_render_cluster.chaos.invariants import (
    check_invariants,
    check_multi_job_invariants,
    counter_total,
    ledger_stats,
)
from tpu_render_cluster.chaos.plan import FaultPlan
from tpu_render_cluster.harness import local as local_harness
from tpu_render_cluster.jobs.models import (
    BlenderJob,
    DistributionStrategy,
    DynamicStrategyOptions,
)
from tpu_render_cluster.master.cluster import ClusterManager
from tpu_render_cluster.obs import MetricsRegistry
from tpu_render_cluster.worker.backends.chaos import FaultyBackend
from tpu_render_cluster.worker.backends.mock import MockBackend
from tpu_render_cluster.worker.runtime import Worker

DEFAULT_FRAMES = 24
DEFAULT_RENDER_SECONDS = 0.12


def unit_latency_stats(unit_seconds: list[float]) -> dict[str, float]:
    """Exact percentiles over the master's per-unit winning-result
    latencies (state.unit_seconds) — the tail the predictive scheduler
    is judged on (bench.py --speculation)."""
    if not unit_seconds:
        return {"count": 0}
    ordered = sorted(unit_seconds)

    def pct(q: float) -> float:
        index = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
        return ordered[index]

    return {
        "count": len(ordered),
        "p50_s": pct(0.50),
        "p90_s": pct(0.90),
        "p99_s": pct(0.99),
        "max_s": ordered[-1],
    }


@dataclass
class ChaosReport:
    """Everything a chaos run produced: schedule, audit, ledger."""

    plan: FaultPlan
    violations: list[str]
    stats: dict[str, Any]
    artifacts: dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict[str, Any]:
        return {
            "plan": self.plan.to_dict(),
            "fingerprint": self.plan.fingerprint(),
            "ok": self.ok,
            "violations": self.violations,
            "stats": self.stats,
            "artifacts": self.artifacts,
        }


def _make_job(
    plan: FaultPlan, frames: int, strategy, tile_grid=None, slo=None
) -> BlenderJob:
    if strategy is None:
        # Dynamic (work-stealing) by default: the strategy with the most
        # fault-sensitive moving parts — steals race evictions, queue
        # mirrors drive victim selection.
        strategy = DistributionStrategy.dynamic_strategy(
            DynamicStrategyOptions(
                target_queue_size=3,
                min_queue_size_to_steal=1,
                min_seconds_before_resteal_to_elsewhere=1,
                min_seconds_before_resteal_to_original_worker=2,
            )
        )
    return BlenderJob(
        job_name=f"chaos-seed-{plan.seed}",
        job_description=f"chaos run (plan {plan.fingerprint()})",
        project_file_path="%BASE%/p.blend",
        render_script_path="%BASE%/s.py",
        frame_range_from=1,
        frame_range_to=frames,
        wait_for_number_of_workers=plan.workers,
        frame_distribution_strategy=strategy,
        output_directory_path="%BASE%/out",
        output_file_name_format="rendered-#####",
        output_file_format="PNG",
        tile_grid=tile_grid,
        slo=slo,
    )


@contextmanager
def _timing_overrides(timings):
    """Apply the plan's compressed timeout profile; restore on exit.

    Uses exactly the tuning surface a deployment has: the ``TRC_*``
    environment overrides plus the two heartbeat module constants and the
    master's reconnect-wait class attribute.
    """
    from tpu_render_cluster.master import worker_handle as wh
    from tpu_render_cluster.transport.reconnect import (
        ReconnectableServerConnection,
    )

    env = {
        "TRC_BACKOFF_BASE": str(timings.backoff_base),
        "TRC_BACKOFF_CAP_SECONDS": str(timings.backoff_cap_seconds),
        "TRC_MAX_CONNECT_RETRIES": str(timings.max_connect_retries),
        "TRC_MAX_RECONNECTS_PER_OP": str(timings.max_reconnects_per_op),
        "TRC_OP_DEADLINE_SECONDS": str(timings.op_deadline_seconds),
        "TRC_SEND_DEADLINE_SECONDS": str(timings.send_deadline_seconds),
        "TRC_RPC_DEADLINE_SECONDS": str(timings.rpc_deadline_seconds),
        "TRC_HEARTBEAT_PONG_RETRIES": str(timings.heartbeat_pong_retries),
    }
    saved_env = {name: os.environ.get(name) for name in env}
    saved_interval = wh.HEARTBEAT_INTERVAL_SECONDS
    saved_timeout = wh.HEARTBEAT_RESPONSE_TIMEOUT
    saved_wait = ReconnectableServerConnection.MAX_WAIT_FOR_RECONNECT
    os.environ.update(env)
    wh.HEARTBEAT_INTERVAL_SECONDS = timings.heartbeat_interval
    wh.HEARTBEAT_RESPONSE_TIMEOUT = timings.heartbeat_response_timeout
    ReconnectableServerConnection.MAX_WAIT_FOR_RECONNECT = (
        timings.max_wait_for_reconnect
    )
    try:
        yield
    finally:
        for name, value in saved_env.items():
            if value is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = value
        wh.HEARTBEAT_INTERVAL_SECONDS = saved_interval
        wh.HEARTBEAT_RESPONSE_TIMEOUT = saved_timeout
        ReconnectableServerConnection.MAX_WAIT_FOR_RECONNECT = saved_wait


async def _chaos_run(
    job: BlenderJob,
    backends: list[FaultyBackend],
    controllers: list[WorkerChaosController],
    hooks: MasterChaosHooks,
    registries: list[MetricsRegistry],
    master_registry: MetricsRegistry,
    flight_directory: str | Path | None = None,
):
    watchdogs: list[asyncio.Task] = []

    async def on_cluster_started(manager, workers, worker_tasks) -> None:
        for slot, worker in enumerate(workers):
            hooks.map_worker(worker.worker_id, slot)
            controllers[slot].attach(worker, worker_tasks[slot].cancel)
            watchdogs.append(
                asyncio.create_task(
                    controllers[slot].run_timed_faults(),
                    name=f"chaos-watchdog-{slot}",
                )
            )

    try:
        return await local_harness._run(
            job,
            backends,
            manager_factory=lambda job: ClusterManager(
                "127.0.0.1",
                0,
                job,
                metrics=master_registry,
                dispatch_delay_fn=hooks.dispatch_delay,
                flight_directory=flight_directory,
            ),
            worker_factory=lambda slot, port, backend: Worker(
                "127.0.0.1",
                port,
                backend,
                metrics=registries[slot],
                connection_wrapper=controllers[slot].wrap_connection,
            ),
            on_cluster_started=on_cluster_started,
            # Killed/hung workers never exit on their own (the master
            # skips dead workers at trace collection); reap them.
            worker_grace=3.0,
            allow_worker_failures=True,
        )
    finally:
        for watchdog in watchdogs:
            watchdog.cancel()
        await asyncio.gather(*watchdogs, return_exceptions=True)


def _aggregate_fault_counts(
    registries: list[MetricsRegistry], master_registry: MetricsRegistry
) -> dict[str, float]:
    from tpu_render_cluster.analysis.obs_events import (
        accumulate_chaos_fault_counts,
    )

    out: dict[str, float] = {}
    for registry in [*registries, master_registry]:
        accumulate_chaos_fault_counts(registry.snapshot(), out)
    return out


def run_chaos_job(
    plan: FaultPlan,
    *,
    frames: int = DEFAULT_FRAMES,
    strategy=None,
    results_directory: str | Path | None = None,
    render_seconds: float = DEFAULT_RENDER_SECONDS,
    timeout: float = 180.0,
    tile_grid: tuple[int, int] | None = None,
    slo=None,
    flight_directory: str | Path | None = None,
) -> ChaosReport:
    """Run one seeded chaos job end to end and audit the invariants.

    ``tile_grid`` torments the TILED pipeline: every frame splits into
    grid tiles, so the same fault schedule now races evictions, steals,
    duplicates, and drains against sub-frame units and the master's
    per-frame assembly ledger — audited at tile granularity
    (``invariants.check_tile_invariants``).

    ``slo`` (a ``jobs.models.JobSlo``) declares objectives on the chaos
    job so seeded fault schedules can drive the SLO engine into breach;
    the report's ``stats["slo"]`` then carries the final per-job
    attainment/burn view and the alert edge ledger.

    ``flight_directory`` arms the master's flight recorder with a dump
    target: incident triggers (an SLO fire, an eviction, a job failure)
    emit ``*_blackbox.json`` bundles there, and the report's
    ``stats["flight"]`` carries the trigger/dump ledger either way.
    """
    job = _make_job(plan, frames, strategy, tile_grid, slo)
    registries = [MetricsRegistry() for _ in range(plan.workers)]
    controllers = [
        WorkerChaosController(slot, plan.events_for(slot), registry=registries[slot])
        for slot in range(plan.workers)
    ]
    master_registry = MetricsRegistry()
    hooks = MasterChaosHooks(plan, registry=master_registry)
    backends = [
        FaultyBackend(
            MockBackend(
                load_seconds=0.004,
                save_seconds=0.004,
                render_seconds=render_seconds,
            ),
            controllers[slot],
        )
        for slot in range(plan.workers)
    ]
    started = time.time()
    with _timing_overrides(plan.timings):
        master_trace, worker_traces, manager, workers = asyncio.run(
            asyncio.wait_for(
                _chaos_run(
                    job,
                    backends,
                    controllers,
                    hooks,
                    registries,
                    master_registry,
                    flight_directory,
                ),
                timeout,
            )
        )

    artifacts: dict[str, str] = {}
    cluster_trace_document = None
    if results_directory is not None:
        results_directory = Path(results_directory)
        results_directory.mkdir(parents=True, exist_ok=True)
        prefix = results_directory / f"chaos-{plan.seed}-{plan.fingerprint()}"
        trace_path, metrics_path, cluster_trace_path = (
            local_harness.save_obs_artifacts(prefix, manager, workers)
        )
        artifacts = {
            "trace_events": str(trace_path),
            "metrics": str(metrics_path),
            "cluster_trace": str(cluster_trace_path),
        }
        cluster_trace_document = json.loads(
            Path(cluster_trace_path).read_text(encoding="utf-8")
        )
    else:
        # No directory given: still validate the merged timeline by
        # building the document in memory from the same collection path.
        from tpu_render_cluster.obs import merge_timeline

        cluster_trace_document = merge_timeline(
            manager.cluster_timeline_processes()
        )

    violations = check_invariants(
        manager, plan, cluster_trace_document=cluster_trace_document
    )
    master_snapshot = manager.metrics.snapshot()
    stats: dict[str, Any] = {
        "frames_total": len(manager.state.frames),
        "tiles_per_frame": job.tiles_per_frame(),
        "frames_assembled": manager.state.frames_assembled,
        "job_seconds": master_trace.job_finish_time - master_trace.job_start_time,
        "wall_seconds": time.time() - started,
        "worker_traces_collected": len(worker_traces),
        "faults_injected": _aggregate_fault_counts(registries, master_registry),
        "ledger": ledger_stats(master_snapshot),
        "reconnects": counter_total(
            master_snapshot, "master_worker_reconnects_total"
        ),
        "unit_latency": unit_latency_stats(manager.state.unit_seconds),
    }
    if manager.speculation.config.enabled or manager.speculation.launched_total:
        stats["speculation"] = manager.speculation.view()
    if manager.slo.tracked():
        stats["slo"] = manager.slo.view()
    if manager.flightrec.triggers or manager.flightrec.dumps:
        stats["flight"] = manager.flightrec.view()
    return ChaosReport(
        plan=plan, violations=violations, stats=stats, artifacts=artifacts
    )


async def _chaos_multi_run(
    specs,
    backends: list[FaultyBackend],
    controllers: list[WorkerChaosController],
    hooks: MasterChaosHooks,
    registries: list[MetricsRegistry],
    master_registry: MetricsRegistry,
):
    from tpu_render_cluster.sched.manager import JobManager, SchedulerConfig

    watchdogs: list[asyncio.Task] = []

    async def on_cluster_started(manager, workers, worker_tasks) -> None:
        for slot, worker in enumerate(workers):
            hooks.map_worker(worker.worker_id, slot)
            controllers[slot].attach(worker, worker_tasks[slot].cancel)
            watchdogs.append(
                asyncio.create_task(
                    controllers[slot].run_timed_faults(),
                    name=f"chaos-watchdog-{slot}",
                )
            )

    try:
        return await local_harness._run_multi_job(
            specs,
            backends,
            manager_factory=lambda: JobManager(
                "127.0.0.1",
                0,
                config=SchedulerConfig.from_env(),
                metrics=master_registry,
                dispatch_delay_fn=hooks.dispatch_delay,
            ),
            worker_factory=lambda slot, port, backend: Worker(
                "127.0.0.1",
                port,
                backend,
                metrics=registries[slot],
                connection_wrapper=controllers[slot].wrap_connection,
            ),
            on_cluster_started=on_cluster_started,
            worker_grace=3.0,
            allow_worker_failures=True,
        )
    finally:
        for watchdog in watchdogs:
            watchdog.cancel()
        await asyncio.gather(*watchdogs, return_exceptions=True)


def run_chaos_multi_job(
    plan: FaultPlan,
    *,
    jobs: int = 2,
    frames: int = DEFAULT_FRAMES,
    weights: list[float] | None = None,
    render_seconds: float = DEFAULT_RENDER_SECONDS,
    timeout: float = 240.0,
) -> ChaosReport:
    """Run CONCURRENT jobs through the scheduler under a seeded fault plan.

    The multi-job counterpart of ``run_chaos_job``: the same per-slot
    fault executors and compressed timeout profile, driving a
    ``sched.JobManager`` service instead of the single-job master, with
    ``jobs`` weighted submissions sharing the worker pool. The audit is
    ``check_multi_job_invariants`` — per-job completion + exactly-once
    ledgers + ghost sweeps, plus the plan's eviction/drain accounting.
    """
    from tpu_render_cluster.sched.models import JobSpec

    weights = weights or [float(2 ** i) for i in range(jobs)]
    if len(weights) != jobs:
        raise ValueError(f"need {jobs} weights, got {len(weights)}")
    specs = []
    for i in range(jobs):
        job = _make_job(plan, frames, None)
        job = BlenderJob.from_dict(
            {**job.to_dict(), "job_name": f"{job.job_name}-mj{i}"}
        )
        specs.append(JobSpec(job=job, weight=weights[i]))
    registries = [MetricsRegistry() for _ in range(plan.workers)]
    controllers = [
        WorkerChaosController(slot, plan.events_for(slot), registry=registries[slot])
        for slot in range(plan.workers)
    ]
    master_registry = MetricsRegistry()
    hooks = MasterChaosHooks(plan, registry=master_registry)
    backends = [
        FaultyBackend(
            MockBackend(
                load_seconds=0.004,
                save_seconds=0.004,
                render_seconds=render_seconds,
            ),
            controllers[slot],
        )
        for slot in range(plan.workers)
    ]
    started = time.time()
    with _timing_overrides(plan.timings):
        worker_traces, job_ids, manager, workers = asyncio.run(
            asyncio.wait_for(
                _chaos_multi_run(
                    specs, backends, controllers, hooks, registries,
                    master_registry,
                ),
                timeout,
            )
        )

    from tpu_render_cluster.obs import merge_timeline

    cluster_trace_document = merge_timeline(manager.cluster_timeline_processes())
    violations = check_multi_job_invariants(
        manager, plan, cluster_trace_document=cluster_trace_document
    )
    master_snapshot = manager.metrics.snapshot()
    stats: dict[str, Any] = {
        "jobs": {
            job_id: manager.job_status(job_id) for job_id in job_ids
        },
        "frames_total": frames * jobs,
        "wall_seconds": time.time() - started,
        "worker_traces_collected": len(worker_traces),
        "faults_injected": _aggregate_fault_counts(registries, master_registry),
        "ledger": ledger_stats(master_snapshot),
        "reconnects": counter_total(
            master_snapshot, "master_worker_reconnects_total"
        ),
    }
    return ChaosReport(plan=plan, violations=violations, stats=stats)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trc-chaos", description="Seeded fault-injection harness"
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--workers", type=int, default=3)
    parser.add_argument("--frames", type=int, default=DEFAULT_FRAMES)
    parser.add_argument(
        "--jobs",
        type=int,
        default=1,
        help="Run N weighted jobs CONCURRENTLY through the sched.JobManager "
        "service instead of one job on the single-job master (audited by "
        "the per-job invariants; obs artifacts are skipped in this mode).",
    )
    parser.add_argument(
        "--plan",
        default=None,
        help="TOML fault plan (overrides --seed/--workers; see chaos/plan.py)",
    )
    parser.add_argument(
        "--results-directory",
        default=None,
        help="Where to write the run's obs artifacts (default: results/chaos-runs)",
    )
    parser.add_argument("--timeout", type=float, default=180.0)
    parser.add_argument(
        "--tiles",
        default=None,
        help="Tile grid ROWSxCOLS (e.g. 2x2): torment the tile-sharded "
        "pipeline — sub-frame work units + the master's assembly ledger "
        "(single-job mode only).",
    )
    parser.add_argument(
        "--failover",
        action="store_true",
        help="Run the master-failover scenario (ha/chaos.py): a "
        "ledger-backed primary is killed mid-job, a standby replays the "
        "write-ahead ledger on the same port, re-adopts the workers via "
        "epoch-fenced re-announce, and the job completes — audited by the "
        "cross-incarnation exactly-once invariant. Uses "
        "FaultPlan.generate_failover(seed, workers) unless --plan is given.",
    )
    parser.add_argument(
        "--replicated-failover",
        dest="replicated_failover",
        action="store_true",
        help="Cross-host failover: the standby's ledger arrives by "
        "STREAMING REPLICATION only (no shared filesystem); the stream "
        "is partitioned and the follower lagged before the kill, then "
        "the router's PromotionMonitor promotes the replica, which "
        "finishes the job. Uses "
        "FaultPlan.generate_replicated_failover(seed, workers) unless "
        "--plan is given.",
    )
    parser.add_argument(
        "--shard-kill",
        dest="shard_kill",
        action="store_true",
        help="Two router-fronted shards, one killed whole (master AND "
        "control endpoint) mid-backlog: every orphaned worker must "
        "re-home through the router's route_worker op and the survivor "
        "finish all --jobs exactly once, with the router's fan-outs "
        "degrading the dead shard to absence. Uses "
        "FaultPlan.generate_shard_kill(seed, workers) unless --plan is "
        "given.",
    )
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.replicated_failover:
        from tpu_render_cluster.ha.chaos import run_chaos_replicated_failover

        plan = (
            FaultPlan.from_toml(args.plan)
            if args.plan
            else FaultPlan.generate_replicated_failover(args.seed, args.workers)
        )
        report = run_chaos_replicated_failover(
            plan, frames=args.frames, timeout=args.timeout
        )
        print(json.dumps(report.to_dict(), indent=2))
        return 0 if report.ok else 1
    if args.shard_kill:
        from tpu_render_cluster.ha.chaos import run_chaos_shard_kill

        plan = (
            FaultPlan.from_toml(args.plan)
            if args.plan
            else FaultPlan.generate_shard_kill(args.seed, args.workers)
        )
        report = run_chaos_shard_kill(
            plan, jobs=args.jobs, frames=args.frames, timeout=args.timeout
        )
        print(json.dumps(report.to_dict(), indent=2))
        return 0 if report.ok else 1
    if args.failover:
        from tpu_render_cluster.ha.chaos import run_chaos_failover_job

        plan = (
            FaultPlan.from_toml(args.plan)
            if args.plan
            else FaultPlan.generate_failover(args.seed, args.workers)
        )
        results_directory = args.results_directory
        if results_directory is None:
            from tpu_render_cluster.analysis.paths import RESULTS_ROOT

            results_directory = RESULTS_ROOT / "chaos-runs"
        tile_grid = None
        if args.tiles:
            from tpu_render_cluster.jobs.tiles import parse_tile_grid

            tile_grid = parse_tile_grid(args.tiles)
        report = run_chaos_failover_job(
            plan,
            frames=args.frames,
            results_directory=results_directory,
            timeout=args.timeout,
            tile_grid=tile_grid,
        )
        print(json.dumps(report.to_dict(), indent=2))
        return 0 if report.ok else 1
    if args.plan:
        plan = FaultPlan.from_toml(args.plan)
    else:
        plan = FaultPlan.generate(args.seed, args.workers)
    if args.jobs > 1:
        report = run_chaos_multi_job(
            plan, jobs=args.jobs, frames=args.frames, timeout=args.timeout
        )
        print(json.dumps(report.to_dict(), indent=2))
        return 0 if report.ok else 1
    results_directory = args.results_directory
    if results_directory is None:
        from tpu_render_cluster.analysis.paths import RESULTS_ROOT

        results_directory = RESULTS_ROOT / "chaos-runs"
    tile_grid = None
    if args.tiles:
        from tpu_render_cluster.jobs.tiles import parse_tile_grid

        tile_grid = parse_tile_grid(args.tiles)
    report = run_chaos_job(
        plan,
        frames=args.frames,
        results_directory=results_directory,
        timeout=args.timeout,
        tile_grid=tile_grid,
        # Operator runs get blackbox bundles beside the other artifacts;
        # an incident-free run writes none.
        flight_directory=results_directory,
    )
    print(json.dumps(report.to_dict(), indent=2))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
