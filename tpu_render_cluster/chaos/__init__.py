"""Deterministic chaos engine: seeded fault injection + exactly-once audit.

- ``plan`` — ``FaultPlan``: a reproducible, PCG-seeded schedule of
  transport / worker / master faults (env/TOML configurable);
- ``inject`` — the executors that turn plan events into runtime behavior
  at the three seams (``FaultyConnection`` wrapping, backend hooks, the
  master dispatch-delay shim);
- ``invariants`` — the exactly-once audit a faulted run must pass;
- ``runner`` — the harness (and ``python -m tpu_render_cluster.chaos.runner``
  CLI) that runs a real in-process cluster under a plan and audits it.
"""

from tpu_render_cluster.chaos.inject import MasterChaosHooks, WorkerChaosController
from tpu_render_cluster.chaos.invariants import check_invariants, ledger_stats
from tpu_render_cluster.chaos.plan import ChaosTimings, FaultEvent, FaultPlan
from tpu_render_cluster.chaos.runner import ChaosReport, run_chaos_job

__all__ = [
    "ChaosReport",
    "ChaosTimings",
    "FaultEvent",
    "FaultPlan",
    "MasterChaosHooks",
    "WorkerChaosController",
    "check_invariants",
    "ledger_stats",
    "run_chaos_job",
]
