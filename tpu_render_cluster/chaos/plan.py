"""Seeded, reproducible fault plans.

A ``FaultPlan`` is pure data: a tuple of ``FaultEvent``s derived from one
PCG64 stream, so the *schedule* (which worker slot suffers which fault,
when, with what parameters) is bit-identical across runs of the same seed
— re-running a failed chaos run replays the exact same faults. Runtime
interleaving naturally still varies; the invariants asserted by
``chaos/invariants.py`` are written to hold under every interleaving of a
given schedule.

Plans address workers by **slot** (their index in the harness's backend
list), not by worker id — ids are random per process. The runner maps
slots to live workers at startup.

Configuration surfaces, mirroring the repo's ``TRC_*`` idiom:

- ``FaultPlan.generate(seed, workers, ...)`` — the seeded generator;
- ``FaultPlan.from_toml(path)`` — an explicit or generated plan from TOML;
- ``FaultPlan.from_env()`` — ``TRC_CHAOS_PLAN`` (TOML path) or
  ``TRC_CHAOS_SEED``/``TRC_CHAOS_WORKERS`` for a generated default plan.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field, fields
from pathlib import Path
from typing import Any

import numpy as np
from tpu_render_cluster.utils.env import env_int, env_str

try:
    import tomllib
except ImportError:  # Python 3.10
    import tomli as tomllib  # type: ignore[no-redef]

# -- fault vocabulary --------------------------------------------------------

# Transport faults (executed by transport/faults.py via chaos/inject.py).
KIND_DROP_SEND = "drop_send"
KIND_DELAY_SEND = "delay_send"
KIND_DUPLICATE_SEND = "duplicate_send"
KIND_KILL_SOCKET = "kill_socket"
KIND_PARTITION = "partition"
# Worker faults (executed by worker/backends/chaos.py + the controller).
KIND_CRASH_BEFORE_RESULT = "crash_before_result"
KIND_CRASH_AFTER_RESULT = "crash_after_result"
KIND_SLOW_RENDER = "slow_render"
KIND_HANG = "hang"
KIND_DRAIN = "drain"
# Master faults (executed by the dispatch-delay shim in worker_handle.py).
KIND_DELAY_DISPATCH = "delay_dispatch"
# Control-plane faults (executed by the failover harness, ha/chaos.py):
# the TARGET is the master, addressed by the ``MASTER_TARGET`` sentinel
# rather than a worker slot.
KIND_MASTER_KILL = "master_kill"
KIND_MASTER_PARTITION = "master_partition"
# Replication/router faults (executed by the replicated-failover harness,
# ha/chaos.py): the replication stream severed mid-flight, the shard
# router itself killed and restarted, and a follower artificially lagged
# (per-record apply delay) so promotion picks among unequal replicas.
KIND_REPLICATION_PARTITION = "replication_partition"
KIND_ROUTER_KILL = "router_kill"
KIND_FOLLOWER_LAG = "follower_lag"

# Slot sentinel for faults aimed at the master process itself.
MASTER_TARGET = -1

ALL_KINDS = (
    KIND_DROP_SEND,
    KIND_DELAY_SEND,
    KIND_DUPLICATE_SEND,
    KIND_KILL_SOCKET,
    KIND_PARTITION,
    KIND_CRASH_BEFORE_RESULT,
    KIND_CRASH_AFTER_RESULT,
    KIND_SLOW_RENDER,
    KIND_HANG,
    KIND_DRAIN,
    KIND_DELAY_DISPATCH,
    KIND_MASTER_KILL,
    KIND_MASTER_PARTITION,
    KIND_REPLICATION_PARTITION,
    KIND_ROUTER_KILL,
    KIND_FOLLOWER_LAG,
)

MASTER_KINDS = (KIND_MASTER_KILL, KIND_MASTER_PARTITION)
REPLICATION_KINDS = (
    KIND_REPLICATION_PARTITION,
    KIND_ROUTER_KILL,
    KIND_FOLLOWER_LAG,
)

FINISHED_EVENT_TYPE = "event_frame-queue_item-finished"
RENDERING_EVENT_TYPE = "event_frame-queue_item-started-rendering"


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault. Which fields matter depends on ``kind``:

    - time-triggered kinds (``partition``, ``drain``) fire ``at_seconds``
      after the cluster starts, ``partition`` for ``duration_seconds``;
    - send-triggered kinds (``drop/delay/duplicate_send``, ``kill_socket``)
      fire on the ``nth`` outgoing message whose wire tag equals
      ``match_message_type`` (``None`` matches every message);
      ``delay_send`` stalls that send for ``duration_seconds``;
    - render-triggered kinds (``crash_before/after_result``, ``hang``)
      fire on the ``nth`` frame that worker renders; ``slow_render``
      stretches every render by ``multiplier``;
    - ``delay_dispatch`` (master side) stalls the ``nth`` queue-add RPC to
      that slot's worker by ``duration_seconds``.

    ``causes_eviction`` is the generator's declaration that this fault is
    expected to get the worker evicted — the invariant checker compares
    ``master_worker_evictions_total`` against the plan's sum.
    """

    kind: str
    target: int
    at_seconds: float = 0.0
    duration_seconds: float = 0.0
    nth: int = 1
    multiplier: float = 1.0
    match_message_type: str | None = None
    causes_eviction: bool = False

    def __post_init__(self) -> None:
        if self.kind not in ALL_KINDS:
            # List the vocabulary: a typo'd kind in a TOML plan must fail
            # loudly at load time with the fix in the message, not produce
            # a plan whose fault silently never fires.
            raise ValueError(
                f"Unknown fault kind: {self.kind!r}. "
                f"Valid kinds: {', '.join(ALL_KINDS)}"
            )

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "FaultEvent":
        allowed = {f.name for f in fields(cls)}
        unknown = set(data) - allowed
        if unknown:
            raise ValueError(f"Unknown fault event field(s): {sorted(unknown)}")
        return cls(**data)


@dataclass(frozen=True)
class ChaosTimings:
    """Cluster timeout profile a chaos run executes under.

    Production defaults (heartbeats every 10 s, 60 s pong budget) would
    stretch every fault scenario to minutes; the chaos runner temporarily
    compresses them to these values — via the same ``TRC_*`` overrides and
    module constants a real deployment would tune — and restores the
    originals afterwards. The *plan generator* also reads them: an
    eviction-driving ``delay_send`` must out-stall the heartbeat budget,
    and a survivable ``partition`` must fit inside it.
    """

    heartbeat_interval: float = 0.15
    heartbeat_response_timeout: float = 1.2
    heartbeat_pong_retries: int = 1
    max_wait_for_reconnect: float = 2.0
    backoff_base: float = 1.5
    backoff_cap_seconds: float = 0.25
    max_connect_retries: int = 80
    max_reconnects_per_op: int = 80
    op_deadline_seconds: float = 12.0
    send_deadline_seconds: float = 5.0
    rpc_deadline_seconds: float = 4.0

    def eviction_latency_seconds(self) -> float:
        """Worst-case heartbeat path from silence to eviction."""
        return (
            (self.heartbeat_pong_retries + 1) * self.heartbeat_response_timeout
            + self.heartbeat_interval
        )

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ChaosTimings":
        allowed = {f.name for f in fields(cls)}
        unknown = set(data) - allowed
        if unknown:
            raise ValueError(f"Unknown timing field(s): {sorted(unknown)}")
        return cls(**data)


@dataclass(frozen=True)
class FaultPlan:
    """A complete, reproducible chaos schedule for one cluster run."""

    seed: int
    workers: int
    events: tuple[FaultEvent, ...] = ()
    timings: ChaosTimings = field(default_factory=ChaosTimings)

    # -- queries -------------------------------------------------------------

    def events_for(self, slot: int) -> tuple[FaultEvent, ...]:
        return tuple(e for e in self.events if e.target == slot)

    def master_events(self) -> tuple[FaultEvent, ...]:
        """Control-plane faults (master kill / partition), schedule order."""
        return tuple(
            sorted(
                (e for e in self.events if e.kind in MASTER_KINDS),
                key=lambda e: e.at_seconds,
            )
        )

    def replication_events(self) -> tuple[FaultEvent, ...]:
        """Replication-plane faults (stream partition, router kill,
        follower lag), schedule order."""
        return tuple(
            sorted(
                (e for e in self.events if e.kind in REPLICATION_KINDS),
                key=lambda e: e.at_seconds,
            )
        )

    def expected_evictions(self) -> int:
        return sum(1 for e in self.events if e.causes_eviction)

    def expected_drains(self) -> int:
        return sum(1 for e in self.events if e.kind == KIND_DRAIN)

    def kinds(self) -> set[str]:
        return {e.kind for e in self.events}

    def fingerprint(self) -> str:
        """Stable digest of the schedule — equal iff the schedules are."""
        canonical = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha256(canonical.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> dict[str, Any]:
        return {
            "seed": self.seed,
            "workers": self.workers,
            "events": [e.to_dict() for e in self.events],
            "timings": self.timings.to_dict(),
        }

    # -- construction --------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "FaultPlan":
        return cls(
            seed=int(data["seed"]),
            workers=int(data["workers"]),
            events=tuple(FaultEvent.from_dict(e) for e in data.get("events", [])),
            timings=ChaosTimings.from_dict(data.get("timings", {})),
        )

    @classmethod
    def generate(
        cls,
        seed: int,
        workers: int = 3,
        *,
        timings: ChaosTimings | None = None,
        kills: int = 1,
        partitions: int = 1,
        duplicate_sends: int = 1,
        stragglers: int = 1,
        wedges: int = 1,
        drops: int = 1,
        dispatch_delays: int = 1,
        hangs: int = 0,
        drains: int = 0,
        master_kills: int = 0,
        master_partitions: int = 0,
        replication_partitions: int = 0,
        router_kills: int = 0,
        follower_lags: int = 0,
    ) -> "FaultPlan":
        """Roll a schedule from one PCG64 stream.

        Role placement keeps the run completable: every fault that removes
        a worker (kill / hang / wedge-eviction / drain) lands on a distinct
        slot, at least one slot stays alive to the end, and survivable
        faults (partition, straggler, duplicate, drop, dispatch delay) are
        placed on surviving slots so their effects stay observable.
        """
        timings = timings if timings is not None else ChaosTimings()
        lethal = kills + hangs + wedges + drains
        if lethal >= workers:
            raise ValueError(
                f"{lethal} worker-removing fault(s) need at least "
                f"{lethal + 1} workers; got {workers}."
            )
        rng = np.random.Generator(np.random.PCG64(seed))
        order = [int(s) for s in rng.permutation(workers)]
        doomed, survivors = order[:lethal], order[lethal:]

        def survivor(i: int) -> int:
            return survivors[i % len(survivors)]

        events: list[FaultEvent] = []
        cursor = 0
        for _ in range(kills):
            events.append(
                FaultEvent(
                    kind=(
                        KIND_CRASH_BEFORE_RESULT
                        if rng.random() < 0.5
                        else KIND_CRASH_AFTER_RESULT
                    ),
                    target=doomed[cursor],
                    nth=int(rng.integers(2, 5)),
                    causes_eviction=True,
                )
            )
            cursor += 1
        for _ in range(hangs):
            events.append(
                FaultEvent(
                    kind=KIND_HANG,
                    target=doomed[cursor],
                    nth=int(rng.integers(2, 5)),
                    causes_eviction=True,
                )
            )
            cursor += 1
        for _ in range(wedges):
            # A finished-event send stalled well past the heartbeat budget:
            # the pong queue wedges behind it, the master evicts, the frame
            # is re-rendered elsewhere, and the stalled result finally lands
            # late — the duplicate-result race, driven end to end.
            events.append(
                FaultEvent(
                    kind=KIND_DELAY_SEND,
                    target=doomed[cursor],
                    nth=int(rng.integers(2, 4)),
                    duration_seconds=float(
                        timings.eviction_latency_seconds() * rng.uniform(1.8, 2.4)
                    ),
                    match_message_type=FINISHED_EVENT_TYPE,
                    causes_eviction=True,
                )
            )
            cursor += 1
        for _ in range(drains):
            events.append(
                FaultEvent(
                    kind=KIND_DRAIN,
                    target=doomed[cursor],
                    at_seconds=float(rng.uniform(0.8, 1.6)),
                )
            )
            cursor += 1
        for i in range(partitions):
            # Shorter than the pong budget and the master's reconnect wait:
            # the link heals, nobody is evicted, nothing is lost.
            events.append(
                FaultEvent(
                    kind=KIND_PARTITION,
                    target=survivor(i),
                    at_seconds=float(rng.uniform(0.6, 1.4)),
                    duration_seconds=float(
                        min(
                            timings.heartbeat_response_timeout,
                            timings.max_wait_for_reconnect,
                        )
                        * rng.uniform(0.35, 0.6)
                    ),
                )
            )
        for i in range(stragglers):
            events.append(
                FaultEvent(
                    kind=KIND_SLOW_RENDER,
                    target=survivor(partitions + i),
                    multiplier=float(rng.uniform(3.0, 5.0)),
                )
            )
        for i in range(duplicate_sends):
            events.append(
                FaultEvent(
                    kind=KIND_DUPLICATE_SEND,
                    target=survivor(i),
                    nth=int(rng.integers(1, 4)),
                    match_message_type=FINISHED_EVENT_TYPE,
                )
            )
        for i in range(drops):
            # Dropping a started-rendering event is survivable by design:
            # the master merely misses the queued->rendering transition.
            events.append(
                FaultEvent(
                    kind=KIND_DROP_SEND,
                    target=survivor(i + 1),
                    nth=int(rng.integers(1, 3)),
                    match_message_type=RENDERING_EVENT_TYPE,
                )
            )
        for i in range(dispatch_delays):
            events.append(
                FaultEvent(
                    kind=KIND_DELAY_DISPATCH,
                    target=survivor(i),
                    nth=int(rng.integers(1, 3)),
                    duration_seconds=float(rng.uniform(0.2, 0.5)),
                )
            )
        # Control-plane faults draw LAST so plans without them (every
        # pre-HA seed) keep a bit-identical schedule for the same seed.
        for _ in range(master_kills):
            events.append(
                FaultEvent(
                    kind=KIND_MASTER_KILL,
                    target=MASTER_TARGET,
                    at_seconds=float(rng.uniform(0.8, 1.4)),
                )
            )
        for _ in range(master_partitions):
            events.append(
                FaultEvent(
                    kind=KIND_MASTER_PARTITION,
                    target=MASTER_TARGET,
                    at_seconds=float(rng.uniform(0.4, 0.8)),
                )
            )
        # Replication-plane faults draw after the master faults, for the
        # same bit-identity reason: every pre-replication seed (including
        # failover plans with master faults) keeps its exact schedule.
        for _ in range(replication_partitions):
            # Severed before the master kill window (0.8+): the follower
            # must reconnect, gap-detect, and catch back up in time for
            # promotion to still find a current replica.
            events.append(
                FaultEvent(
                    kind=KIND_REPLICATION_PARTITION,
                    target=MASTER_TARGET,
                    at_seconds=float(rng.uniform(0.2, 0.6)),
                    duration_seconds=float(rng.uniform(0.1, 0.3)),
                )
            )
        for _ in range(router_kills):
            events.append(
                FaultEvent(
                    kind=KIND_ROUTER_KILL,
                    target=MASTER_TARGET,
                    at_seconds=float(rng.uniform(0.3, 0.7)),
                    duration_seconds=float(rng.uniform(0.2, 0.5)),
                )
            )
        for _ in range(follower_lags):
            events.append(
                FaultEvent(
                    kind=KIND_FOLLOWER_LAG,
                    target=MASTER_TARGET,
                    at_seconds=float(rng.uniform(0.1, 0.4)),
                    duration_seconds=float(rng.uniform(0.3, 0.8)),
                    multiplier=float(rng.uniform(0.005, 0.02)),
                )
            )
        return cls(
            seed=seed, workers=workers, events=tuple(events), timings=timings
        )

    @classmethod
    def generate_failover(cls, seed: int, workers: int = 3) -> "FaultPlan":
        """A failover-focused schedule: one master kill mid-job plus the
        survivable worker faults (straggler, duplicated result send,
        dropped rendering event) that keep the dedup seam honest while
        the standby adopts the pool. No worker-removing faults — every
        worker must survive to be re-adopted."""
        return cls.generate(
            seed,
            workers,
            kills=0,
            partitions=0,
            wedges=0,
            hangs=0,
            drains=0,
            duplicate_sends=1,
            stragglers=1,
            drops=1,
            dispatch_delays=0,
            master_kills=1,
            master_partitions=1,
        )

    @classmethod
    def generate_replicated_failover(cls, seed: int, workers: int = 3) -> "FaultPlan":
        """A cross-host failover schedule: the replication stream is
        severed and re-established mid-job, the follower is briefly
        lagged, and THEN the primary is killed — promotion must find a
        replica that caught back up over TCP, with no shared filesystem
        to fall back on. Worker faults stay survivable (straggler +
        duplicated result send) so the exactly-once seam is exercised
        across the promotion boundary."""
        return cls.generate(
            seed,
            workers,
            kills=0,
            partitions=0,
            wedges=0,
            hangs=0,
            drains=0,
            duplicate_sends=1,
            stragglers=1,
            drops=1,
            dispatch_delays=0,
            master_kills=1,
            master_partitions=0,
            replication_partitions=1,
            follower_lags=1,
        )

    @classmethod
    def generate_shard_kill(cls, seed: int, workers: int = 4) -> "FaultPlan":
        """A shard-death schedule for the two-shard router scenario: one
        shard's master is killed mid-run (its workers must re-home to the
        survivor through the router) and the router itself is bounced
        once (re-homing must ride out the window). Worker faults stay
        survivable so every worker lives to re-home and the survivor's
        dedup seam still sees a duplicated send."""
        return cls.generate(
            seed,
            workers,
            kills=0,
            partitions=0,
            wedges=0,
            hangs=0,
            drains=0,
            duplicate_sends=1,
            stragglers=1,
            drops=1,
            dispatch_delays=0,
            master_kills=1,
            master_partitions=0,
            router_kills=1,
        )

    @classmethod
    def from_toml(cls, path: str | Path) -> "FaultPlan":
        """Load a plan from TOML: explicit ``[[events]]``, or a seeded
        ``[generate]`` table (kills / partitions / ... counts)."""
        with open(path, "rb") as f:
            data = tomllib.load(f)
        seed = int(data.get("seed", 0))
        workers = int(data.get("workers", 3))
        timings = ChaosTimings.from_dict(data.get("timings", {}))
        if "events" in data:
            return cls(
                seed=seed,
                workers=workers,
                events=tuple(FaultEvent.from_dict(e) for e in data["events"]),
                timings=timings,
            )
        counts = {k: int(v) for k, v in data.get("generate", {}).items()}
        return cls.generate(seed, workers, timings=timings, **counts)

    @classmethod
    def from_env(cls) -> "FaultPlan":
        """``TRC_CHAOS_PLAN`` (TOML path) wins; else a generated plan from
        ``TRC_CHAOS_SEED`` / ``TRC_CHAOS_WORKERS`` (defaults 0 / 3)."""
        plan_path = env_str("TRC_CHAOS_PLAN")
        if plan_path:
            return cls.from_toml(plan_path)
        return cls.generate(
            env_int("TRC_CHAOS_SEED", 0),
            env_int("TRC_CHAOS_WORKERS", 3),
        )
