"""Plan-driven fault executors.

Two controllers turn a ``FaultPlan``'s pure-data events into runtime
behavior, one per side of the cluster link:

- ``WorkerChaosController`` — one per worker slot. It is simultaneously
  the ``FaultController`` behind that worker's ``FaultyConnection``
  (transport faults), the hook consulted by ``FaultyBackend`` (render
  faults), and the owner of a watchdog coroutine that fires the
  time-triggered faults (partitions, drains).
- ``MasterChaosHooks`` — the master-side dispatch-delay shim, keyed by
  worker id once the runner has mapped slots to live workers.

Every injected fault increments ``chaos_faults_injected_total{kind=...}``
in the owning component's metrics registry, so run artifacts (and the
``chaos`` section of statistics.json) record exactly what was done to the
cluster alongside what the cluster did about it.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import TYPE_CHECKING, Callable

from tpu_render_cluster.chaos.plan import (
    FINISHED_EVENT_TYPE,
    KIND_CRASH_AFTER_RESULT,
    KIND_CRASH_BEFORE_RESULT,
    KIND_DELAY_DISPATCH,
    KIND_DELAY_SEND,
    KIND_DRAIN,
    KIND_DROP_SEND,
    KIND_DUPLICATE_SEND,
    KIND_HANG,
    KIND_KILL_SOCKET,
    KIND_PARTITION,
    KIND_SLOW_RENDER,
    FaultEvent,
    FaultPlan,
)
from tpu_render_cluster.transport.faults import (
    PASS_DECISION,
    SEND_ACTION_DROP,
    SEND_ACTION_DUPLICATE,
    SEND_ACTION_KILL,
    FaultyConnection,
    SendDecision,
)
from tpu_render_cluster.transport.ws import WebSocketClosed, WebSocketConnection

if TYPE_CHECKING:
    from tpu_render_cluster.obs import MetricsRegistry

logger = logging.getLogger(__name__)

_SEND_KINDS = (
    KIND_DROP_SEND,
    KIND_DELAY_SEND,
    KIND_DUPLICATE_SEND,
    KIND_KILL_SOCKET,
)
_TIMED_KINDS = (KIND_PARTITION, KIND_DRAIN)


class _Pending:
    """One schedulable fault instance with its own match counter."""

    def __init__(self, event: FaultEvent) -> None:
        self.event = event
        self.seen = 0
        self.consumed = False

    def matches(self, text: str) -> bool:
        match = self.event.match_message_type
        return match is None or f'"message_type":"{match}"' in text


def _payload_frame_index(text: str) -> int | None:
    try:
        payload = json.loads(text).get("payload", {})
        index = payload.get("frame_index")
        return None if index is None else int(index)
    except (ValueError, AttributeError):
        return None


class WorkerChaosController:
    """Fault executor for one worker slot."""

    def __init__(
        self,
        slot: int,
        events: tuple[FaultEvent, ...],
        *,
        registry: "MetricsRegistry | None" = None,
    ) -> None:
        self.slot = slot
        self._events = events
        self._registry = registry
        self._send_faults = [_Pending(e) for e in events if e.kind in _SEND_KINDS]
        self._render_faults = [
            _Pending(e)
            for e in events
            if e.kind in (KIND_CRASH_BEFORE_RESULT, KIND_CRASH_AFTER_RESULT, KIND_HANG)
        ]
        self._slow_multiplier = 1.0
        self._slow_counted = False
        for event in events:
            if event.kind == KIND_SLOW_RENDER:
                self._slow_multiplier *= max(1.0, event.multiplier)
        self.killed = False
        self.silent = False
        self._partition_until = 0.0
        self._kill_after_frame: int | None = None
        self._current: FaultyConnection | None = None
        self._worker = None
        self._cancel_worker: Callable[[], None] | None = None

    # -- wiring (chaos/runner.py) -------------------------------------------

    def attach(self, worker, cancel_worker: Callable[[], None]) -> None:
        """Give the controller its live worker + a task-cancel callback."""
        self._worker = worker
        self._cancel_worker = cancel_worker

    def wrap_connection(self, ws: WebSocketConnection) -> FaultyConnection:
        """The ``wrap`` hook for ``connect_with_exponential_backoff``."""
        self.check_gate(raw=ws)
        connection = FaultyConnection(ws, self)
        self._current = connection
        return connection

    async def run_timed_faults(self) -> None:
        """Fire partitions/drains at their scheduled offsets (watchdog)."""
        loop = asyncio.get_running_loop()
        start = loop.time()
        for event in sorted(
            (e for e in self._events if e.kind in _TIMED_KINDS),
            key=lambda e: e.at_seconds,
        ):
            await asyncio.sleep(max(0.0, start + event.at_seconds - loop.time()))
            if event.kind == KIND_PARTITION:
                self._count(KIND_PARTITION)
                logger.info(
                    "chaos: partitioning slot %d for %.2f s",
                    self.slot,
                    event.duration_seconds,
                )
                self._partition_until = loop.time() + event.duration_seconds
                if self._current is not None:
                    self._current.abort()
            elif event.kind == KIND_DRAIN:
                self._count(KIND_DRAIN)
                logger.info("chaos: draining slot %d", self.slot)
                if self._worker is not None:
                    self._worker.request_drain()

    # -- FaultController (transport/faults.py) ------------------------------

    def check_gate(self, raw: WebSocketConnection | None = None) -> None:
        loop = asyncio.get_running_loop()
        if self.killed or self.silent or loop.time() < self._partition_until:
            if raw is not None:
                raw.abort()
            reason = "worker killed" if self.killed or self.silent else "partition"
            raise WebSocketClosed(f"chaos: {reason} (slot {self.slot})")

    def on_send(self, text: str) -> SendDecision:
        # Every matching fault's ordinal counter advances on every match —
        # even when another fault fires first on this message — so each
        # nth trigger lands exactly where the plan's schedule declares.
        # One fault acts per send (first in schedule order); a fault whose
        # ordinal was reached on a message another consumed fires on the
        # next match (hence >=).
        fired: _Pending | None = None
        for pending in self._send_faults:
            if pending.consumed or not pending.matches(text):
                continue
            pending.seen += 1
            if fired is None and pending.seen >= pending.event.nth:
                fired = pending
        if fired is None:
            return PASS_DECISION
        fired.consumed = True
        kind = fired.event.kind
        self._count(kind)
        logger.info("chaos: %s fired on slot %d", kind, self.slot)
        if kind == KIND_DROP_SEND:
            return SendDecision(SEND_ACTION_DROP)
        if kind == KIND_DUPLICATE_SEND:
            return SendDecision(SEND_ACTION_DUPLICATE)
        if kind == KIND_KILL_SOCKET:
            return SendDecision(SEND_ACTION_KILL)
        return SendDecision(delay_seconds=fired.event.duration_seconds)

    def after_send(self, text: str) -> None:
        if self._kill_after_frame is None:
            return
        if f'"message_type":"{FINISHED_EVENT_TYPE}"' not in text:
            return
        if _payload_frame_index(text) != self._kill_after_frame:
            return
        self._kill_after_frame = None
        self.kill_now(KIND_CRASH_AFTER_RESULT)

    # -- FaultyBackend hooks (worker/backends/chaos.py) ----------------------

    def render_multiplier(self) -> float:
        if self._slow_multiplier > 1.0 and not self._slow_counted:
            self._slow_counted = True
            self._count(KIND_SLOW_RENDER)
        return self._slow_multiplier

    def note_render_start(self, frame_index: int, ordinal: int) -> None:
        for pending in self._render_faults:
            if (
                not pending.consumed
                and pending.event.kind == KIND_CRASH_BEFORE_RESULT
                and ordinal == pending.event.nth
            ):
                pending.consumed = True
                self.kill_now(KIND_CRASH_BEFORE_RESULT)

    def note_render_done(self, frame_index: int, ordinal: int) -> None:
        for pending in self._render_faults:
            if (
                not pending.consumed
                and pending.event.kind == KIND_CRASH_AFTER_RESULT
                and ordinal == pending.event.nth
            ):
                pending.consumed = True
                # Armed: the kill fires the instant the finished event for
                # this frame clears the socket (after_send above) — "crash
                # after sending a frame result", with zero timing slack.
                self._kill_after_frame = frame_index

    def should_hang(self, ordinal: int) -> bool:
        for pending in self._render_faults:
            if (
                not pending.consumed
                and pending.event.kind == KIND_HANG
                and ordinal == pending.event.nth
            ):
                pending.consumed = True
                self._count(KIND_HANG)
                logger.info("chaos: hanging slot %d", self.slot)
                self.silent = True
                if self._current is not None:
                    self._current.abort()
                return True
        return False

    # -- kill mechanics ------------------------------------------------------

    def kill_now(self, kind: str) -> None:
        """Crash the worker: dead socket, no reconnect, task cancelled."""
        if self.killed:
            return
        self.killed = True
        self._count(kind)
        logger.info("chaos: killing slot %d (%s)", self.slot, kind)
        if self._current is not None:
            self._current.abort()
        if self._cancel_worker is not None:
            self._cancel_worker()

    def _count(self, kind: str) -> None:
        if self._registry is not None:
            self._registry.counter(
                "chaos_faults_injected_total",
                "Faults the chaos engine injected, by kind",
                labels=("kind",),
            ).inc(kind=kind)


class MasterChaosHooks:
    """Master-side faults: the assignment dispatch-delay shim.

    ``dispatch_delay`` is handed to ``ClusterManager`` and consulted at
    the top of every ``WorkerHandle.queue_frame``; it returns how long to
    stall that dispatch (0.0 almost always). Slot mapping arrives late —
    worker ids are random — via ``map_worker``.
    """

    def __init__(
        self, plan: FaultPlan, *, registry: "MetricsRegistry | None" = None
    ) -> None:
        self._registry = registry
        self._pending_by_slot: dict[int, list[_Pending]] = {}
        for event in plan.events:
            if event.kind == KIND_DELAY_DISPATCH:
                self._pending_by_slot.setdefault(event.target, []).append(
                    _Pending(event)
                )
        self._slot_by_worker_id: dict[int, int] = {}

    def map_worker(self, worker_id: int, slot: int) -> None:
        self._slot_by_worker_id[worker_id] = slot

    def dispatch_delay(self, worker_id: int, frame_index: int) -> float:
        slot = self._slot_by_worker_id.get(worker_id)
        if slot is None:
            return 0.0
        # Same ordinal contract as WorkerChaosController.on_send: every
        # pending fault's counter advances on every dispatch, one fault
        # acts per dispatch, and a fault whose ordinal was reached while
        # another fired acts on the next dispatch (hence >=).
        fired: _Pending | None = None
        for pending in self._pending_by_slot.get(slot, []):
            if pending.consumed:
                continue
            pending.seen += 1
            if fired is None and pending.seen >= pending.event.nth:
                fired = pending
        if fired is None:
            return 0.0
        fired.consumed = True
        if self._registry is not None:
            self._registry.counter(
                "chaos_faults_injected_total",
                "Faults the chaos engine injected, by kind",
                labels=("kind",),
            ).inc(kind=KIND_DELAY_DISPATCH)
        logger.info(
            "chaos: delaying dispatch of frame %d to slot %d by %.2f s",
            frame_index,
            slot,
            fired.event.duration_seconds,
        )
        return fired.event.duration_seconds
