"""Exactly-once invariants a cluster must hold under any fault schedule.

``check_invariants`` inspects a finished run (the live ``ClusterManager``
plus its metrics) against the plan that tormented it and returns a list of
human-readable violations (empty = the cluster survived correctly):

1.  **Completion** — every frame reached FINISHED and the O(1) finished
    counter agrees with the frame table.
2.  **Exactly-once ledger** — ``ok_results - duplicate_results`` equals
    the frame count: every frame was counted finished exactly once, and
    every extra ok delivery (duplicated send, late result from an evicted
    worker whose frame was re-rendered elsewhere) was explicitly absorbed
    by the dedup path rather than double-counted.
3.  **No ghost assignments** — no worker handle (dead or alive) still
    mirrors a frame: eviction, drain, steals, and finished events must
    between them sweep every queue mirror clean.
4.  **Eviction/drain accounting** — ``master_worker_evictions_total`` and
    ``master_worker_drains_total`` match exactly what the plan injected:
    kills and wedges evict, drains drain, and nothing else (a healed
    partition, a straggler, a duplicated send) may cost a worker.
5.  **Duplicate visibility** — when the plan duplicated a result send,
    the dedup counter must show it was seen and absorbed.
6.  **Trace validity** — the merged cluster timeline (when given) holds
    every structural invariant in ``obs/validate.py``: even a run that
    lost workers mid-flight must export a Perfetto file whose flows all
    resolve.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Iterable

from tpu_render_cluster.chaos.plan import KIND_DUPLICATE_SEND, FaultPlan
from tpu_render_cluster.master.state import ClusterManagerState, FrameStatus

if TYPE_CHECKING:
    from tpu_render_cluster.master.cluster import ClusterManager
    from tpu_render_cluster.master.worker_handle import WorkerHandle

__all__ = [
    "check_invariants",
    "check_job_invariants",
    "check_multi_job_invariants",
    "check_tile_invariants",
    "counter_total",
    "ledger_stats",
]


def counter_total(
    snapshot: dict[str, Any], name: str, label: str | None = None
) -> float:
    """Sum a counter's series from a ``MetricsRegistry.snapshot()`` dict."""
    entry = snapshot.get(name)
    if not entry:
        return 0.0
    series = entry.get("series", {})
    if label is not None:
        return float(series.get(label, 0.0))
    return sum(float(v) for v in series.values())


def ledger_stats(snapshot: dict[str, Any]) -> dict[str, float]:
    """The master-side exactly-once ledger, as one flat dict."""
    return {
        "ok_results": counter_total(
            snapshot, "master_frame_results_total", "result=ok"
        ),
        "errored_results": counter_total(
            snapshot, "master_frame_results_total", "result=errored"
        ),
        "duplicate_results": counter_total(
            snapshot, "master_duplicate_results_total"
        ),
        "late_results": counter_total(snapshot, "master_late_results_total"),
        "stale_results": counter_total(snapshot, "master_stale_results_total"),
        "evictions": counter_total(snapshot, "master_worker_evictions_total"),
        "drains": counter_total(snapshot, "master_worker_drains_total"),
    }


def check_tile_invariants(
    state: ClusterManagerState, *, expect_complete: bool = True
) -> list[str]:
    """The tile-grain exactly-once audit of one job's assembly ledger.

    For a tiled job the unit equation (ok - duplicates == units_total,
    checked by the callers) already proves each TILE landed exactly once;
    this adds the FRAME-level shape on top:

    - a completed job assembled every frame exactly once
      (``frames_assembled == frame_count``);
    - no frame is left PARTIALLY assembled — some tiles landed, some
      not — after a completed run (cancel legitimately strands partial
      frames mid-flight, so only the assembled-count monotone bound is
      checked there): the no-ghost-frame guarantee.
    """
    if state.job.tiles_per_frame() == 1:
        return []
    violations: list[str] = []
    frame_count = state.job.frame_count()
    if expect_complete:
        partial = state.partially_assembled_frames()
        if partial:
            violations.append(
                f"tiles: {len(partial)} frame(s) partially assembled after "
                f"a completed run: {partial[:10]}"
            )
        if state.frames_assembled != frame_count:
            violations.append(
                f"tiles: frames_assembled {state.frames_assembled} != "
                f"frame count {frame_count} — a frame assembled twice or "
                "never"
            )
    elif state.frames_assembled > frame_count:
        violations.append(
            f"tiles: frames_assembled {state.frames_assembled} exceeds the "
            f"frame count {frame_count}"
        )
    return violations


def check_job_invariants(
    state: ClusterManagerState,
    workers: "Iterable[WorkerHandle]",
    *,
    expect_complete: bool = True,
) -> list[str]:
    """The PER-JOB exactly-once audit, over one job's frame table + ledger.

    The multi-job analog of invariants 1-3: with several jobs sharing the
    pool, the global metrics counters aggregate across jobs, so each
    ``ClusterManagerState`` carries its own ledger (master/state.py) and
    is audited here. ``expect_complete=False`` relaxes the completion +
    exactly-once-count checks for cancelled jobs (which legitimately end
    with unfinished frames) while still requiring their mirrors swept —
    cancel must release workers with no ghost assignments.
    """
    violations: list[str] = []
    job_name = state.job.job_name
    total = len(state.frames)
    if expect_complete:
        unfinished = sorted(
            (unit for unit, record in state.frames.items()
             if record.status is not FrameStatus.FINISHED),
            key=lambda u: u.sort_key,
        )
        if unfinished:
            violations.append(
                f"completion: {len(unfinished)} unit(s) not FINISHED: "
                f"{[u.label for u in unfinished[:10]]}"
            )
        if state.finished_count() != total:
            violations.append(
                f"completion: finished_count {state.finished_count()} != "
                f"frame table size {total}"
            )
        delivered_once = (
            state.ledger["ok_results"] - state.ledger["duplicate_results"]
        )
        if delivered_once != total:
            violations.append(
                "exactly-once: ok_results - duplicate_results = "
                f"{state.ledger['ok_results']} - "
                f"{state.ledger['duplicate_results']} = {delivered_once}, "
                f"expected {total} (frame table size)"
            )
    violations.extend(
        check_tile_invariants(state, expect_complete=expect_complete)
    )
    for worker in workers:
        ghosts = sorted(
            (f.unit for f in worker.queue.frames_for_job(job_name)),
            key=lambda u: u.sort_key,
        )
        if ghosts:
            violations.append(
                f"ghost assignments: worker {worker.worker_id:08x} still "
                f"mirrors unit(s) {[u.label for u in ghosts[:10]]} of job "
                f"{job_name!r}"
            )
    return violations


def check_multi_job_invariants(
    manager: "ClusterManager",
    plan: FaultPlan,
    *,
    cluster_trace_document: Any | None = None,
) -> list[str]:
    """The fault-run audit for a scheduler (sched.JobManager) cluster.

    Runs ``check_job_invariants`` per submission (completion expected for
    finished jobs, ghost-sweep only for cancelled ones), plus the global
    eviction/drain accounting and trace validity of ``check_invariants``
    (the global ok-dup equation is per-job here: cancelled jobs' stale
    results make the aggregate equation meaningless by design).
    """
    from tpu_render_cluster.sched.models import JOB_CANCELLED, JOB_FINISHED

    violations: list[str] = []
    runs = getattr(manager, "_runs", {})
    for job_id, run in runs.items():
        if run.state is None:
            continue
        expect_complete = run.status == JOB_FINISHED
        if run.status not in (JOB_FINISHED, JOB_CANCELLED):
            violations.append(
                f"{job_id}: job ended the run in state {run.status!r}"
            )
        for problem in check_job_invariants(
            run.state, manager.workers.values(), expect_complete=expect_complete
        ):
            violations.append(f"{job_id}: {problem}")

    snapshot = manager.metrics.snapshot()
    ledger = ledger_stats(snapshot)
    expected_evictions = plan.expected_evictions()
    if ledger["evictions"] != expected_evictions:
        violations.append(
            f"evictions: master_worker_evictions_total = "
            f"{ledger['evictions']:.0f}, plan injected {expected_evictions} "
            f"eviction-causing fault(s)"
        )
    expected_drains = plan.expected_drains()
    if ledger["drains"] != expected_drains:
        violations.append(
            f"drains: master_worker_drains_total = {ledger['drains']:.0f}, "
            f"plan injected {expected_drains} drain(s)"
        )
    absorbed = (
        ledger["duplicate_results"]
        + ledger["late_results"]
        + ledger["stale_results"]
    )
    if KIND_DUPLICATE_SEND in plan.kinds() and absorbed < 1:
        # Weaker than the single-job check on purpose: with several jobs
        # sharing fewer slots each, the re-dispatch races shift — a
        # duplicated result's twin may legally be absorbed as a LATE or
        # STALE event instead of a duplicate (e.g. the delayed original
        # lands before the requeued copy ever re-renders). What must
        # never happen is the twin silently double-counting a finish —
        # that is what the per-job ok-dup equations above pin down; this
        # check only proves the dedup seam SAW an out-of-band result.
        violations.append(
            "duplicate visibility: plan duplicated a result send but no "
            "duplicate/late/stale result was ever recorded — the twin was "
            "never seen (or was double-counted as a fresh finish)"
        )
    if cluster_trace_document is not None:
        from tpu_render_cluster.obs import validate_trace_document

        problems = validate_trace_document(cluster_trace_document)
        for problem in problems[:10]:
            violations.append(f"cluster trace: {problem}")
    return violations


def check_invariants(
    manager: "ClusterManager",
    plan: FaultPlan,
    *,
    cluster_trace_document: Any | None = None,
) -> list[str]:
    violations: list[str] = []
    state = manager.state
    total = len(state.frames)

    unfinished = sorted(
        (unit for unit, record in state.frames.items()
         if record.status is not FrameStatus.FINISHED),
        key=lambda u: u.sort_key,
    )
    if unfinished:
        violations.append(
            f"completion: {len(unfinished)} unit(s) not FINISHED: "
            f"{[u.label for u in unfinished[:10]]}"
        )
    if state.finished_count() != total:
        violations.append(
            f"completion: finished_count {state.finished_count()} != "
            f"unit table size {total}"
        )

    snapshot = manager.metrics.snapshot()
    ledger = ledger_stats(snapshot)
    delivered_once = ledger["ok_results"] - ledger["duplicate_results"]
    if delivered_once != total:
        violations.append(
            "exactly-once: ok_results - duplicate_results = "
            f"{ledger['ok_results']:.0f} - {ledger['duplicate_results']:.0f} "
            f"= {delivered_once:.0f}, expected {total} (frame table size)"
        )

    violations.extend(check_tile_invariants(state))

    for worker in manager.workers.values():
        if len(worker.queue) > 0:
            ghosts = sorted(
                (f.unit for f in worker.queue.all_frames()),
                key=lambda u: u.sort_key,
            )
            violations.append(
                f"ghost assignments: worker {worker.worker_id:08x} "
                f"({'dead' if worker.is_dead else 'alive'}) still mirrors "
                f"unit(s) {[u.label for u in ghosts[:10]]}"
            )

    expected_evictions = plan.expected_evictions()
    if ledger["evictions"] != expected_evictions:
        violations.append(
            f"evictions: master_worker_evictions_total = "
            f"{ledger['evictions']:.0f}, plan injected {expected_evictions} "
            f"eviction-causing fault(s)"
        )
    expected_drains = plan.expected_drains()
    if ledger["drains"] != expected_drains:
        violations.append(
            f"drains: master_worker_drains_total = {ledger['drains']:.0f}, "
            f"plan injected {expected_drains} drain(s)"
        )
    drained_handles = sum(
        1 for worker in manager.workers.values() if worker.drained
    )
    if drained_handles != expected_drains:
        violations.append(
            f"drains: {drained_handles} worker handle(s) took the goodbye "
            f"path, plan injected {expected_drains} drain(s) — a drain "
            f"collapsed into an eviction (or vice versa)"
        )

    if KIND_DUPLICATE_SEND in plan.kinds() and ledger["duplicate_results"] < 1:
        violations.append(
            "duplicate visibility: plan duplicated a result send but "
            "master_duplicate_results_total is 0 — the duplicate was never "
            "seen (or was double-counted as a fresh finish)"
        )

    if cluster_trace_document is not None:
        from tpu_render_cluster.obs import validate_trace_document

        problems = validate_trace_document(cluster_trace_document)
        for problem in problems[:10]:
            violations.append(f"cluster trace: {problem}")

    return violations
