"""NTP-style per-worker clock-offset estimation from heartbeat pings.

Master and worker timestamps live on different wall clocks; merging their
span timelines into one causal view (``obs/timeline.py``) needs the offset
between them. Every heartbeat already crosses the wire twice with a
fractional-unix timestamp on each leg, which is exactly the classic NTP
four-timestamp exchange:

    t1  master sends the ping        (master clock — the ping's request_time)
    t2  worker receives the ping     (worker clock)
    t3  worker sends the pong        (worker clock)
    t4  master receives the pong     (master clock)

    offset = ((t2 - t1) + (t3 - t4)) / 2      (worker clock - master clock)
    delay  = (t4 - t1) - (t3 - t2)            (round trip minus worker hold)

The offset estimate's error is bounded by the *asymmetry* of the two
network legs (at most delay/2), so single samples jitter by the scheduling
noise of both event loops. ``ClockOffsetEstimator`` keeps a sliding window
of samples and reports the window median — robust to the occasional
GC-pause outlier — plus a least-squares drift slope so slow clock skew
(crystal drift, NTP slewing on one host) is visible as a rate.
"""

from __future__ import annotations

import math
from collections import deque

__all__ = ["ClockOffsetEstimator", "ntp_offset_and_delay"]


def ntp_offset_and_delay(
    t1: float, t2: float, t3: float, t4: float
) -> tuple[float, float]:
    """The classic NTP estimate from one four-timestamp exchange.

    Returns ``(offset, delay)`` where ``offset`` is (worker clock -
    master clock) in seconds and ``delay`` is the network round trip
    excluding the worker's hold time (clamped at 0 against clock noise).
    """
    offset = ((t2 - t1) + (t3 - t4)) / 2.0
    delay = max(0.0, (t4 - t1) - (t3 - t2))
    return offset, delay


class ClockOffsetEstimator:
    """Online median-of-window offset estimator with drift tracking.

    One instance per worker, held by the master's ``WorkerHandle`` and fed
    from the heartbeat loop. Thread-free by design: all mutation happens on
    the master's event loop.
    """

    def __init__(self, window: int = 16) -> None:
        if window < 1:
            raise ValueError("window must be >= 1")
        # (sample midpoint on the master clock, offset, delay) triples.
        self._samples: deque[tuple[float, float, float]] = deque(maxlen=window)

    def add_ping(self, t1: float, t2: float, t3: float, t4: float) -> float:
        """Fold one ping exchange in; returns that sample's raw offset."""
        offset, delay = ntp_offset_and_delay(t1, t2, t3, t4)
        self._samples.append(((t1 + t4) / 2.0, offset, delay))
        return offset

    @property
    def sample_count(self) -> int:
        return len(self._samples)

    @property
    def last_delay(self) -> float:
        """Network delay of the most recent sample (0.0 with no samples)."""
        return self._samples[-1][2] if self._samples else 0.0

    def offset(self) -> float:
        """Median offset over the window (worker - master, seconds).

        0.0 with no samples — a worker that never reported timestamps
        (e.g. the C++ daemon's reference-shaped empty pong) merges into
        the cluster timeline unshifted.
        """
        if not self._samples:
            return 0.0
        offsets = sorted(s[1] for s in self._samples)
        mid = len(offsets) // 2
        if len(offsets) % 2:
            return offsets[mid]
        return (offsets[mid - 1] + offsets[mid]) / 2.0

    def _drift_fit(self) -> tuple[float, float] | None:
        """Least-squares (reference time, slope) of offset vs master time."""
        if len(self._samples) < 2:
            return None
        times = [s[0] for s in self._samples]
        offsets = [s[1] for s in self._samples]
        t_mean = sum(times) / len(times)
        o_mean = sum(offsets) / len(offsets)
        var = sum((t - t_mean) ** 2 for t in times)
        if var <= 0.0:
            return None
        cov = sum(
            (t - t_mean) * (o - o_mean) for t, o in zip(times, offsets)
        )
        return t_mean, cov / var

    def drift(self) -> float:
        """Offset slope in seconds per second (0.0 until two samples)."""
        fit = self._drift_fit()
        return fit[1] if fit is not None else 0.0

    def drift_ppm(self) -> float:
        """Drift expressed as parts-per-million, the usual crystal unit."""
        return self.drift() * 1e6

    def offset_at(self, t: float) -> float:
        """Offset extrapolated to master time ``t`` using the drift fit.

        Anchored at the window's median offset (robust) and slid along the
        least-squares slope; with fewer than two samples this degrades to
        the plain median.
        """
        fit = self._drift_fit()
        base = self.offset()
        if fit is None:
            return base
        t_mean, slope = fit
        out = base + slope * (t - t_mean)
        return out if math.isfinite(out) else base
