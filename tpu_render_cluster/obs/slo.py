"""Per-job SLO engine: attainment + multi-window burn rate, online.

Jobs declare objectives in their TOML (``[slo]`` table ->
``jobs.models.JobSlo``); the master tracks them live off the same
per-unit winning-result latency stream that feeds
``master_unit_latency_seconds`` (worker_handle._record_winning_result):

- **attainment**: the fraction of units meeting the latency objective,
  cumulative over the job;
- **burn ratio**: over each sliding window, the violation fraction
  divided by the error budget (a p99 objective leaves a 1% budget) — a
  burn of 1.0 means the budget is being consumed exactly as fast as it
  accrues; sustained burn > threshold means the objective will be missed.
  Two windows (short + long, the classic multi-window rule): a transient
  blip clears on its own once it slides out of the short window, while a
  sustained regression keeps both windows burning. With a 1% budget any
  violation in a sparse window reads as a large burn, so
  ``TRC_SLO_MIN_WINDOW_SAMPLES`` can demand a minimum observation count
  per window before its burn is considered meaningful (default 1: every
  violation is eligible to fire — small jobs have few samples total);
- **deadline**: elapsed wall time since job start vs
  ``slo.deadline_seconds``, fired once when exceeded.

Alert lifecycle is a per-(job, kind) state machine with exactly-once
edges: one ``fire`` when the breach condition becomes true, one ``clear``
when it recovers (latency only — a missed deadline stays missed). Every
transition lands in three places: the ``slo_alerts_total`` counter, a
Perfetto instant on the master's "alerts" track, and the bounded
structured alert log the control plane serves (``{"op": "alerts"}``) and
``cluster_view()['slo']`` mirrors into ``/clusterz`` + metrics-live.json.

Tuning (read at call time): ``TRC_SLO_SHORT_WINDOW_SECONDS`` /
``TRC_SLO_LONG_WINDOW_SECONDS`` / ``TRC_SLO_BURN_THRESHOLD`` /
``TRC_SLO_MIN_WINDOW_SAMPLES`` / ``TRC_SLO_TICK_SECONDS``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any

from tpu_render_cluster.utils.env import env_float

if TYPE_CHECKING:
    from tpu_render_cluster.jobs.models import BlenderJob, JobSlo
    from tpu_render_cluster.obs.registry import MetricsRegistry
    from tpu_render_cluster.obs.tracer import Tracer

logger = logging.getLogger(__name__)

__all__ = ["SloService", "SloTracker", "SloAlert", "slo_loop"]

# A p99 latency objective: 1% of units may miss it before the SLO does.
LATENCY_TARGET = 0.99
ERROR_BUDGET = 1.0 - LATENCY_TARGET

KIND_UNIT_LATENCY = "unit_latency_p99"
KIND_DEADLINE = "deadline"

TRANSITION_FIRE = "fire"
TRANSITION_CLEAR = "clear"


def short_window_seconds() -> float:
    return env_float("TRC_SLO_SHORT_WINDOW_SECONDS", 60.0)


def long_window_seconds() -> float:
    return env_float("TRC_SLO_LONG_WINDOW_SECONDS", 300.0)


def burn_threshold() -> float:
    return env_float("TRC_SLO_BURN_THRESHOLD", 1.0)


def tick_seconds() -> float:
    return env_float("TRC_SLO_TICK_SECONDS", 0.5)


def min_window_samples() -> int:
    return int(env_float("TRC_SLO_MIN_WINDOW_SAMPLES", 1))


class _WindowCounter:
    """Rolling violation counts over one sliding window.

    Each observation is appended once and pruned once, so burn queries
    are amortized O(1) regardless of the unit rate — the tracker is
    evaluated inline on the master event loop for EVERY winning result,
    and a tiled job can push thousands of units through a window.
    """

    __slots__ = ("window", "_q", "total", "violated")

    def __init__(self, window: float) -> None:
        self.window = window
        self._q: deque[tuple[float, bool]] = deque()
        self.total = 0
        self.violated = 0

    def add(self, now: float, violated: bool) -> None:
        self._q.append((now, violated))
        self.total += 1
        self.violated += violated

    def prune(self, now: float) -> None:
        horizon = now - self.window
        while self._q and self._q[0][0] < horizon:
            _at, violated = self._q.popleft()
            self.total -= 1
            self.violated -= violated

    def burn(self, now: float, min_samples: int = 1) -> float:
        """Violation fraction over the window / the error budget.

        A window with fewer than ``min_samples`` observations reports
        0.0: with a 1% budget ANY violation in a sparse window would
        read as a huge burn, so operators can demand a minimum sample
        count before the burn is considered meaningful
        (``TRC_SLO_MIN_WINDOW_SAMPLES``).
        """
        self.prune(now)
        if self.total == 0 or self.total < min_samples:
            return 0.0
        return (self.violated / self.total) / ERROR_BUDGET


@dataclass(frozen=True)
class SloAlert:
    """One alert edge, as served on the control plane (``to_dict``)."""

    at: float
    job_name: str
    kind: str  # KIND_UNIT_LATENCY | KIND_DEADLINE
    transition: str  # TRANSITION_FIRE | TRANSITION_CLEAR
    detail: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "at": self.at,
            "job_name": self.job_name,
            "kind": self.kind,
            "transition": self.transition,
            **self.detail,
        }


class SloTracker:
    """One job's objectives, observations, and alert state machines."""

    def __init__(
        self,
        job_name: str,
        slo: "JobSlo",
        *,
        started_at: float,
        short_window: float | None = None,
        long_window: float | None = None,
        threshold: float | None = None,
        min_samples: int | None = None,
    ) -> None:
        self.job_name = job_name
        self.slo = slo
        self.started_at = started_at
        self.short_window = (
            short_window if short_window is not None else short_window_seconds()
        )
        self.long_window = max(
            self.short_window,
            long_window if long_window is not None else long_window_seconds(),
        )
        self.threshold = threshold if threshold is not None else burn_threshold()
        self.min_samples = (
            min_samples if min_samples is not None else min_window_samples()
        )
        self.finished_at: float | None = None
        # Rolling per-window violation counts (amortized O(1) per query).
        self._short = _WindowCounter(self.short_window)
        self._long = _WindowCounter(self.long_window)
        self.units_observed = 0
        self.units_violating = 0
        # kind -> currently firing; fires/clears are exactly-once edges.
        self.firing: dict[str, bool] = {}
        self.fires: dict[str, int] = {}
        self.clears: dict[str, int] = {}

    # -- observations --------------------------------------------------------

    def observe(self, latency_seconds: float, now: float) -> None:
        objective = self.slo.unit_latency_p99_seconds
        if objective is None:
            return
        violated = latency_seconds > objective
        self.units_observed += 1
        if violated:
            self.units_violating += 1
        self._short.add(now, violated)
        self._long.add(now, violated)

    def _burn(self, now: float, window: float) -> float:
        """Burn over one of the two tracked windows (rolling counters)."""
        if window == self.short_window:
            return self._short.burn(now, self.min_samples)
        if window == self.long_window:
            return self._long.burn(now, self.min_samples)
        raise ValueError(
            f"Untracked window {window}; tracked: "
            f"{self.short_window}/{self.long_window}"
        )

    def attainment(self) -> float | None:
        if self.units_observed == 0:
            return None
        return 1.0 - self.units_violating / self.units_observed

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now: float) -> list[SloAlert]:
        """Advance the alert state machines; returns the edges crossed.

        Exactly-once semantics: while a breach persists, evaluate() can
        run every tick (and after every observation) without re-firing;
        the next fire requires an intervening clear.
        """
        alerts: list[SloAlert] = []
        if self.slo.unit_latency_p99_seconds is not None:
            burn_short = self._burn(now, self.short_window)
            burn_long = self._burn(now, self.long_window)
            breaching = (
                burn_short >= self.threshold and burn_long >= self.threshold
            )
            detail = {
                "objective_seconds": self.slo.unit_latency_p99_seconds,
                "burn_short": round(burn_short, 4),
                "burn_long": round(burn_long, 4),
                "attainment": self.attainment(),
            }
            alerts.extend(
                self._transition(KIND_UNIT_LATENCY, breaching, now, detail)
            )
        if self.slo.deadline_seconds is not None:
            end = self.finished_at if self.finished_at is not None else now
            missed = (end - self.started_at) > self.slo.deadline_seconds
            # A missed deadline never recovers: only the fire edge exists.
            if missed and not self.firing.get(KIND_DEADLINE, False):
                alerts.extend(
                    self._transition(
                        KIND_DEADLINE,
                        True,
                        now,
                        {
                            "deadline_seconds": self.slo.deadline_seconds,
                            "elapsed_seconds": round(end - self.started_at, 3),
                        },
                    )
                )
        return alerts

    def _transition(
        self, kind: str, breaching: bool, now: float, detail: dict[str, Any]
    ) -> list[SloAlert]:
        was_firing = self.firing.get(kind, False)
        if breaching == was_firing:
            return []
        self.firing[kind] = breaching
        transition = TRANSITION_FIRE if breaching else TRANSITION_CLEAR
        ledger = self.fires if breaching else self.clears
        ledger[kind] = ledger.get(kind, 0) + 1
        return [
            SloAlert(
                at=now,
                job_name=self.job_name,
                kind=kind,
                transition=transition,
                detail=detail,
            )
        ]

    def finish(self, now: float) -> None:
        self.finished_at = now

    # -- views ---------------------------------------------------------------

    def view(self, now: float | None = None) -> dict[str, Any]:
        now = now if now is not None else time.time()
        out: dict[str, Any] = {
            "objectives": self.slo.to_dict(),
            "units_observed": self.units_observed,
            "units_violating": self.units_violating,
            "attainment": self.attainment(),
            "firing": sorted(k for k, v in self.firing.items() if v),
            "fires": dict(self.fires),
            "clears": dict(self.clears),
            "finished": self.finished_at is not None,
        }
        if self.slo.unit_latency_p99_seconds is not None:
            out["burn"] = {
                "short_window_seconds": self.short_window,
                "long_window_seconds": self.long_window,
                "threshold": self.threshold,
                "min_samples": self.min_samples,
                "short": self._burn(now, self.short_window),
                "long": self._burn(now, self.long_window),
            }
        if self.slo.deadline_seconds is not None:
            end = self.finished_at if self.finished_at is not None else now
            out["deadline"] = {
                "deadline_seconds": self.slo.deadline_seconds,
                "elapsed_seconds": end - self.started_at,
            }
        return out


class SloService:
    """All tracked jobs' SLOs + the shared alert log and metrics export."""

    MAX_ALERTS = 256

    def __init__(
        self,
        *,
        metrics: "MetricsRegistry | None" = None,
        span_tracer: "Tracer | None" = None,
        on_alert=None,
    ) -> None:
        self.metrics = metrics
        self.span_tracer = span_tracer
        self.trackers: dict[str, SloTracker] = {}
        self.alerts: deque[SloAlert] = deque(maxlen=self.MAX_ALERTS)
        # Fires with every alert edge AFTER the three standard sinks — the
        # flight recorder's trigger seam (master/cluster.py dumps a
        # blackbox on each FIRE). Failures are contained: an alert must
        # land in the log/counter/track even when the hook explodes.
        self.on_alert = on_alert

    # -- lifecycle -----------------------------------------------------------

    def register_job(
        self, job: "BlenderJob", started_at: float | None = None
    ) -> SloTracker | None:
        """Track a job's objectives from ``started_at`` on (no-op without
        an ``[slo]`` table). Re-registering a name replaces the tracker —
        the scheduler releases names at finish, so a resubmit is a new
        job."""
        if job.slo is None:
            return None
        tracker = SloTracker(
            job.job_name,
            job.slo,
            started_at=started_at if started_at is not None else time.time(),
        )
        self.trackers[job.job_name] = tracker
        if self.metrics is not None:
            for kind, objective in (
                (KIND_UNIT_LATENCY, job.slo.unit_latency_p99_seconds),
                (KIND_DEADLINE, job.slo.deadline_seconds),
            ):
                if objective is not None:
                    self.metrics.gauge(
                        "slo_objective_seconds",
                        "Declared per-job SLO objective",
                        labels=("job", "objective"),
                    ).set(objective, job=job.job_name, objective=kind)
        return tracker

    def observe_unit_latency(self, state, unit, latency_seconds: float) -> None:
        """The worker-handle hook: one winning result's dispatch-to-result
        latency (the ``master_unit_latency_seconds`` stream). Evaluates
        immediately so a breach alerts on the unit that crossed the line,
        not the next tick."""
        tracker = self.trackers.get(state.job.job_name)
        if tracker is None or tracker.finished_at is not None:
            return
        now = time.time()
        tracker.observe(latency_seconds, now)
        self._apply(tracker, now)

    def finish_job(self, job_name: str) -> None:
        """Final evaluation at job end (finish or cancel): the deadline is
        judged against the actual end time, and a still-firing latency
        alert stays on record (the view keeps it) without further ticks."""
        tracker = self.trackers.get(job_name)
        if tracker is None or tracker.finished_at is not None:
            return
        now = time.time()
        tracker.finish(now)
        self._apply(tracker, now)

    def tick(self, now: float | None = None) -> None:
        """Periodic evaluation: burns decay as windows slide (clearing
        recovered alerts) and deadlines fire even when the observation
        stream has stalled — exactly the case a latency-only hook misses."""
        now = now if now is not None else time.time()
        for tracker in self.trackers.values():
            if tracker.finished_at is None:
                self._apply(tracker, now)

    # -- plumbing ------------------------------------------------------------

    def _apply(self, tracker: SloTracker, now: float) -> None:
        for alert in tracker.evaluate(now):
            self.alerts.append(alert)
            self._emit(alert)
        if self.metrics is not None:
            attainment = tracker.attainment()
            if attainment is not None:
                self.metrics.gauge(
                    "slo_attainment_ratio",
                    "Fraction of units meeting the latency objective "
                    "(cumulative per job)",
                    labels=("job",),
                ).set(attainment, job=tracker.job_name)
            if tracker.slo.unit_latency_p99_seconds is not None:
                burn_gauge = self.metrics.gauge(
                    "slo_burn_ratio",
                    "Error-budget burn per window (1.0 = budget consumed "
                    "exactly as fast as it accrues)",
                    labels=("job", "window"),
                )
                burn_gauge.set(
                    tracker._burn(now, tracker.short_window),
                    job=tracker.job_name,
                    window="short",
                )
                burn_gauge.set(
                    tracker._burn(now, tracker.long_window),
                    job=tracker.job_name,
                    window="long",
                )

    def _emit(self, alert: SloAlert) -> None:
        log = logger.warning if alert.transition == TRANSITION_FIRE else logger.info
        log(
            "SLO %s %s for job %r: %s",
            alert.kind,
            alert.transition,
            alert.job_name,
            alert.detail,
        )
        if self.metrics is not None:
            self.metrics.counter(
                "slo_alerts_total",
                "SLO alert state transitions (exactly one fire per breach "
                "episode, one clear per recovery)",
                labels=("job", "kind", "transition"),
            ).inc(
                job=alert.job_name,
                kind=alert.kind,
                transition=alert.transition,
            )
        if self.span_tracer is not None:
            self.span_tracer.instant(
                f"slo {alert.kind} {alert.transition}",
                cat="slo",
                track="alerts",
                args=alert.to_dict(),
            )
        if self.on_alert is not None:
            try:
                self.on_alert(alert)
            except Exception as e:  # noqa: BLE001 - sinks above already landed
                logger.warning("SLO on_alert hook failed: %s", e)

    # -- views ---------------------------------------------------------------

    def tracked(self) -> bool:
        return bool(self.trackers)

    def view(self) -> dict[str, Any]:
        """The ``slo`` section of ``cluster_view()`` (-> /clusterz,
        metrics-live.json, and the statistics.json fold)."""
        if not self.trackers:
            return {}
        now = time.time()
        return {
            "jobs": {
                name: tracker.view(now)
                for name, tracker in self.trackers.items()
            },
            "alerts": self.alerts_view(),
        }

    def alerts_view(self) -> list[dict[str, Any]]:
        return [alert.to_dict() for alert in self.alerts]


async def slo_loop(service: SloService, state, cancellation) -> None:
    """Single-job sidecar (the scheduler loop ticks inline instead):
    evaluate periodically until the job's frames are done or the run is
    cancelled, so deadline breaches and window-slide recoveries surface
    even while no results are arriving."""
    interval = tick_seconds()
    while not cancellation.is_cancelled() and not state.all_frames_finished():
        service.tick()
        await asyncio.sleep(interval)
