"""Periodic JSON metrics snapshots for live inspection.

While a job runs, the master (or any process holding a registry) can keep
an on-disk snapshot fresh: ``SnapshotWriter`` serialises the registry —
plus any caller-supplied live extras (queue depths, aggregated worker
heartbeat payloads) — every ``interval`` seconds, writing atomically
(tmp + replace) so a tail -f / file-watcher reader never sees a torn
JSON document. ``write_once`` is the same path without the loop, used for
the final end-of-job snapshot.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from pathlib import Path
from typing import Any, Callable

from tpu_render_cluster.obs.registry import MetricsRegistry

logger = logging.getLogger(__name__)

__all__ = ["SnapshotWriter", "write_metrics_snapshot"]


def write_metrics_snapshot(
    path: str | Path,
    registry: MetricsRegistry,
    *,
    extra: dict[str, Any] | None = None,
) -> Path:
    """Write one atomic snapshot: ``{written_at, metrics, **extra}``."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload: dict[str, Any] = {
        "written_at": time.time(),
        "metrics": registry.snapshot(),
    }
    if extra:
        payload.update(extra)
    tmp = path.with_suffix(path.suffix + ".tmp")
    # flush + fsync BEFORE the atomic rename: os.replace is atomic against
    # concurrent readers, but without the fsync a crash (or SIGKILL) after
    # the rename can still leave a truncated/empty file once the page cache
    # is lost — the rename must only ever publish fully-durable bytes.
    with open(tmp, "w", encoding="utf-8") as handle:
        handle.write(json.dumps(payload, indent=2))
        handle.flush()
        os.fsync(handle.fileno())
    os.replace(tmp, path)
    return path


class SnapshotWriter:
    """Asyncio-periodic snapshot task (master's live metrics file)."""

    def __init__(
        self,
        path: str | Path,
        registry: MetricsRegistry,
        *,
        interval: float = 1.0,
        extra_fn: Callable[[], dict[str, Any]] | None = None,
    ) -> None:
        self.path = Path(path)
        self.registry = registry
        self.interval = interval
        self.extra_fn = extra_fn
        self._task: asyncio.Task | None = None

    def write_once(self) -> Path:
        extra = self.extra_fn() if self.extra_fn is not None else None
        return write_metrics_snapshot(self.path, self.registry, extra=extra)

    async def _run(self) -> None:
        while True:
            try:
                # extra_fn reads live loop-owned state, so it runs here;
                # the registry snapshot + serialize + write go to a thread
                # so a large cluster view never stalls heartbeat service.
                extra = self.extra_fn() if self.extra_fn is not None else None
                await asyncio.to_thread(
                    write_metrics_snapshot, self.path, self.registry, extra=extra
                )
            except Exception as e:  # noqa: BLE001 - observability must not kill jobs
                logger.warning("Metrics snapshot write failed: %s", e)
            await asyncio.sleep(self.interval)

    def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="metrics-snapshot")

    async def stop(self, *, final_write: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if final_write:
            try:
                # Same split as _run: extra_fn reads loop-owned state here,
                # the serialize + write + fsync go to a thread — the final
                # snapshot must not stall the rest of shutdown either.
                extra = self.extra_fn() if self.extra_fn is not None else None
                await asyncio.to_thread(
                    write_metrics_snapshot, self.path, self.registry, extra=extra
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("Final metrics snapshot failed: %s", e)
