"""Pull-based telemetry endpoints: a minimal asyncio HTTP/1.1 server.

The live path of the telemetry plane. One ``TelemetryServer`` per
process-with-a-registry:

- ``GET /metrics``  — the registry in Prometheus text exposition format
  (obs/prometheus.py; Content-Type ``text/plain; version=0.0.4``);
- ``GET /healthz``  — JSON liveness (``healthz_fn``, or a bare
  ``{"ok": true}``);
- ``GET /clusterz`` — JSON live cluster view (``clusterz_fn``, the
  master's ``cluster_view()``; 404 on processes that have none, e.g. a
  worker daemon);
- ``GET /history`` — the embedded metrics-history store (obs/history.py;
  404 on processes without one): a summary with no query, or
  ``?name=X[&seconds=S]`` for absolute range series,
  ``?name=X&query=rate[&seconds=S]`` for increase/second, and
  ``?name=X&query=quantile&q=0.99[&seconds=S]`` for
  quantile-over-window reconstructed from bucket deltas.

``extra_routes`` maps a path to an async handler ``(query) -> (status,
content_type, body)`` and takes precedence over the built-ins — the HA
shard router uses it to serve *federated* ``/metrics`` + ``/history``
merged across every master shard (ha/shards.py).

Replaces file-polling of ``metrics-live.json`` as the LIVE inspection
path (the snapshot writer stays for post-hoc artifacts): an operator —
or the terminal dashboard (obs/dashboard.py), or an actual Prometheus —
scrapes the master and workers over plain HTTP while jobs run.

Deliberately stdlib-only and GET-only, in the spirit of the JSON-lines
control plane (sched/control.py): no framework, no TLS, no mutation. The
``clusterz_fn``/``healthz_fn`` callables run on the event loop and must
stay cheap (``cluster_view()`` is a dict build over live state); the
registry snapshot + render go to a thread so a large registry never
stalls heartbeat service.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import urllib.parse
from typing import Any, Awaitable, Callable

from tpu_render_cluster.obs.history import HistoryStore
from tpu_render_cluster.obs.prometheus import CONTENT_TYPE, render_prometheus
from tpu_render_cluster.obs.registry import MetricsRegistry

logger = logging.getLogger(__name__)

__all__ = ["TelemetryServer", "resolve_telemetry_port"]


def resolve_telemetry_port(
    flag_value: int | None, env_name: str
) -> int | None:
    """One definition of the CLI/env port contract: an explicit flag wins;
    otherwise the env variable enables the endpoints when set to >= 0
    (0 = ephemeral); absent/negative = disabled (None)."""
    if flag_value is not None:
        return flag_value
    from tpu_render_cluster.utils.env import env_int

    port = env_int(env_name, -1)
    return port if port >= 0 else None

_MAX_REQUEST_BYTES = 64 * 1024
_JSON_CONTENT_TYPE = "application/json; charset=utf-8"


class TelemetryServer:
    """Serve one registry (and optional live views) over HTTP."""

    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        clusterz_fn: Callable[[], dict[str, Any]] | None = None,
        healthz_fn: Callable[[], dict[str, Any]] | None = None,
        history: HistoryStore | None = None,
        extra_routes: dict[
            str, Callable[[dict[str, str]], Awaitable[tuple[int, str, str]]]
        ]
        | None = None,
    ) -> None:
        self.registry = registry
        self.host = host
        self.port = port
        self.clusterz_fn = clusterz_fn
        self.healthz_fn = healthz_fn
        self.history = history
        self.extra_routes = dict(extra_routes or {})
        self.started_at = time.time()
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("Telemetry endpoints on http://%s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("Telemetry server close timed out.")
            self._server = None

    # -- request handling ---------------------------------------------------

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10.0)
            if not request_line:
                return
            # Drain headers (bounded); GET carries no body we care about.
            consumed = len(request_line)
            while True:
                line = await asyncio.wait_for(reader.readline(), 10.0)
                consumed += len(line)
                if line in (b"\r\n", b"\n", b""):
                    break
                if consumed > _MAX_REQUEST_BYTES:
                    writer.close()
                    return
            try:
                method, target, _version = (
                    request_line.decode("latin-1").strip().split(None, 2)
                )
            except ValueError:
                await self._respond(
                    writer, 400, _JSON_CONTENT_TYPE,
                    json.dumps({"ok": False, "error": "malformed request"}),
                )
                return
            if method not in ("GET", "HEAD"):
                await self._respond(
                    writer, 405, _JSON_CONTENT_TYPE,
                    json.dumps({"ok": False, "error": "GET only"}),
                    head_only=method == "HEAD",
                )
                return
            path, _, query_string = target.partition("?")
            try:
                status, content_type, body = await self._route(
                    path, query_string
                )
            except Exception as e:  # noqa: BLE001 - one bad scrape must not kill the plane
                # Answer with a self-diagnosing 500 instead of slamming the
                # socket: a lint-refused metric or a clusterz_fn raising
                # mid-shutdown should tell the operator WHAT broke, not
                # show up as an opaque connection reset in the scraper.
                logger.warning("Telemetry handler for %s failed: %s", path, e)
                status, content_type, body = (
                    500,
                    _JSON_CONTENT_TYPE,
                    json.dumps({"ok": False, "error": str(e)}),
                )
            await self._respond(
                writer, status, content_type, body, head_only=method == "HEAD"
            )
        except (ConnectionError, asyncio.TimeoutError, asyncio.IncompleteReadError):
            pass  # scraper went away; nothing to answer
        except Exception as e:  # noqa: BLE001 - one bad scrape must not kill the plane
            logger.warning("Telemetry request from %s failed: %s", peer, e)
        finally:
            writer.close()

    async def _route(
        self, path: str, query_string: str = ""
    ) -> tuple[int, str, str]:
        query = {
            name: values[-1]
            for name, values in urllib.parse.parse_qs(query_string).items()
        }
        handler = self.extra_routes.get(path)
        if handler is not None:
            return await handler(query)
        if path == "/metrics":
            # Snapshot + render in a thread: the registry lock is cheap but
            # serialization of a big registry is not.
            body = await asyncio.to_thread(
                lambda: render_prometheus(self.registry.snapshot())
            )
            return 200, CONTENT_TYPE, body
        if path == "/healthz":
            payload = {"ok": True, "uptime_seconds": time.time() - self.started_at}
            if self.healthz_fn is not None:
                payload.update(self.healthz_fn())
            return 200, _JSON_CONTENT_TYPE, json.dumps(payload, default=str)
        if path == "/clusterz":
            if self.clusterz_fn is None:
                return 404, _JSON_CONTENT_TYPE, json.dumps(
                    {"ok": False, "error": "no cluster view on this process"}
                )
            view = self.clusterz_fn()
            return 200, _JSON_CONTENT_TYPE, json.dumps(view, default=str)
        if path == "/history":
            if self.history is None:
                return 404, _JSON_CONTENT_TYPE, json.dumps(
                    {"ok": False, "error": "no history store on this process"}
                )
            # Query reconstruction walks the sample ring; off-loop like
            # the /metrics render.
            payload = await asyncio.to_thread(self._history_query, query)
            return 200, _JSON_CONTENT_TYPE, json.dumps(payload, default=str)
        paths = ["/metrics", "/healthz", "/clusterz"]
        if self.history is not None:
            paths.append("/history")
        paths.extend(sorted(self.extra_routes))
        return 404, _JSON_CONTENT_TYPE, json.dumps(
            {"ok": False, "error": f"unknown path {path!r}", "paths": paths}
        )

    def _history_query(self, query: dict[str, str]) -> dict[str, Any]:
        """One /history query against the embedded store (obs/history.py)."""
        store = self.history
        assert store is not None
        name = query.get("name")
        if not name:
            return {"ok": True, **store.meta(), "names": store.names()}
        seconds = None
        if query.get("seconds"):
            try:
                seconds = float(query["seconds"])
            except ValueError:
                return {"ok": False, "error": f"bad seconds={query['seconds']!r}"}
        kind = store.names().get(name)
        what = query.get("query", "range")
        out: dict[str, Any] = {
            "ok": True,
            "name": name,
            "kind": kind,
            "query": what,
            "seconds": seconds,
        }
        if what == "range":
            out["series"] = store.range_series(name, seconds)
        elif what == "rate":
            out["series"] = store.rate(name, seconds)
        elif what == "quantile":
            try:
                q = float(query.get("q", "0.99"))
            except ValueError:
                return {"ok": False, "error": f"bad q={query.get('q')!r}"}
            out["q"] = q
            out.update(store.quantile(name, q, seconds))
        else:
            return {
                "ok": False,
                "error": f"unknown query {what!r} "
                "(expected range | rate | quantile)",
            }
        return out

    @staticmethod
    async def _respond(
        writer: asyncio.StreamWriter,
        status: int,
        content_type: str,
        body: str,
        *,
        head_only: bool = False,
    ) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed"}.get(status, "Error")
        payload = body.encode("utf-8")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(payload)}\r\n"
            f"Connection: close\r\n"
            f"\r\n"
        ).encode("latin-1")
        writer.write(head if head_only else head + payload)
        await writer.drain()
