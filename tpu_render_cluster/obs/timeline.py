"""Merged cluster timeline: one Perfetto file across process boundaries.

``export_chrome_trace`` can already merge colocated tracers, but a real
cluster collects span events from SEPARATE processes whose tracers (a) may
reuse the same ``pid`` values (each process numbers its tracers from 1)
and (b) timestamp on uncorrelated wall clocks. This module fixes both:

- every contributing process gets a FRESH pid in the merged document (its
  metadata and span events are rewritten consistently), so two workers
  that both called themselves pid 1 land on separate Perfetto rows;
- each process's events are REBASED onto the master's clock by the
  per-worker offset the heartbeat estimator measured
  (``obs/clocksync.py``): ``ts_master = ts_worker - offset``.

The applied offsets are recorded under ``otherData.clock_offsets_seconds``
so a reader can tell a corrected timeline from a raw one.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable

from tpu_render_cluster.obs.tracer import Tracer

logger = logging.getLogger(__name__)

__all__ = [
    "TimelineProcess",
    "export_cluster_trace",
    "merge_timeline",
    "rebase_events",
    "tracer_process",
]


@dataclass
class TimelineProcess:
    """One process's contribution: its raw events + estimated clock offset.

    ``events`` must include the tracer's metadata events (``process_name``
    etc.) — ``Tracer.metadata_events() + Tracer.events()``, or the
    equivalent list a worker shipped over the wire. ``offset_seconds`` is
    (process clock - master clock); the master itself contributes 0.0.
    ``dropped`` carries the source tracer's past-the-cap drop count so a
    truncated contribution stays visible in the merged document.
    """

    name: str
    events: list[dict[str, Any]] = field(default_factory=list)
    offset_seconds: float = 0.0
    dropped: int = 0


def tracer_process(tracer: Tracer, offset_seconds: float = 0.0) -> TimelineProcess:
    """Wrap a live in-process tracer (harness path) as a timeline process."""
    return TimelineProcess(
        name=tracer.process_name,
        events=tracer.metadata_events() + tracer.events(),
        offset_seconds=offset_seconds,
        dropped=tracer.dropped,
    )


def rebase_events(
    events: Iterable[dict[str, Any]], offset_seconds: float, *, pid: int | None = None
) -> list[dict[str, Any]]:
    """Copy events onto the master clock (ts -= offset) and optionally
    rewrite their pid. Metadata events carry no ``ts``; they pass through
    with only the pid rewritten."""
    shift_us = offset_seconds * 1e6
    out: list[dict[str, Any]] = []
    for event in events:
        copy = dict(event)
        if pid is not None:
            copy["pid"] = pid
        if shift_us and "ts" in copy:
            copy["ts"] = round(float(copy["ts"]) - shift_us, 3)
        out.append(copy)
    return out


def merge_timeline(
    processes: Iterable[TimelineProcess],
    *,
    extra_other_data: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Build the merged, offset-corrected cluster timeline document.

    Process order is preserved (callers put the master first so it renders
    as the top row); pids are reassigned 1..N. ``export_cluster_trace``
    writes this to disk; the chaos harness also validates it in memory.
    ``extra_other_data`` lands under ``otherData`` — the multi-job
    scheduler stamps its per-job lifecycle summary there (``sched_jobs``),
    so a reader can map the master row's per-job tracks (``job job-NNNN``)
    back to names/weights/makespans without a second artifact.
    """
    events: list[dict[str, Any]] = []
    offsets: dict[str, float] = {}
    dropped: dict[str, int] = {}
    for new_pid, process in enumerate(processes, start=1):
        offsets[process.name] = process.offset_seconds
        events.extend(
            rebase_events(process.events, process.offset_seconds, pid=new_pid)
        )
        if process.dropped:
            # Same non-silent-truncation contract as Tracer.export: a
            # capped contributor's timeline is missing its TAIL, and a
            # clean-looking merged file must not imply full coverage.
            dropped[process.name] = process.dropped
            logger.warning(
                "Cluster timeline contribution %r dropped %d events past "
                "its cap; that process row is truncated.",
                process.name, process.dropped,
            )
    document: dict[str, Any] = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"clock_offsets_seconds": offsets},
    }
    if dropped:
        document["otherData"]["dropped_events"] = dropped
    if extra_other_data:
        document["otherData"].update(extra_other_data)
    return document


def export_cluster_trace(
    path: str | Path,
    processes: Iterable[TimelineProcess],
    *,
    extra_other_data: dict[str, Any] | None = None,
) -> Path:
    """Write the merged cluster timeline (see ``merge_timeline``)."""
    document = merge_timeline(processes, extra_other_data=extra_other_data)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(document), encoding="utf-8")
    return path
