"""On-device kernel roofline profiling: FLOPs/bytes vs measured time.

ROADMAP Open item 4 ("close the on-chip gap") needs the gap to be a
*per-kernel number*: which jitted renderer entry point achieves what
fraction of the chip's attainable rate, and whether it is compute- or
memory-bound. This module makes every execution tier report that:

- **cost capture**: at first use, each instrumented kernel's XLA cost
  analysis (``jax.stages.Lowered.cost_analysis()`` — FLOPs + bytes
  accessed, estimated from the lowered HLO without a second backend
  compile) is recorded once per (kernel key, arg shapes);
- **execute pairing**: the same drivers that feed the
  ``render_execute_seconds`` histograms report each kernel's measured
  wall time (device-fenced where the tier syncs);
- **roofline placement**: achieved FLOP/s = FLOPs x executions / total
  measured seconds, compared against ``min(peak_flops,
  arithmetic_intensity x peak_bytes_per_second)`` — the classic roofline
  attainable bound. Peaks come from ``TRC_PEAK_FLOPS`` /
  ``TRC_PEAK_BYTES_PER_SECOND`` or per-backend defaults.

Exposed three ways: registry gauges (``render_kernel_flops`` /
``render_kernel_bytes`` / ``render_kernel_achieved_flops_per_second``,
scrapeable at ``/metrics``), the ``roofline`` section workers/harness/
bench stamp into metrics snapshots, and ``statistics.json`` via
``analysis/obs_events.summarize_roofline``.

``TRC_OBS_PROFILING=0`` disables capture (the wrappers become
pass-through); measured-time pairing is cheap and always on.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable
from tpu_render_cluster.utils.env import env_str

logger = logging.getLogger(__name__)

__all__ = [
    "KernelProfiler",
    "bvh_dims",
    "get_profiler",
    "kernel_key",
    "profiling_enabled",
    "device_peaks",
    "roofline_placement",
]


def kernel_key(tier: str, scene_name: str | None = None, **dims: Any) -> str:
    """Canonical kernel identity: ``tier/scene@k=v,...``.

    One definition site so the capture sites (render tiers) and the
    measured-time sites (backends, bench) can never key the same program
    differently."""
    key = tier if scene_name is None else f"{tier}/{scene_name}"
    if dims:
        key += "@" + ",".join(f"{k}={v}" for k, v in sorted(dims.items()))
    return key

def bvh_dims(
    *, tlas: int | bool, quant: int, builder: str, wide: int
) -> dict:
    """The BVH node-format dims every mesh-kernel key carries.

    One definition site (like ``kernel_key``) so the masked, region,
    wavefront, and raypool capture sites can never attribute two node
    formats to one roofline row: a distinct (tlas, quant, builder, wide)
    is a distinct kernel identity — exactly the set of knobs that change
    the compiled program (``TRC_TLAS``/``TRC_BVH_QUANT``/
    ``TRC_BVH_BUILDER``/``TRC_BVH_WIDE``).
    """
    return {
        "tlas": int(tlas),
        "quant": int(quant),
        "bvh": f"{builder}{int(wide)}",
    }


# Conservative per-backend peak defaults, overridable via TRC_PEAK_*.
# TPU: a single modern TPU core's VPU-adjusted vector peak (the renderer
# is VPU-bound — NORTHSTAR.md round 5 measured against this basis) and
# HBM bandwidth. CPU: a few-core host's vector peak and DRAM bandwidth —
# deliberately round numbers; on-chip runs should set TRC_PEAK_* from the
# part's datasheet.
_DEFAULT_PEAKS = {
    "tpu": (3.0e12, 1.2e12),
    "cpu": (5.0e10, 2.0e10),
    "gpu": (1.0e13, 1.0e12),
}


def profiling_enabled() -> bool:
    return (env_str("TRC_OBS_PROFILING", "1") or "").strip() not in ("0", "off")


def device_peaks() -> dict[str, float]:
    """{peak_flops, peak_bytes_per_second, source} for the active backend."""
    source = "default"
    backend = "cpu"
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - peaks must resolve even without jax
        pass
    flops, bandwidth = _DEFAULT_PEAKS.get(backend, _DEFAULT_PEAKS["cpu"])
    raw_flops = env_str("TRC_PEAK_FLOPS")
    raw_bw = env_str("TRC_PEAK_BYTES_PER_SECOND")
    try:
        if raw_flops:
            flops = float(raw_flops)
            source = "env"
        if raw_bw:
            bandwidth = float(raw_bw)
            source = "env"
    except ValueError:
        logger.warning(
            "Ignoring non-numeric TRC_PEAK_FLOPS/TRC_PEAK_BYTES_PER_SECOND"
        )
    return {
        "backend": backend,
        "peak_flops": flops,
        "peak_bytes_per_second": bandwidth,
        "source": source,
    }


def roofline_placement(
    flops: float,
    bytes_accessed: float,
    seconds_per_execution: float,
    peaks: dict[str, float],
) -> dict[str, float]:
    """One kernel's roofline numbers from its cost + measured time."""
    out: dict[str, float] = {}
    intensity = flops / bytes_accessed if bytes_accessed > 0 else float("inf")
    out["arithmetic_intensity_flops_per_byte"] = intensity
    attainable = min(
        peaks["peak_flops"], intensity * peaks["peak_bytes_per_second"]
    )
    out["attainable_flops_per_second"] = attainable
    out["bound"] = (
        "compute"
        if intensity * peaks["peak_bytes_per_second"] >= peaks["peak_flops"]
        else "memory"
    )
    if seconds_per_execution > 0:
        achieved = flops / seconds_per_execution
        out["achieved_flops_per_second"] = achieved
        out["achieved_fraction_of_peak"] = achieved / peaks["peak_flops"]
        if attainable > 0:
            out["achieved_fraction_of_attainable"] = achieved / attainable
    return out


class _KernelRecord:
    __slots__ = (
        "flops", "bytes_accessed", "captured", "capture_seconds",
        "executions", "execute_seconds_total", "meta",
    )

    def __init__(self) -> None:
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.captured = False
        self.capture_seconds = 0.0
        self.executions = 0
        self.execute_seconds_total = 0.0
        self.meta: dict[str, Any] = {}


class KernelProfiler:
    """Thread-safe per-kernel cost + measured-time store."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._kernels: dict[str, _KernelRecord] = {}

    # -- capture -------------------------------------------------------------

    def record_cost(
        self,
        kernel: str,
        *,
        flops: float,
        bytes_accessed: float,
        capture_seconds: float = 0.0,
        meta: dict[str, Any] | None = None,
    ) -> None:
        with self._lock:
            record = self._kernels.setdefault(kernel, _KernelRecord())
            record.flops = float(flops)
            record.bytes_accessed = float(bytes_accessed)
            record.captured = True
            record.capture_seconds = capture_seconds
            if meta:
                record.meta.update(meta)
        self._export_cost(kernel)

    def captured(self, kernel: str) -> bool:
        with self._lock:
            record = self._kernels.get(kernel)
            return record is not None and record.captured

    def capture(
        self, kernel: str, jitted: Any, *args: Any, **kwargs: Any
    ) -> bool:
        """Lower a jitted callable with these args and record its cost
        analysis — once per kernel key; later calls are near-free. The
        lowering is one extra trace (no backend compile); failures are
        logged and the kernel simply stays uncaptured (profiling must
        never break rendering).
        """
        if not profiling_enabled() or self.captured(kernel):
            return False
        started = time.perf_counter()
        try:
            lowered = jitted.lower(*args, **kwargs)
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):  # per-device list on some paths
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0) or 0.0)
            bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
        except Exception as e:  # noqa: BLE001 - never break the render path
            logger.debug("Cost capture for %r failed: %s", kernel, e)
            return False
        self.record_cost(
            kernel,
            flops=flops,
            bytes_accessed=bytes_accessed,
            capture_seconds=time.perf_counter() - started,
        )
        return True

    def instrument(
        self, kernel: str, jitted: Callable[..., Any]
    ) -> Callable[..., Any]:
        """Wrap a jitted callable so its first call captures cost analysis
        with the call's actual arguments (identical shapes/dtypes to the
        compiled program). The wrapper adds one flag check per call."""

        def wrapped(*args: Any, **kwargs: Any):
            if not self.captured(kernel):
                self.capture(kernel, jitted, *args, **kwargs)
            return jitted(*args, **kwargs)

        wrapped.kernel_key = kernel  # type: ignore[attr-defined]
        wrapped.__wrapped__ = jitted  # type: ignore[attr-defined]
        return wrapped

    # -- measured time -------------------------------------------------------

    def record_execute(self, kernel: str, seconds: float) -> None:
        with self._lock:
            record = self._kernels.setdefault(kernel, _KernelRecord())
            record.executions += 1
            record.execute_seconds_total += max(0.0, float(seconds))
            flops = record.flops
            executions = record.executions
            total = record.execute_seconds_total
        registry = _registry()
        if registry is not None and flops > 0 and total > 0:
            registry.gauge(
                "render_kernel_achieved_flops_per_second",
                "Per-kernel achieved FLOP/s (cost-model FLOPs x executions "
                "/ measured execute seconds)",
                labels=("kernel",),
            ).set(flops * executions / total, kernel=kernel)

    # -- views ---------------------------------------------------------------

    def view(self) -> dict[str, Any]:
        """The ``roofline`` metrics-snapshot section (and bench record)."""
        with self._lock:
            items = [
                (kernel, record.flops, record.bytes_accessed, record.captured,
                 record.executions, record.execute_seconds_total,
                 dict(record.meta))
                for kernel, record in self._kernels.items()
            ]
        if not items:
            return {}
        peaks = device_peaks()
        kernels: dict[str, Any] = {}
        for (kernel, flops, bytes_accessed, captured, executions,
             total_seconds, meta) in sorted(items):
            entry: dict[str, Any] = {
                "flops": flops,
                "bytes_accessed": bytes_accessed,
                "captured": captured,
                "executions": executions,
                "execute_seconds_total": total_seconds,
                **meta,
            }
            if captured:
                per_execution = (
                    total_seconds / executions if executions else 0.0
                )
                entry.update(
                    roofline_placement(flops, bytes_accessed, per_execution, peaks)
                )
            kernels[kernel] = entry
        return {"peaks": peaks, "kernels": kernels}

    def reset(self) -> None:
        """Testing hook (compile/capture-count assertions isolate runs)."""
        with self._lock:
            self._kernels.clear()

    # -- registry export -----------------------------------------------------

    def _export_cost(self, kernel: str) -> None:
        registry = _registry()
        if registry is None:
            return
        with self._lock:
            record = self._kernels.get(kernel)
            if record is None:
                return
            flops, bytes_accessed = record.flops, record.bytes_accessed
        registry.gauge(
            "render_kernel_flops",
            "XLA cost-analysis FLOPs per execution of this kernel",
            labels=("kernel",),
        ).set(flops, kernel=kernel)
        registry.gauge(
            "render_kernel_bytes",
            "XLA cost-analysis bytes accessed per execution of this kernel",
            labels=("kernel",),
        ).set(bytes_accessed, kernel=kernel)


def _registry():
    try:
        from tpu_render_cluster.obs import get_registry

        return get_registry()
    except Exception:  # noqa: BLE001 - import cycles during teardown
        return None


_global_profiler = KernelProfiler()


def get_profiler() -> KernelProfiler:
    """The process-global profiler (one accelerator per process, like
    ``obs.get_registry``)."""
    return _global_profiler
