"""Trace-invariant checker for exported Chrome trace-event artifacts.

Every timeline this repo writes — per-run ``*_trace-events.json``, the
merged ``*_cluster_trace-events.json``, worker-daemon exports — must hold
a small set of structural invariants or the Perfetto view silently lies
(mis-nested slices, arrows pointing nowhere, two processes folded onto one
row). ``validate_trace_events`` returns a list of human-readable problem
strings (empty = valid):

1.  Every event is an object with a ``ph``; complete (``X``) events carry
    finite, non-negative ``ts`` and ``dur``; all timestamped events carry
    finite non-negative ``ts``.
2.  ``B``/``E`` duration events balance per (pid, tid) in stack order.
3.  Per (pid, tid) track, ``X`` events appear in non-decreasing END-time
    order (the tracer appends at completion, so out-of-order ends mean a
    clock went backwards or a merge interleaved two tracks onto one tid).
    A small tolerance absorbs wall-vs-monotonic rounding.
4.  Metadata is unique: one ``process_name`` per pid, one ``thread_name``
    per (pid, tid) — conflicting claims are exactly the pid-collision bug
    a bad multi-process merge produces.
5.  Flow ids resolve: no half-open arrows — an id with a start (``s``)
    must carry a terminal (``f``) and vice versa — and every flow event
    binds inside some ``X`` span on its own (pid, tid) track. Step-only
    (``t``) chains are legal: a per-process fragment (a worker daemon's
    own export) routes flows whose start and terminal live on the
    master's timeline; the merged cluster file carries all three.
6.  Attribution tracks are self-contained: the ``sched`` (tick profiler)
    and ``loop`` (loop-lag monitor) rows carry only complete (``X``) and
    instant (``i``) events — a ``B``/``E`` or flow event landing there
    means a merge folded another track onto an attribution row.

``scripts/validate_trace.py`` is the CLI wrapper; tests call these
functions directly on every artifact they export.
"""

from __future__ import annotations

import bisect
import json
import math
from pathlib import Path
from typing import Any, Iterable

__all__ = [
    "validate_trace_events",
    "validate_trace_document",
    "validate_trace_file",
    "validate_blackbox_document",
    "validate_blackbox_file",
]

# End-time ordering tolerance per track, in trace microseconds. Spans anchor
# on wall-clock but measure duration on the monotonic clock, so two spans
# completing back-to-back can disagree about "now" by the rounding jitter
# between the clocks; 5 ms is far above that and far below any real
# ordering violation a merge or rebase bug would introduce.
END_ORDER_TOLERANCE_US = 5000.0


def _finite_nonneg(value: Any) -> bool:
    return (
        isinstance(value, (int, float))
        and not isinstance(value, bool)
        and math.isfinite(value)
        and value >= 0
    )


def validate_trace_events(events: Iterable[Any]) -> list[str]:
    problems: list[str] = []
    spans_by_track: dict[tuple[Any, Any], list[dict[str, Any]]] = {}
    open_stacks: dict[tuple[Any, Any], list[str]] = {}
    process_names: dict[Any, str] = {}
    thread_names: dict[tuple[Any, Any], str] = {}
    flow_events: list[dict[str, Any]] = []
    phases_by_track: dict[tuple[Any, Any], set[str]] = {}

    for i, event in enumerate(events):
        if not isinstance(event, dict) or "ph" not in event:
            problems.append(f"event #{i}: not an object with a 'ph' field")
            continue
        ph = event["ph"]
        track = (event.get("pid"), event.get("tid"))
        if ph == "M":
            name = event.get("name")
            claimed = (event.get("args") or {}).get("name")
            if name == "process_name":
                previous = process_names.setdefault(event.get("pid"), claimed)
                if previous != claimed:
                    problems.append(
                        f"pid {event.get('pid')}: conflicting process_name "
                        f"metadata ({previous!r} vs {claimed!r})"
                    )
            elif name == "thread_name":
                previous = thread_names.setdefault(track, claimed)
                if previous != claimed:
                    problems.append(
                        f"track {track}: conflicting thread_name metadata "
                        f"({previous!r} vs {claimed!r})"
                    )
            continue
        phases_by_track.setdefault(track, set()).add(str(ph))
        if not _finite_nonneg(event.get("ts")):
            problems.append(
                f"event #{i} ({event.get('name')!r}, ph={ph!r}): "
                f"missing or negative ts"
            )
            continue
        if ph == "X":
            if not _finite_nonneg(event.get("dur")):
                problems.append(
                    f"event #{i} ({event.get('name')!r}): complete event "
                    f"with missing or negative dur"
                )
                continue
            spans_by_track.setdefault(track, []).append(event)
        elif ph == "B":
            open_stacks.setdefault(track, []).append(str(event.get("name")))
        elif ph == "E":
            stack = open_stacks.setdefault(track, [])
            if not stack:
                problems.append(
                    f"track {track}: 'E' event ({event.get('name')!r}) "
                    f"with no open 'B'"
                )
            else:
                stack.pop()
        elif ph in ("s", "t", "f"):
            flow_events.append(event)

    for track, stack in open_stacks.items():
        if stack:
            problems.append(
                f"track {track}: {len(stack)} unclosed 'B' event(s): {stack}"
            )

    # Invariant 6: attribution tracks carry only self-contained events.
    for track, name in thread_names.items():
        if name not in ("sched", "loop"):
            continue
        stray = phases_by_track.get(track, set()) - {"X", "i"}
        if stray:
            problems.append(
                f"track {track} ({name!r}): event phase(s) {sorted(stray)} "
                f"on an attribution track (only 'X' and 'i' belong there)"
            )

    # Per-track monotonic end times (completion order is append order).
    for track, spans in spans_by_track.items():
        high_water = -math.inf
        for span in spans:
            end = float(span["ts"]) + float(span["dur"])
            if end < high_water - END_ORDER_TOLERANCE_US:
                problems.append(
                    f"track {track}: span {span.get('name')!r} ends at "
                    f"{end:.1f}us, {high_water - end:.1f}us before an "
                    f"earlier-appended span's end (non-monotonic track)"
                )
            high_water = max(high_water, end)

    # Flow resolution: start + terminal per id, every event bound to a span.
    # Binding is a point-stabbing query per flow event; a linear scan over
    # the track's spans is quadratic on production artifacts (a 14400-frame
    # job puts ~60k spans and as many flow steps on one track). Sorting by
    # start with a running max-end answers "does any span contain ts?" in
    # O(log n): a containing span exists iff the max end among spans
    # starting at or before ts reaches ts.
    stab_index: dict[tuple[Any, Any], tuple[list[float], list[float]]] = {}
    for track, spans in spans_by_track.items():
        intervals = sorted(
            (float(s["ts"]), float(s["ts"]) + float(s["dur"])) for s in spans
        )
        starts = [start for start, _ in intervals]
        max_ends: list[float] = []
        high = -math.inf
        for _, end in intervals:
            high = max(high, end)
            max_ends.append(high)
        stab_index[track] = (starts, max_ends)

    phases_by_id: dict[Any, set[str]] = {}
    for event in flow_events:
        phases_by_id.setdefault(event.get("id"), set()).add(event["ph"])
        track = (event.get("pid"), event.get("tid"))
        ts = float(event["ts"])
        starts, max_ends = stab_index.get(track, ([], []))
        index = bisect.bisect_right(starts, ts) - 1
        bound = index >= 0 and max_ends[index] >= ts
        if not bound:
            problems.append(
                f"flow {event.get('id')!r} ({event['ph']}) at {ts:.1f}us on "
                f"track {track}: no enclosing span to bind to"
            )
    for flow_id, phases in phases_by_id.items():
        # Step-only chains are per-process fragments (start/terminal live
        # on another process's timeline); half-open chains are broken.
        if "s" in phases and "f" not in phases:
            problems.append(
                f"flow {flow_id!r}: start ('s') without terminal ('f')"
            )
        elif "f" in phases and "s" not in phases:
            problems.append(
                f"flow {flow_id!r}: terminal ('f') without start ('s')"
            )

    return problems


def validate_trace_document(document: Any) -> list[str]:
    """Validate a parsed trace document (object or bare-array format)."""
    if isinstance(document, dict):
        events = document.get("traceEvents")
        if not isinstance(events, list):
            return ["document: 'traceEvents' missing or not a list"]
    elif isinstance(document, list):
        events = document
    else:
        return ["document: not a Chrome trace-event document"]
    return validate_trace_events(events)


def validate_trace_file(path: str | Path) -> list[str]:
    path = Path(path)
    try:
        document = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable ({e})"]
    return [f"{path}: {p}" for p in validate_trace_document(document)]


def validate_blackbox_document(document: Any) -> list[str]:
    """Validate a flight-recorder blackbox bundle (obs/flightrec.py).

    A bundle IS a trace document (its ``traceEvents`` must satisfy every
    trace invariant — a post-mortem that lies in Perfetto is worse than
    none) plus a ``blackbox`` section whose window must be coherent: a
    finite ``[t0, t1]`` ordered pair with ``dumped_at`` at the closing
    edge, and every metric sample / protocol digest stamped inside it.
    """
    problems = validate_trace_document(document)
    if not isinstance(document, dict):
        return problems
    box = document.get("blackbox")
    if not isinstance(box, dict):
        problems.append("blackbox: section missing or not an object")
        return problems
    trigger = box.get("trigger")
    if not isinstance(trigger, str) or not trigger:
        problems.append("blackbox: missing trigger")
    window = box.get("window")
    if (
        not isinstance(window, list)
        or len(window) != 2
        or not all(
            isinstance(edge, (int, float)) and math.isfinite(edge)
            for edge in window
        )
        or window[0] > window[1]
    ):
        problems.append(f"blackbox: malformed window {window!r}")
        return problems
    t0, t1 = float(window[0]), float(window[1])
    dumped_at = box.get("dumped_at")
    if not isinstance(dumped_at, (int, float)) or not (
        t0 <= float(dumped_at) <= t1 + 1e-6
    ):
        problems.append(
            f"blackbox: dumped_at {dumped_at!r} outside window [{t0}, {t1}]"
        )
    # A fraction of a sampling interval of slack at the edges: the sampler
    # stamps before the recorder computes its cut.
    slack = 1e-3
    previous_t = -math.inf
    for i, sample in enumerate(box.get("metric_samples") or []):
        at = sample.get("t") if isinstance(sample, dict) else None
        if not isinstance(at, (int, float)) or not (
            t0 - slack <= float(at) <= t1 + slack
        ):
            problems.append(
                f"blackbox: metric sample #{i} at {at!r} outside the window"
            )
            continue
        if float(at) < previous_t:
            problems.append(
                f"blackbox: metric sample #{i} out of time order"
            )
        previous_t = float(at)
    for i, event in enumerate(box.get("protocol_events") or []):
        at = event.get("t") if isinstance(event, dict) else None
        if not isinstance(at, (int, float)) or not (
            t0 - slack <= float(at) <= t1 + slack
        ):
            problems.append(
                f"blackbox: protocol event #{i} at {at!r} outside the window"
            )
    return problems


def validate_blackbox_file(path: str | Path) -> list[str]:
    path = Path(path)
    try:
        document = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable ({e})"]
    return [f"{path}: {p}" for p in validate_blackbox_document(document)]
