"""Prometheus text-exposition rendering of a ``MetricsRegistry`` snapshot.

The live pull path of the telemetry plane (obs/http.py serves this at
``/metrics``): the registry's counters/gauges/histograms rendered in the
text exposition format (version 0.0.4) any Prometheus-compatible scraper
ingests. Stdlib-only, like the rest of ``obs``.

Naming is enforced, not hoped for: ``lint_snapshot`` checks every metric
name and label against the conventions below, and ``render_prometheus``
refuses to emit a series that fails them — the exporter can never produce
an invalid exposition line, and the tier-1 lint test keeps the whole
registry population conforming.

Conventions (prometheus.io/docs/practices/naming, narrowed):

- metric names match ``[a-z][a-z0-9_]*`` (we never emit the colon forms);
- label names match ``[a-z][a-z0-9_]*`` and never start with ``__``;
- counters end in ``_total``;
- gauges and histograms end in a unit (or documented dimensionless)
  suffix from ``UNIT_SUFFIXES`` — and never in ``_total``/``_count``/
  ``_sum``/``_bucket``, which belong to counters and histogram expansions.

``parse_prometheus`` is the minimal inverse used by the terminal
dashboard (obs/dashboard.py) and the round-trip tests.
"""

from __future__ import annotations

import math
import re
from typing import Any, Iterator, Mapping

__all__ = [
    "CONTENT_TYPE",
    "UNIT_SUFFIXES",
    "lint_metric",
    "lint_snapshot",
    "render_prometheus",
    "render_sample_line",
    "parse_prometheus",
]

# The content type every text-exposition scraper expects.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# Unit (and documented dimensionless) suffixes a gauge or histogram may
# end in. Dimensionless entries: *_fraction / *_ratio / *_share /
# *_occupancy are 0..1 proportions; *_depth / *_units are discrete counts
# sampled as gauges (a count that can go DOWN is a gauge, and `_total`
# on a gauge would read as a counter to every scraper).
UNIT_SUFFIXES = (
    "_seconds",
    "_bytes",
    "_ppm",
    "_flops",
    "_per_second",
    "_fraction",
    "_ratio",
    "_share",
    "_occupancy",
    "_depth",
    "_units",
)

# Suffixes the exposition format reserves for expansions of other types.
_RESERVED_SUFFIXES = ("_count", "_sum", "_bucket")


def lint_metric(
    name: str, kind: str, label_names: tuple[str, ...] | list[str]
) -> list[str]:
    """Convention violations for one metric declaration (empty = clean)."""
    problems: list[str] = []
    if not _NAME_RE.match(name):
        problems.append(f"{name!r}: name must match [a-z][a-z0-9_]*")
    for label in label_names:
        if not _LABEL_RE.match(str(label)):
            problems.append(
                f"{name!r}: label {label!r} must match [a-z][a-z0-9_]*"
            )
    if kind == "counter":
        if not name.endswith("_total"):
            problems.append(f"{name!r}: counter names must end in _total")
    elif kind in ("gauge", "histogram"):
        if name.endswith("_total"):
            problems.append(
                f"{name!r}: _total is reserved for counters ({kind})"
            )
        for reserved in _RESERVED_SUFFIXES:
            if name.endswith(reserved):
                problems.append(
                    f"{name!r}: {reserved} is reserved for histogram "
                    f"expansions ({kind})"
                )
        if not name.endswith(UNIT_SUFFIXES):
            problems.append(
                f"{name!r}: {kind} names must end in a unit suffix "
                f"({', '.join(UNIT_SUFFIXES)})"
            )
    else:
        problems.append(f"{name!r}: unknown metric kind {kind!r}")
    return problems


def lint_snapshot(snapshot: dict[str, Any]) -> list[str]:
    """Lint every metric in a ``MetricsRegistry.snapshot()`` document."""
    problems: list[str] = []
    for name, entry in snapshot.items():
        problems.extend(
            lint_metric(
                str(name),
                str(entry.get("type", "")),
                tuple(entry.get("labels") or ()),
            )
        )
    return problems


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _parse_label_string(
    label_str: str, label_names: tuple[str, ...] | list[str] = ()
) -> list[tuple[str, str]]:
    """Split a snapshot series key (``name=value,...``) into pairs.

    Registry label VALUES may themselves contain ``,`` or ``=`` (job
    names, file paths), making the flat key ambiguous on its own — but
    the snapshot entry DECLARES its label names, so the split anchors on
    the known ``<name>=`` prefixes in declared order: each value runs to
    the next ``,<next-name>=`` occurrence (or the end). Without declared
    names (legacy callers) it falls back to the name-grammar heuristic.
    """
    if not label_str:
        return []
    names = [str(n) for n in label_names]
    if names and label_str.startswith(f"{names[0]}="):
        pairs: list[tuple[str, str]] = []
        rest = label_str
        for i, name in enumerate(names):
            prefix = f"{name}="
            if not rest.startswith(prefix):
                break  # key disagrees with the declaration; fall back
            rest = rest[len(prefix):]
            if i + 1 < len(names):
                separator = f",{names[i + 1]}="
                cut = rest.find(separator)
                if cut < 0:
                    break
                value, rest = rest[:cut], rest[cut + 1:]
            else:
                value = rest
            pairs.append((name, value))
        if len(pairs) == len(names):
            return pairs
    pairs = []
    for chunk in re.split(r",(?=[a-zA-Z_][a-zA-Z0-9_]*=)", label_str):
        name, _, value = chunk.partition("=")
        pairs.append((name, value))
    return pairs


def _format_value(value: float) -> str:
    value = float(value)
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if math.isnan(value):
        return "NaN"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def _render_labels(pairs: list[tuple[str, str]]) -> str:
    if not pairs:
        return ""
    inner = ",".join(
        f'{name}="{_escape_label_value(str(value))}"' for name, value in pairs
    )
    return "{" + inner + "}"


def _render_metric_lines(
    name: str, entry: dict[str, Any]
) -> Iterator[str]:
    kind = str(entry.get("type"))
    help_text = str(entry.get("help") or "")
    label_names = tuple(entry.get("labels") or ())
    if help_text:
        yield f"# HELP {name} {_escape_help(help_text)}"
    yield f"# TYPE {name} {kind}"
    series = entry.get("series") or {}
    if kind in ("counter", "gauge"):
        for label_str, value in series.items():
            pairs = _parse_label_string(str(label_str), label_names)
            yield f"{name}{_render_labels(pairs)} {_format_value(value)}"
        return
    # Histogram: cumulative buckets + the +Inf overflow, then sum/count.
    bounds = [float(b) for b in entry.get("bucket_bounds") or []]
    for label_str, data in series.items():
        pairs = _parse_label_string(str(label_str), label_names)
        counts = list(data.get("bucket_counts") or [])
        cumulative = 0
        for bound, count in zip(bounds, counts):
            cumulative += int(count)
            bucket_pairs = pairs + [("le", _format_value(bound))]
            yield f"{name}_bucket{_render_labels(bucket_pairs)} {cumulative}"
        overflow = int(counts[len(bounds)]) if len(counts) > len(bounds) else 0
        cumulative += overflow
        inf_pairs = pairs + [("le", "+Inf")]
        yield f"{name}_bucket{_render_labels(inf_pairs)} {cumulative}"
        yield f"{name}_sum{_render_labels(pairs)} {_format_value(data.get('sum', 0.0))}"
        yield f"{name}_count{_render_labels(pairs)} {int(data.get('count', 0))}"


def render_sample_line(
    name: str, labels: Mapping[str, str], value: float
) -> str:
    """One exposition sample line from already-parsed pieces.

    The inverse of one ``parse_prometheus`` row — the federation path
    (ha/shards.py) re-serves scraped samples re-labeled with their shard,
    and hand-assembled f-strings would skip the escaping rules.
    """
    return f"{name}{_render_labels(list(labels.items()))} {_format_value(value)}"


def render_prometheus(snapshot: dict[str, Any]) -> str:
    """Render a registry snapshot as one text-exposition document.

    Raises ``ValueError`` on the first convention violation: a metric
    that fails the lint never reaches a scraper half-formed.
    """
    lines: list[str] = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        problems = lint_metric(
            str(name),
            str(entry.get("type", "")),
            tuple(entry.get("labels") or ()),
        )
        if problems:
            raise ValueError(
                "Refusing to export non-conforming metric: "
                + "; ".join(problems)
            )
        lines.extend(_render_metric_lines(str(name), entry))
    return "\n".join(lines) + "\n" if lines else ""


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$"
)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')


def _unescape_label_value(value: str) -> str:
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse_prometheus(
    text: str,
) -> dict[str, list[tuple[dict[str, str], float]]]:
    """Parse an exposition document into ``name -> [(labels, value)]``.

    Minimal (no TYPE/HELP retention, exemplars, or native histograms) —
    enough for the terminal dashboard and the round-trip tests. Histogram
    expansions appear under their ``_bucket``/``_sum``/``_count`` names.
    """
    out: dict[str, list[tuple[dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        match = _SAMPLE_RE.match(line)
        if match is None:
            raise ValueError(f"Malformed exposition line: {line!r}")
        labels = {
            name: _unescape_label_value(value)
            for name, value in _LABEL_PAIR_RE.findall(match.group("labels") or "")
        }
        raw = match.group("value")
        if raw == "+Inf":
            value = math.inf
        elif raw == "-Inf":
            value = -math.inf
        elif raw == "NaN":
            value = math.nan
        else:
            value = float(raw)
        out.setdefault(match.group("name"), []).append((labels, value))
    return out
