"""Embedded metrics-history store: a bounded ring of registry samples.

The telemetry plane so far is *instantaneous*: ``/metrics`` serves the
current snapshot and the moments leading up to an incident (SLO breach,
eviction, failover) are gone by the time anyone looks. This module keeps
a short, bounded history in process memory — the same place the registry
lives — so every master, worker, and scheduler service can answer range,
``rate()``, and quantile-over-window queries (``/history`` in obs/http.py)
and feed the flight recorder (obs/flightrec.py) without any external TSDB.

Design:

- **Fixed-interval samples** of every registered counter/gauge plus full
  histogram bucket vectors, taken from ``MetricsRegistry.snapshot()`` by
  an in-process sampler loop (``HistorySampler``).
- **Delta-encoded**: counters and histogram bucket vectors store the
  per-interval *increase* (zero-delta entries are omitted, so an idle
  registry costs almost nothing per sample); gauges store raw values.
  Absolute values are reconstructible because evicted samples fold their
  deltas into per-series anchors (the absolute value at the ring's
  trailing edge).
- **Counter reset detection**: a raw value below the previous sample's
  means the producing process restarted mid-series; the delta becomes the
  raw value (the increase since the reset, exactly promql's ``rate()``
  convention) and the sample records the reset so consumers can tell a
  restart from a quiet interval.
- **Bounded**: the ring holds ``retention / interval`` samples; both knobs
  are env-tunable (``TRC_OBS_HISTORY_INTERVAL`` / ``TRC_OBS_HISTORY_RETENTION``).

Queries reconstruct from deltas:

- ``range_series(name)`` — absolute per-series time series;
- ``rate(name, seconds)`` — increase/elapsed over the window (the first
  retained sample's delta describes pre-window time and is excluded);
- ``quantile(name, q, seconds)`` — quantile-over-window reconstructed
  from bucket *deltas*, so it describes only the window's observations —
  unlike the cumulative ``/metrics`` histogram, which never forgets.
"""

from __future__ import annotations

import asyncio
import logging
import math
import threading
import time
from collections import deque
from typing import Any

from tpu_render_cluster.obs.registry import MetricsRegistry
from tpu_render_cluster.utils.env import env_float

logger = logging.getLogger(__name__)

__all__ = [
    "HistoryStore",
    "HistorySampler",
    "history_interval_seconds",
    "history_retention_seconds",
    "quantile_from_bucket_counts",
]


def history_interval_seconds() -> float:
    return max(0.01, env_float("TRC_OBS_HISTORY_INTERVAL", 1.0))


def history_retention_seconds() -> float:
    return max(0.1, env_float("TRC_OBS_HISTORY_RETENTION", 600.0))


def quantile_from_bucket_counts(
    bounds: list[float], counts: list[float], q: float
) -> float | None:
    """Quantile estimate from per-bucket counts (NOT cumulative).

    ``counts`` carries one entry per bound plus the +inf overflow. The
    classic cumulative walk with linear interpolation inside the landing
    bucket (what promql's histogram_quantile does); the overflow bucket
    clamps to the last finite bound. None when the window saw nothing.
    """
    total = float(sum(counts))
    if total <= 0:
        return None
    rank = q * total
    cumulative = 0.0
    previous_bound = 0.0
    for i, bound in enumerate(bounds):
        count = float(counts[i]) if i < len(counts) else 0.0
        if cumulative + count >= rank and count > 0:
            fraction = (rank - cumulative) / count
            return previous_bound + fraction * (bound - previous_bound)
        cumulative += count
        previous_bound = bound
    return bounds[-1] if bounds else None


class HistoryStore:
    """Bounded delta-encoded sample ring over one ``MetricsRegistry``."""

    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        interval: float | None = None,
        retention: float | None = None,
    ) -> None:
        self.registry = registry
        self.interval = (
            interval if interval is not None else history_interval_seconds()
        )
        self.retention = (
            retention if retention is not None else history_retention_seconds()
        )
        self.capacity = max(2, int(round(self.retention / self.interval)) + 1)
        self._lock = threading.Lock()
        # Serializes whole sample() passes: cancelling the sampler task
        # does NOT stop an in-flight to_thread sample, so stop()'s final
        # synchronous sample could otherwise interleave with it — both
        # reading the same previous raw values (double-counted deltas)
        # and appending out of timestamp order.
        self._sample_lock = threading.Lock()
        self._samples: deque[dict[str, Any]] = deque()
        # Metric shape memory (name -> kind, histogram name -> bounds).
        self._kinds: dict[str, str] = {}
        self._bounds: dict[str, list[float]] = {}
        # Last RAW values per series key ("name|label_str"), for deltas
        # and reset detection. Touched only by sample() (single writer).
        self._last_counter: dict[str, float] = {}
        self._last_hist: dict[str, tuple[list[int], int, float]] = {}
        # Absolute values at the ring's trailing edge: evicted samples
        # fold their deltas here so range queries stay exact.
        self._anchor_counter: dict[str, float] = {}
        self._anchor_hist: dict[str, dict[str, Any]] = {}
        self.samples_total = 0
        self.resets_total = 0

    # -- sampling ------------------------------------------------------------

    def sample(self, now: float | None = None) -> None:
        """Take one fixed-interval sample of the whole registry."""
        with self._sample_lock:
            self._sample_locked(now)

    def _sample_locked(self, now: float | None) -> None:
        # `now` resolved under the sample lock so two near-simultaneous
        # callers cannot append out of timestamp order.
        now = time.time() if now is None else now
        snapshot = self.registry.snapshot()
        counters: dict[str, float] = {}
        gauges: dict[str, float] = {}
        hists: dict[str, dict[str, Any]] = {}
        resets: list[str] = []
        for name, entry in snapshot.items():
            kind = str(entry.get("type"))
            self._kinds[name] = kind
            if kind == "histogram":
                self._bounds[name] = [
                    float(b) for b in entry.get("bucket_bounds") or []
                ]
            for label_str, value in (entry.get("series") or {}).items():
                key = f"{name}|{label_str}"
                if kind == "counter":
                    raw = float(value)
                    previous = self._last_counter.get(key)
                    if previous is None:
                        delta = raw
                    elif raw < previous:
                        # The producing process restarted: the counter came
                        # back below its old value, so the increase since
                        # the reset is the raw value itself.
                        delta = raw
                        resets.append(key)
                    else:
                        delta = raw - previous
                    self._last_counter[key] = raw
                    if delta or previous is None:
                        counters[key] = delta
                elif kind == "gauge":
                    gauges[key] = float(value)
                elif kind == "histogram":
                    buckets = [int(b) for b in value.get("bucket_counts") or []]
                    count = int(value.get("count", 0))
                    total = float(value.get("sum", 0.0))
                    previous_hist = self._last_hist.get(key)
                    if previous_hist is None:
                        deltas, dn, ds = buckets, count, total
                    else:
                        pb, pn, ps = previous_hist
                        if (
                            count < pn
                            or len(buckets) != len(pb)
                            or any(b < p for b, p in zip(buckets, pb))
                        ):
                            deltas, dn, ds = buckets, count, total
                            resets.append(key)
                        else:
                            deltas = [b - p for b, p in zip(buckets, pb)]
                            dn, ds = count - pn, total - ps
                    self._last_hist[key] = (buckets, count, total)
                    if dn or previous_hist is None:
                        hists[key] = {"b": deltas, "n": dn, "s": ds}
        with self._lock:
            self._samples.append(
                {"t": now, "c": counters, "g": gauges, "h": hists, "r": resets}
            )
            self.samples_total += 1
            self.resets_total += len(resets)
            while len(self._samples) > self.capacity or (
                len(self._samples) > 1
                and now - self._samples[0]["t"] > self.retention
            ):
                self._fold_into_anchor(self._samples.popleft())

    def _fold_into_anchor(self, evicted: dict[str, Any]) -> None:
        for key, delta in evicted["c"].items():
            self._anchor_counter[key] = (
                self._anchor_counter.get(key, 0.0) + delta
            )
        for key, entry in evicted["h"].items():
            base = self._anchor_hist.get(key)
            if base is None or len(base["b"]) != len(entry["b"]):
                self._anchor_hist[key] = {
                    "b": list(entry["b"]),
                    "n": entry["n"],
                    "s": entry["s"],
                }
            else:
                base["b"] = [a + b for a, b in zip(base["b"], entry["b"])]
                base["n"] += entry["n"]
                base["s"] += entry["s"]

    # -- query plumbing ------------------------------------------------------

    def _snapshot_samples(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._samples)

    def _window_samples(
        self, seconds: float | None
    ) -> list[dict[str, Any]]:
        samples = self._snapshot_samples()
        if seconds is None or not samples:
            return samples
        cutoff = samples[-1]["t"] - seconds
        return [s for s in samples if s["t"] >= cutoff]

    @staticmethod
    def _keys_for(name: str, samples, *fields: str) -> set[str]:
        prefix = f"{name}|"
        keys: set[str] = set()
        for sample in samples:
            for field in fields:
                keys.update(k for k in sample[field] if k.startswith(prefix))
        return keys

    # -- queries -------------------------------------------------------------

    def window(self) -> tuple[float, float] | None:
        with self._lock:
            if not self._samples:
                return None
            return (self._samples[0]["t"], self._samples[-1]["t"])

    def names(self) -> dict[str, str]:
        return dict(self._kinds)

    def samples_since(self, t0: float) -> list[dict[str, Any]]:
        """Raw retained samples at or after ``t0`` (the flight recorder's
        window cut)."""
        return [s for s in self._snapshot_samples() if s["t"] >= t0]

    def range_series(
        self, name: str, seconds: float | None = None
    ) -> dict[str, Any]:
        """Absolute per-series time series for one metric.

        Counters/histograms accumulate anchor + deltas (so the values are
        cumulative increase since the store first saw the series — after
        a reset they keep growing rather than re-dropping to the raw
        post-restart value). Gauges are raw samples. A ``seconds`` window
        limits which POINTS are emitted, never the baseline: deltas of
        retained samples older than the cutoff still accumulate before
        the first emitted point, so windowed values stay absolute.
        """
        kind = self._kinds.get(name)
        samples = self._snapshot_samples()
        cutoff = (
            samples[-1]["t"] - seconds
            if seconds is not None and samples
            else -math.inf
        )
        prefix = f"{name}|"
        out: dict[str, Any] = {}
        if kind == "gauge":
            for sample in samples:
                if sample["t"] < cutoff:
                    continue
                for key, value in sample["g"].items():
                    if not key.startswith(prefix):
                        continue
                    series = out.setdefault(
                        key[len(prefix):], {"t": [], "v": []}
                    )
                    series["t"].append(sample["t"])
                    series["v"].append(value)
            return out
        if kind == "counter":
            keys = self._keys_for(name, samples, "c")
            with self._lock:
                running = {
                    k: self._anchor_counter.get(k, 0.0) for k in keys
                }
            for sample in samples:
                for key in keys:
                    running[key] += sample["c"].get(key, 0.0)
                    if sample["t"] < cutoff:
                        continue
                    series = out.setdefault(
                        key[len(prefix):], {"t": [], "v": []}
                    )
                    series["t"].append(sample["t"])
                    series["v"].append(running[key])
            return out
        if kind == "histogram":
            keys = self._keys_for(name, samples, "h")
            with self._lock:
                anchors = {
                    k: dict(self._anchor_hist.get(k) or {"n": 0, "s": 0.0})
                    for k in keys
                }
            running_n = {k: int(anchors[k].get("n", 0)) for k in keys}
            running_s = {k: float(anchors[k].get("s", 0.0)) for k in keys}
            for sample in samples:
                for key in keys:
                    entry = sample["h"].get(key)
                    if entry is not None:
                        running_n[key] += entry["n"]
                        running_s[key] += entry["s"]
                    if sample["t"] < cutoff:
                        continue
                    series = out.setdefault(
                        key[len(prefix):], {"t": [], "count": [], "sum": []}
                    )
                    series["t"].append(sample["t"])
                    series["count"].append(running_n[key])
                    series["sum"].append(running_s[key])
            return out
        return {}

    def rate(
        self, name: str, seconds: float | None = None
    ) -> dict[str, float]:
        """Per-series increase/second over the window (counters; for
        histograms the observation-count rate). The first retained
        sample's delta describes pre-window time and is excluded."""
        samples = self._window_samples(seconds)
        if len(samples) < 2:
            return {}
        elapsed = samples[-1]["t"] - samples[0]["t"]
        if elapsed <= 0:
            return {}
        prefix = f"{name}|"
        kind = self._kinds.get(name)
        increase: dict[str, float] = {}
        for sample in samples[1:]:
            if kind == "histogram":
                for key, entry in sample["h"].items():
                    if key.startswith(prefix):
                        increase[key] = increase.get(key, 0.0) + entry["n"]
            else:
                for key, delta in sample["c"].items():
                    if key.startswith(prefix):
                        increase[key] = increase.get(key, 0.0) + delta
        return {
            key[len(prefix):]: total / elapsed
            for key, total in increase.items()
        }

    def quantile(
        self, name: str, q: float, seconds: float | None = None
    ) -> dict[str, Any]:
        """Quantile-over-window from bucket deltas, per series plus the
        all-series merge (the cluster-wide view the dashboard shows)."""
        bounds = self._bounds.get(name)
        if not bounds:
            return {"series": {}, "merged": None}
        samples = self._window_samples(seconds)
        prefix = f"{name}|"
        per_series: dict[str, list[float]] = {}
        merged = [0.0] * (len(bounds) + 1)
        for sample in samples[1:] if len(samples) > 1 else samples:
            for key, entry in sample["h"].items():
                if not key.startswith(prefix):
                    continue
                counts = per_series.setdefault(
                    key[len(prefix):], [0.0] * (len(bounds) + 1)
                )
                for i, delta in enumerate(entry["b"][: len(counts)]):
                    counts[i] += delta
                    merged[i] += delta
        return {
            "series": {
                label: quantile_from_bucket_counts(bounds, counts, q)
                for label, counts in per_series.items()
            },
            "merged": quantile_from_bucket_counts(bounds, merged, q),
        }

    # -- export --------------------------------------------------------------

    def meta(self) -> dict[str, Any]:
        window = self.window()
        with self._lock:
            retained = len(self._samples)
        return {
            "interval_seconds": self.interval,
            "retention_seconds": self.retention,
            "samples": retained,
            "samples_total": self.samples_total,
            "resets_total": self.resets_total,
            "window": list(window) if window else None,
        }

    def summary_dict(self) -> dict[str, Any]:
        """Compact roll-up stamped into metrics artifacts (the
        ``statistics.json`` fold consumes it): per-counter increase + rate
        + trend (second-half rate / first-half rate) over the retained
        window, per-gauge last/min/max/mean."""
        samples = self._snapshot_samples()
        out: dict[str, Any] = {**self.meta(), "counters": {}, "gauges": {}}
        if len(samples) < 2:
            return out
        t0, t1 = samples[0]["t"], samples[-1]["t"]
        elapsed = t1 - t0
        mid = t0 + elapsed / 2.0
        increase: dict[str, float] = {}
        halves: dict[str, list[float]] = {}
        for sample in samples[1:]:
            late = sample["t"] >= mid
            for key, delta in sample["c"].items():
                increase[key] = increase.get(key, 0.0) + delta
                half = halves.setdefault(key, [0.0, 0.0])
                half[1 if late else 0] += delta
        for key, total in increase.items():
            entry: dict[str, Any] = {"increase": total}
            if elapsed > 0:
                entry["rate_per_second"] = total / elapsed
                early, late = halves[key]
                if early > 0:
                    entry["trend"] = late / early
            out["counters"][key] = entry
        gauge_values: dict[str, list[float]] = {}
        for sample in samples:
            for key, value in sample["g"].items():
                gauge_values.setdefault(key, []).append(value)
        for key, values in gauge_values.items():
            out["gauges"][key] = {
                "last": values[-1],
                "min": min(values),
                "max": max(values),
                "mean": sum(values) / len(values),
            }
        return out


class HistorySampler:
    """Asyncio-periodic sampler feeding one ``HistoryStore`` (the history
    analog of ``SnapshotWriter``). ``stop()`` takes a final sample so runs
    shorter than one interval still leave a usable window behind."""

    def __init__(self, store: HistoryStore) -> None:
        self.store = store
        self._task: asyncio.Task | None = None

    async def _run(self) -> None:
        while True:
            try:
                # snapshot() + delta fold go to a thread so a large
                # registry never stalls heartbeat service on the loop.
                await asyncio.to_thread(self.store.sample)
            except Exception as e:  # noqa: BLE001 - observability must not kill jobs
                logger.warning("History sample failed: %s", e)
            await asyncio.sleep(self.store.interval)

    def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="obs-history-sampler")

    async def stop(self, *, final_sample: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if final_sample:
            try:
                self.store.sample()
            except Exception as e:  # noqa: BLE001
                logger.warning("Final history sample failed: %s", e)
