"""Sampled asyncio event-loop lag probe.

The static trc-lint ``loop-blocking`` pass proves no *statically
resolvable* sync call parks the loop; this is the runtime complement —
it measures how late the loop actually runs scheduled callbacks. The
probe sleeps ``TRC_OBS_LOOPMON_INTERVAL`` seconds and compares the
monotonic wake time against the scheduled one: the delta is exactly the
time some other callback held the loop (GC pauses, an unexpectedly-sync
hot path, a compiler sneaking onto the loop). Each sample feeds the
``obs_loop_lag_seconds{role}`` histogram; samples over
``TRC_OBS_LOOPMON_THRESHOLD`` count a blocked episode
(``obs_loop_blocked_episodes_total{role}``), draw a span on the "loop"
Perfetto track covering the blocked window, and — when a flight
recorder is attached — dump a ``loop_lag`` blackbox bundle (debounced
by the recorder's existing ``TRC_OBS_FLIGHT_DEBOUNCE`` machinery).

One monitor per process role: the master (``role="master"``), each
worker runtime (``"worker"``), and the shard router (``"router"``).
"""

from __future__ import annotations

import asyncio
import logging
import time

from tpu_render_cluster.utils.env import env_float

__all__ = ["LoopLagMonitor", "LAG_METRIC", "EPISODES_METRIC"]

logger = logging.getLogger(__name__)

LAG_METRIC = "obs_loop_lag_seconds"
EPISODES_METRIC = "obs_loop_blocked_episodes_total"

_LAG_HELP = "Event-loop callback lag (scheduled vs actual wake) by role"
_EPISODES_HELP = "Loop-lag samples over TRC_OBS_LOOPMON_THRESHOLD by role"


def loopmon_interval_seconds() -> float:
    return max(0.001, env_float("TRC_OBS_LOOPMON_INTERVAL", 0.25))


def loopmon_threshold_seconds() -> float:
    return max(0.0, env_float("TRC_OBS_LOOPMON_THRESHOLD", 0.1))


class LoopLagMonitor:
    """Periodic lag sampler for the current event loop.

    ``start()`` inside a running loop; ``await stop()`` on teardown.
    The span tracer and flight recorder are optional — workers run with
    just the histogram, the master wires all three.
    """

    def __init__(
        self,
        metrics,
        *,
        role: str,
        span_tracer=None,
        flightrec=None,
    ) -> None:
        self.metrics = metrics
        self.role = role
        self.span_tracer = span_tracer
        self.flightrec = flightrec
        self.samples = 0
        self.blocked_episodes = 0
        self.max_lag_seconds = 0.0
        self._lag = metrics.histogram(LAG_METRIC, _LAG_HELP, labels=("role",))
        self._episodes = metrics.counter(
            EPISODES_METRIC, _EPISODES_HELP, labels=("role",)
        )
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(
                self._run(), name=f"loopmon-{self.role}"
            )

    async def stop(self) -> None:
        if self._task is None:
            return
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            interval = loopmon_interval_seconds()
            scheduled = loop.time() + interval
            await asyncio.sleep(interval)
            lag = max(0.0, loop.time() - scheduled)
            self.samples += 1
            self.max_lag_seconds = max(self.max_lag_seconds, lag)
            self._lag.observe(lag, role=self.role)
            if lag >= loopmon_threshold_seconds():
                self._record_episode(lag)

    def _record_episode(self, lag: float) -> None:
        self.blocked_episodes += 1
        self._episodes.inc(role=self.role)
        logger.warning(
            "Event loop (%s) blocked ~%.3fs (threshold %.3fs).",
            self.role, lag, loopmon_threshold_seconds(),
        )
        if self.span_tracer is not None:
            # The lag window ends at the sample; anchor the span so it
            # covers the time the loop was held.
            self.span_tracer.complete(
                "loop blocked",
                cat="obs",
                start_wall=time.time() - lag,
                duration=lag,
                track="loop",
                args={"role": self.role, "lag_s": round(lag, 6)},
            )
        if self.flightrec is not None:
            from tpu_render_cluster.obs.flightrec import TRIGGER_LOOP_LAG

            self.flightrec.trigger(
                TRIGGER_LOOP_LAG,
                {
                    "role": self.role,
                    "lag_seconds": round(lag, 6),
                    "threshold_seconds": loopmon_threshold_seconds(),
                },
            )
