"""Always-on flight recorder: the last N seconds, dumped on incident.

The post-mortem half of the continuous-observability layer. While the
process runs, the recorder costs almost nothing — it *references* the
bounded state other obs components already keep (the history store's
sample ring, the span tracer's event buffer) and maintains one small
deque of protocol-event digests of its own. When something goes wrong it
dumps an atomic blackbox bundle covering the window *leading up to* the
incident — the data that is otherwise already gone by the time anyone
scrapes ``/metrics``.

Trigger seams (wired in master/cluster.py and sched/manager.py):

- ``slo_alert`` — an SLO alert FIRE edge (obs/slo.py ``on_alert``);
- ``worker_eviction`` — a worker marked dead and evicted;
- ``job_failure`` — a job cancelled for a deterministic unit failure
  (``state.failed_reason``);
- ``epoch_fence`` — a worker event refused for echoing a previous master
  incarnation's epoch;
- ``master_failover`` — this incarnation adopted a predecessor's ledger.

Bundle format: a Chrome trace-event document (``traceEvents`` at the top
level, so ``scripts/validate_trace.py`` and Perfetto both load it
directly) plus a ``blackbox`` section carrying the trigger, the sample
window, the history store's metric samples, the protocol-event digests,
and a final registry snapshot. Only complete (``X``), instant (``i``),
and metadata events are included — flow/duration events whose
counterparts fall outside the window would fail the trace validator, and
a blackbox that fails validation is worse than one without arrows.

Dumps are debounced per trigger kind (``TRC_OBS_FLIGHT_DEBOUNCE``): an
eviction storm produces one bundle per kind per window, not hundreds.
Every ACTUAL dump is counted in ``obs_flight_dumps_total{trigger}``.

Tuning: ``TRC_OBS_FLIGHT_SECONDS`` (window, default 60),
``TRC_OBS_FLIGHT_EVENTS`` (protocol-digest ring size),
``TRC_OBS_FLIGHT_DEBOUNCE`` (seconds between dumps per trigger),
``TRC_OBS_FLIGHT_DIR`` (dump directory; without one — explicit, env, or
derived from the metrics snapshot path — triggers are still counted and
recorded in ``view()`` but no file is written).
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import TYPE_CHECKING, Any

from tpu_render_cluster.utils.env import env_float, env_str

if TYPE_CHECKING:
    from tpu_render_cluster.obs.history import HistoryStore
    from tpu_render_cluster.obs.registry import MetricsRegistry
    from tpu_render_cluster.obs.tracer import Tracer

logger = logging.getLogger(__name__)

__all__ = ["FlightRecorder", "resolve_flight_directory"]

TRIGGER_SLO_ALERT = "slo_alert"
TRIGGER_WORKER_EVICTION = "worker_eviction"
TRIGGER_JOB_FAILURE = "job_failure"
TRIGGER_EPOCH_FENCE = "epoch_fence"
TRIGGER_MASTER_FAILOVER = "master_failover"
TRIGGER_PROMOTION = "promotion"
TRIGGER_LOOP_LAG = "loop_lag"
TRIGGER_TICK_BUDGET = "tick_budget"


def flight_window_seconds() -> float:
    return max(1.0, env_float("TRC_OBS_FLIGHT_SECONDS", 60.0))


def flight_debounce_seconds() -> float:
    return max(0.0, env_float("TRC_OBS_FLIGHT_DEBOUNCE", 5.0))


def flight_max_events() -> int:
    return max(16, int(env_float("TRC_OBS_FLIGHT_EVENTS", 4096)))


def resolve_flight_directory(
    explicit: str | Path | None, fallback: str | Path | None = None
) -> Path | None:
    """Explicit argument wins, else ``TRC_OBS_FLIGHT_DIR``, else the
    caller's fallback (the metrics snapshot's directory), else None."""
    if explicit is not None:
        return Path(explicit)
    env = env_str("TRC_OBS_FLIGHT_DIR")
    if env:
        return Path(env)
    if fallback is not None:
        return Path(fallback)
    return None


class FlightRecorder:
    """One process's blackbox: bounded recent context + triggered dumps."""

    def __init__(
        self,
        *,
        history: "HistoryStore | None" = None,
        span_tracer: "Tracer | None" = None,
        metrics: "MetricsRegistry | None" = None,
        directory: str | Path | None = None,
        window_seconds: float | None = None,
        process_name: str = "master",
    ) -> None:
        self.history = history
        self.span_tracer = span_tracer
        self.metrics = metrics
        self.directory = Path(directory) if directory is not None else None
        self.window_seconds = (
            window_seconds if window_seconds is not None else flight_window_seconds()
        )
        self.process_name = process_name
        self._lock = threading.Lock()
        self._events: deque[tuple[float, str, dict[str, Any]]] = deque(
            maxlen=flight_max_events()
        )
        self._last_dump: dict[str, float] = {}
        self._sequence = 0
        # Every trigger attempt (incl. debounced) and every actual dump.
        # The dump ledger is bounded like SloService.alerts: a long-lived
        # service with recurring incidents must not grow it (or the
        # /clusterz view serializing it) without limit; the counter keeps
        # the lifetime totals.
        self.triggers: dict[str, int] = {}
        self.dumps: deque[dict[str, Any]] = deque(maxlen=256)
        # Deferred bundle writes in flight (loop contexts only).
        self._pending: set = set()
        self._last_write_ok = True

    # -- recording -----------------------------------------------------------

    def record_event(self, kind: str, **detail: Any) -> None:
        """One protocol-event digest (dispatch, finished, refusal, ...):
        cheap enough for the master's hottest paths — a deque append."""
        self._events.append((time.time(), str(kind), detail))

    # -- triggering ----------------------------------------------------------

    def trigger(
        self, trigger: str, detail: dict[str, Any] | None = None
    ) -> Path | None:
        """Dump a blackbox bundle for ``trigger`` (debounced per kind).

        Returns the bundle path, or None when debounced / no directory is
        configured (the trigger is still counted and recorded either way).
        """
        now = time.time()
        with self._lock:
            self.triggers[trigger] = self.triggers.get(trigger, 0) + 1
            last = self._last_dump.get(trigger, -math.inf)
            if now - last < flight_debounce_seconds():
                return None
            self._last_dump[trigger] = now
            self._sequence += 1
            sequence = self._sequence
        bundle = self._build_bundle(trigger, detail or {}, now)
        path: Path | None = None
        if self.directory is not None:
            path = (
                self.directory
                / f"{self.process_name}-{sequence:03d}-{trigger}_blackbox.json"
            )
            if not self._dispatch_write(path, bundle):
                path = None
        record = {
            "trigger": trigger,
            "at": now,
            "window": bundle["blackbox"]["window"],
            "path": str(path) if path is not None else None,
        }
        with self._lock:
            self.dumps.append(record)
        if self.metrics is not None:
            self.metrics.counter(
                "obs_flight_dumps_total",
                "Flight-recorder blackbox bundles dumped, by trigger",
                labels=("trigger",),
            ).inc(trigger=trigger)
        if self.span_tracer is not None:
            self.span_tracer.instant(
                f"flight dump {trigger}",
                cat="flight",
                track="flights",
                args={"trigger": trigger, **(detail or {})},
            )
        logger.warning(
            "Flight recorder dumped (%s): %s", trigger, path or "<in-memory>"
        )
        return path

    # -- bundle assembly -----------------------------------------------------

    def _build_bundle(
        self, trigger: str, detail: dict[str, Any], now: float
    ) -> dict[str, Any]:
        t0 = now - self.window_seconds
        trace_events: list[dict[str, Any]] = []
        if self.span_tracer is not None:
            trace_events.extend(self.span_tracer.metadata_events())
            t0_us, now_us = t0 * 1e6, now * 1e6
            for event in self.span_tracer.events():
                ph = event.get("ph")
                ts = event.get("ts")
                if not isinstance(ts, (int, float)):
                    continue
                if ph == "X":
                    # Include spans OVERLAPPING the window (a long-running
                    # job span that started before it still matters).
                    if ts <= now_us and ts + float(event.get("dur", 0)) >= t0_us:
                        trace_events.append(event)
                elif ph == "i" and t0_us <= ts <= now_us:
                    trace_events.append(event)
                # B/E and flow events are dropped: their counterparts may
                # fall outside the cut and the bundle must validate clean.
        # Bounded on BOTH edges: the sampler thread runs concurrently with
        # this build, so a sample stamped just after `now` would otherwise
        # land in the bundle outside its declared window and fail the
        # blackbox validator.
        samples = (
            [s for s in self.history.samples_since(t0) if s["t"] <= now]
            if self.history is not None
            else []
        )
        protocol_events = [
            {"t": t, "kind": kind, **digest}
            for t, kind, digest in list(self._events)
            if t0 <= t <= now
        ]
        blackbox: dict[str, Any] = {
            "trigger": trigger,
            "detail": detail,
            "process": self.process_name,
            "dumped_at": now,
            "window": [t0, now],
            "metric_samples": samples,
            "protocol_events": protocol_events,
        }
        if self.history is not None:
            blackbox["history_meta"] = self.history.meta()
        if self.metrics is not None:
            blackbox["final_metrics"] = self.metrics.snapshot()
        return {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {"blackbox_trigger": trigger},
            "blackbox": blackbox,
        }

    def _dispatch_write(self, path: Path, bundle: dict[str, Any]) -> bool:
        """Write the bundle WITHOUT ever holding an event loop.

        The triggers fire inside the master's async handlers (SLO fires,
        evictions, epoch-fence refusals), where the serialize+fsync of a
        multi-megabyte bundle would stall heartbeat service exactly when
        the cluster is already in trouble. On a running loop the atomic
        write is deferred to ``asyncio.to_thread`` (tracked; ``drain()``
        awaits it at shutdown so no bundle is lost to loop teardown).
        Without a loop the write still runs on a short-lived worker
        thread — structurally, ``_write_atomic`` cannot execute on a
        thread that owns a running event loop, which is also what keeps
        the loop-blocking lint clean without suppressions.

        Returns False only on a synchronous write failure; deferred
        failures are logged by the writer task (the recorded ``path`` of
        such a dump may then name a file that never landed — the log
        line and the bundle's absence are the post-mortem's post-mortem).
        """
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            task = loop.create_task(
                self._write_deferred(path, bundle),
                name=f"flightrec-dump-{path.name}",
            )
            self._pending.add(task)
            task.add_done_callback(self._pending.discard)
            return True
        worker = threading.Thread(
            target=self._write_checked, args=(path, bundle), daemon=True
        )
        worker.start()
        worker.join()
        return self._last_write_ok

    async def _write_deferred(self, path: Path, bundle: dict[str, Any]) -> None:
        await asyncio.to_thread(self._write_checked, path, bundle)

    def _write_checked(self, path: Path, bundle: dict[str, Any]) -> None:
        try:
            self._write_atomic(path, bundle)
            self._last_write_ok = True
        except OSError as e:
            self._last_write_ok = False
            logger.error("Flight-recorder dump to %s failed: %s", path, e)

    async def drain(self) -> None:
        """Await every deferred bundle write (call before loop teardown)."""
        while self._pending:
            await asyncio.gather(*list(self._pending), return_exceptions=True)

    @staticmethod
    def _write_atomic(path: Path, bundle: dict[str, Any]) -> None:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        with open(tmp, "w", encoding="utf-8") as handle:
            json.dump(bundle, handle)
            handle.flush()
            os.fsync(handle.fileno())
        os.replace(tmp, path)

    # -- views ---------------------------------------------------------------

    def view(self) -> dict[str, Any]:
        with self._lock:
            return {
                "window_seconds": self.window_seconds,
                "directory": str(self.directory) if self.directory else None,
                "triggers": dict(self.triggers),
                "dumps": list(self.dumps),
            }
