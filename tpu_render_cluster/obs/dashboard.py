"""Live terminal dashboard over the pull-based telemetry endpoints.

``python -m tpu_render_cluster.obs.dashboard --port <telemetryPort>``
polls a master's ``/metrics`` (Prometheus text exposition, parsed with
``obs.prometheus.parse_prometheus``) and ``/clusterz`` (the live
``cluster_view()``) and redraws a one-screen operator view:

- cluster totals + per-worker queue depth;
- per-job progress and achieved-vs-target fair share;
- unit-latency percentiles reconstructed from the
  ``master_unit_latency_seconds`` histogram buckets;
- the speculation and assembly ledgers;
- SLO attainment/burn per job and the most recent alert edges.

Stdlib-only (urllib + ANSI clears), like the rest of ``obs``: the
dashboard must run on any operator box that can reach the master, with
nothing installed. All rendering is pure (``render_dashboard``) so the
tier-1 tests exercise it against canned endpoint payloads; ``--once``
prints a single frame and exits (scripts, smoke tests).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Iterable

from tpu_render_cluster.obs.prometheus import parse_prometheus

__all__ = [
    "fetch_endpoints",
    "histogram_quantiles",
    "render_dashboard",
    "main",
]

Samples = dict[str, list[tuple[dict[str, str], float]]]

_CLEAR = "\x1b[2J\x1b[H"


def fetch_endpoints(
    host: str, port: int, timeout: float = 5.0
) -> tuple[Samples, dict[str, Any]]:
    """One poll: parsed ``/metrics`` samples + the ``/clusterz`` JSON.

    A worker endpoint (no cluster view, /clusterz is 404) yields an empty
    dict for the second element rather than failing the poll.
    """
    base = f"http://{host}:{port}"
    with urllib.request.urlopen(f"{base}/metrics", timeout=timeout) as resp:
        metrics = parse_prometheus(resp.read().decode("utf-8"))
    try:
        with urllib.request.urlopen(f"{base}/clusterz", timeout=timeout) as resp:
            clusterz = json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise
        clusterz = {}
    return metrics, clusterz


def histogram_quantiles(
    samples: Samples, name: str, quantiles: Iterable[float]
) -> dict[float, float] | None:
    """Quantile estimates from a histogram's ``_bucket`` expansion.

    The classic cumulative-bucket walk with linear interpolation inside
    the landing bucket (what promql's histogram_quantile does); the +Inf
    bucket clamps to the previous finite bound. Buckets with differing
    labels (multi-series histograms) are summed — the dashboard shows the
    cluster-wide distribution. Returns None when the histogram is absent
    or empty.
    """
    rows = samples.get(f"{name}_bucket")
    if not rows:
        return None
    by_bound: dict[float, float] = {}
    for labels, value in rows:
        le = labels.get("le")
        if le is None:
            continue
        bound = float("inf") if le == "+Inf" else float(le)
        by_bound[bound] = by_bound.get(bound, 0.0) + value
    bounds = sorted(by_bound)
    if not bounds:
        return None
    total = by_bound[bounds[-1]]
    if total <= 0:
        return None
    out: dict[float, float] = {}
    for q in quantiles:
        rank = q * total
        previous_bound = 0.0
        previous_count = 0.0
        for bound in bounds:
            count = by_bound[bound]
            if count >= rank:
                if bound == float("inf"):
                    out[q] = previous_bound
                elif count == previous_count:
                    out[q] = bound
                else:
                    fraction = (rank - previous_count) / (count - previous_count)
                    out[q] = previous_bound + fraction * (bound - previous_bound)
                break
            previous_bound, previous_count = bound, count
        else:
            out[q] = bounds[-2] if len(bounds) > 1 else bounds[-1]
    return out


def _sample_value(
    samples: Samples, name: str, **labels: str
) -> float | None:
    for sample_labels, value in samples.get(name, ()):
        if all(sample_labels.get(k) == v for k, v in labels.items()):
            return value
    return None


def _fmt_seconds(value: float | None) -> str:
    if value is None:
        return "-"
    if value < 1e-3:
        return f"{value * 1e6:.0f}us"
    if value < 1.0:
        return f"{value * 1e3:.1f}ms"
    return f"{value:.2f}s"


def _fmt_share(value: Any) -> str:
    return f"{value:.2f}" if isinstance(value, (int, float)) else "-"


def render_dashboard(
    samples: Samples, clusterz: dict[str, Any], *, now: float | None = None
) -> str:
    """One dashboard frame as plain text (pure: canned payloads in, text
    out — the tests and --once path share it with the live loop)."""
    lines: list[str] = []
    cluster = clusterz.get("cluster") or {}
    stamp = time.strftime(
        "%H:%M:%S", time.localtime(now if now is not None else time.time())
    )
    lines.append(f"tpu-render-cluster telemetry  [{stamp}]")
    lines.append("=" * 72)

    frames_total = cluster.get("frames_total", 0)
    frames_finished = cluster.get("frames_finished", 0)
    frames_pending = cluster.get("frames_pending", 0)
    lines.append(
        f"units: {frames_finished}/{frames_total} finished, "
        f"{frames_pending} pending"
    )

    workers = cluster.get("workers") or {}
    if workers:
        lines.append("")
        lines.append(f"{'worker':<28} {'queue':>5} {'stolen':>6}  state")
        for worker_id, info in sorted(workers.items()):
            state = "DEAD" if info.get("is_dead") else "live"
            lines.append(
                f"{worker_id:<28} {info.get('queue_depth', 0):>5} "
                f"{info.get('frames_stolen', 0):>6}  {state}"
            )

    jobs = clusterz.get("jobs") or {}
    if jobs:
        lines.append("")
        lines.append(
            f"{'job':<24} {'state':<9} {'done':>9} "
            f"{'share':>6} {'target':>6}"
        )
        for name, info in sorted(jobs.items()):
            done = f"{info.get('frames_finished', 0)}/{info.get('frames_total', 0)}"
            lines.append(
                f"{name:<24} {str(info.get('state', '-')):<9} {done:>9} "
                f"{_fmt_share(info.get('share_achieved')):>6} "
                f"{_fmt_share(info.get('share_target')):>6}"
            )

    quantiles = histogram_quantiles(
        samples, "master_unit_latency_seconds", (0.5, 0.9, 0.99)
    )
    if quantiles:
        lines.append("")
        lines.append(
            "unit latency  p50 "
            f"{_fmt_seconds(quantiles.get(0.5))}   p90 "
            f"{_fmt_seconds(quantiles.get(0.9))}   p99 "
            f"{_fmt_seconds(quantiles.get(0.99))}"
        )

    speculation = clusterz.get("speculation") or {}
    if speculation.get("launched"):
        outcomes = speculation.get("outcomes") or {}
        lines.append(
            f"speculation   launched {speculation['launched']}  "
            + "  ".join(f"{k} {v}" for k, v in sorted(outcomes.items()))
        )

    assembled = [
        (name, info["assembly"])
        for name, info in sorted(jobs.items())
        if isinstance(info.get("assembly"), dict)
    ]
    for name, assembly in assembled:
        lines.append(
            f"assembly      {name}: {assembly.get('frames_assembled', 0)} "
            f"stitched, {assembly.get('frames_partial', 0)} partial "
            f"({assembly.get('tiles_per_frame', 1)} tiles/frame)"
        )

    slo = clusterz.get("slo") or {}
    slo_jobs = slo.get("jobs") or {}
    if slo_jobs:
        lines.append("")
        lines.append(
            f"{'SLO job':<24} {'attain':>7} {'burn_s':>7} {'burn_l':>7}  firing"
        )
        for name, info in sorted(slo_jobs.items()):
            attainment = info.get("attainment")
            attain_str = f"{attainment:.3f}" if attainment is not None else "-"
            burn = info.get("burn") or {}
            firing = ",".join(info.get("firing") or ()) or "-"
            lines.append(
                f"{name:<24} {attain_str:>7} "
                f"{burn.get('short', 0.0):>7.2f} "
                f"{burn.get('long', 0.0):>7.2f}  {firing}"
            )
    alerts = slo.get("alerts") or []
    for alert in alerts[-5:]:
        at = time.strftime("%H:%M:%S", time.localtime(alert.get("at", 0)))
        lines.append(
            f"alert  [{at}] {alert.get('job_name')} {alert.get('kind')} "
            f"{str(alert.get('transition', '')).upper()}"
        )

    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Live terminal dashboard over the telemetry endpoints"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--port", type=int, required=True,
        help="The master's --telemetryPort (or TRC_OBS_PORT)",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--once", action="store_true",
        help="Print one frame and exit (scripts, smoke tests)",
    )
    args = parser.parse_args(argv)
    while True:
        try:
            samples, clusterz = fetch_endpoints(args.host, args.port)
        except (OSError, urllib.error.URLError, ValueError) as e:
            frame = f"telemetry endpoint unreachable: {e}\n"
        else:
            frame = render_dashboard(samples, clusterz)
        if args.once:
            sys.stdout.write(frame)
            return 0
        sys.stdout.write(_CLEAR + frame)
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
