"""Live terminal dashboard over the pull-based telemetry endpoints.

``python -m tpu_render_cluster.obs.dashboard --port <telemetryPort>``
polls a master's ``/metrics`` (Prometheus text exposition, parsed with
``obs.prometheus.parse_prometheus``) and ``/clusterz`` (the live
``cluster_view()``) and redraws a one-screen operator view:

- cluster totals + per-worker queue depth;
- per-job progress and achieved-vs-target fair share;
- unit-latency percentiles reconstructed from the
  ``master_unit_latency_seconds`` histogram buckets;
- the speculation and assembly ledgers;
- SLO attainment/burn per job and the most recent alert edges;
- sparkline columns over the embedded metrics history (``/history``,
  obs/history.py): per-interval unit-completion rate and queue depth,
  so a stall or burst is visible as a *shape*, not one number;
- a "where did the time go" panel from the attribution families:
  sched-tick phase cost (``sched_tick_seconds{phase}``), event-loop lag
  per role (``obs_loop_lag_seconds``), and the wire's top talkers by
  ``transport_message_bytes_total{tag,direction}``;
- an HA section when the endpoint is the shard router's federated view
  (ha/shards.py): per-shard routed requests, ledger append p99
  (``ha_ledger_append_seconds``), and last-failover MTTR.

Stdlib-only (urllib + ANSI clears), like the rest of ``obs``: the
dashboard must run on any operator box that can reach the master, with
nothing installed. All rendering is pure (``render_dashboard``) so the
tier-1 tests exercise it against canned endpoint payloads; ``--once``
prints a single frame and exits (scripts, smoke tests).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Iterable

from tpu_render_cluster.obs.prometheus import parse_prometheus

__all__ = [
    "fetch_endpoints",
    "fetch_history",
    "histogram_quantiles",
    "render_dashboard",
    "sparkline",
    "main",
]

Samples = dict[str, list[tuple[dict[str, str], float]]]

_CLEAR = "\x1b[2J\x1b[H"

# History series the dashboard sparklines by default: the unit-completion
# counter (rendered as per-interval rate) and the queue-depth gauge.
HISTORY_NAMES = (
    "master_frame_results_total",
    "master_worker_queue_depth",
)

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 32) -> str:
    """Unicode block sparkline over ``values`` (newest right), resampled
    to ``width`` columns; a flat series renders as a flat low line."""
    if not values:
        return ""
    if len(values) > width:
        # Keep the newest `width` points — the dashboard shows recency.
        values = values[-width:]
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK_BLOCKS[0] * len(values)
    top = len(_SPARK_BLOCKS) - 1
    return "".join(
        _SPARK_BLOCKS[int(round((v - lo) / span * top))] for v in values
    )


def fetch_endpoints(
    host: str, port: int, timeout: float = 5.0
) -> tuple[Samples, dict[str, Any]]:
    """One poll: parsed ``/metrics`` samples + the ``/clusterz`` JSON.

    A worker endpoint (no cluster view, /clusterz is 404) yields an empty
    dict for the second element rather than failing the poll.
    """
    base = f"http://{host}:{port}"
    with urllib.request.urlopen(f"{base}/metrics", timeout=timeout) as resp:
        metrics = parse_prometheus(resp.read().decode("utf-8"))
    try:
        with urllib.request.urlopen(f"{base}/clusterz", timeout=timeout) as resp:
            clusterz = json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise
        clusterz = {}
    return metrics, clusterz


def fetch_history(
    host: str,
    port: int,
    names: Iterable[str] = HISTORY_NAMES,
    timeout: float = 5.0,
) -> dict[str, Any]:
    """Range series for each ``name`` from ``/history`` (absent store —
    a pre-history master, a 404 — yields an empty dict, never a failed
    poll)."""
    out: dict[str, Any] = {}
    for name in names:
        url = (
            f"http://{host}:{port}/history?name="
            f"{urllib.parse.quote(name)}"
        )
        try:
            with urllib.request.urlopen(url, timeout=timeout) as response:
                document = json.loads(response.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            return {}
        if document.get("ok") and document.get("series"):
            out[name] = document
    return out


def histogram_quantiles(
    samples: Samples,
    name: str,
    quantiles: Iterable[float],
    where: dict[str, str] | None = None,
) -> dict[float, float] | None:
    """Quantile estimates from a histogram's ``_bucket`` expansion.

    The classic cumulative-bucket walk with linear interpolation inside
    the landing bucket (what promql's histogram_quantile does); the +Inf
    bucket clamps to the previous finite bound. Buckets with differing
    labels (multi-series histograms) are summed — the dashboard shows the
    cluster-wide distribution — unless ``where`` narrows them (the HA
    section computes per-shard percentiles from federated samples this
    way). Returns None when the histogram is absent or empty.
    """
    rows = samples.get(f"{name}_bucket")
    if not rows:
        return None
    by_bound: dict[float, float] = {}
    for labels, value in rows:
        if where and any(labels.get(k) != v for k, v in where.items()):
            continue
        le = labels.get("le")
        if le is None:
            continue
        bound = float("inf") if le == "+Inf" else float(le)
        by_bound[bound] = by_bound.get(bound, 0.0) + value
    bounds = sorted(by_bound)
    if not bounds:
        return None
    total = by_bound[bounds[-1]]
    if total <= 0:
        return None
    out: dict[float, float] = {}
    for q in quantiles:
        rank = q * total
        previous_bound = 0.0
        previous_count = 0.0
        for bound in bounds:
            count = by_bound[bound]
            if count >= rank:
                if bound == float("inf"):
                    out[q] = previous_bound
                elif count == previous_count:
                    out[q] = bound
                else:
                    fraction = (rank - previous_count) / (count - previous_count)
                    out[q] = previous_bound + fraction * (bound - previous_bound)
                break
            previous_bound, previous_count = bound, count
        else:
            # Rank past every bucket (float noise in the cumulative sums):
            # clamp to the largest FINITE bound. A degenerate histogram
            # whose only bucket is +Inf yields no estimate for this
            # quantile rather than an "inf" row.
            finite = [b for b in bounds if b != float("inf")]
            if finite:
                out[q] = finite[-1]
    return out or None


def _sample_value(
    samples: Samples, name: str, **labels: str
) -> float | None:
    for sample_labels, value in samples.get(name, ()):
        if all(sample_labels.get(k) == v for k, v in labels.items()):
            return value
    return None


def _fmt_seconds(value: float | None) -> str:
    if value is None or not math.isfinite(value):
        return "-"
    if value < 1e-3:
        return f"{value * 1e6:.0f}us"
    if value < 1.0:
        return f"{value * 1e3:.1f}ms"
    return f"{value:.2f}s"


def _fmt_share(value: Any) -> str:
    return f"{value:.2f}" if isinstance(value, (int, float)) else "-"


def _history_sparkline_rows(history: dict[str, Any]) -> list[str]:
    """Sparkline rows from /history range responses: counters render as
    per-interval deltas (the *rate* shape), gauges as raw values."""
    rows: list[str] = []
    for name, document in sorted(history.items()):
        kind = document.get("kind")
        for label_str, series in sorted((document.get("series") or {}).items()):
            values = [float(v) for v in series.get("v") or []]
            if not values:
                continue
            if kind == "counter":
                values = [
                    b - a for a, b in zip(values, values[1:])
                ] or values
                suffix = f"rate~{values[-1]:g}/t" if values else ""
            else:
                suffix = f"last={values[-1]:g}"
            label = f"{name}{{{label_str}}}" if label_str else name
            rows.append(f"{label:<44.44} {sparkline(values):<32} {suffix}")
    return rows


def _fmt_bytes(value: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if value < 1024 or unit == "GiB":
            return f"{value:.0f}{unit}" if unit == "B" else f"{value:.1f}{unit}"
        value /= 1024
    return f"{value:.1f}GiB"  # unreachable; keeps the signature total


def load_sched_bench(path: str | None = None) -> dict[str, Any] | None:
    """The committed control-plane A/B record (``bench.py --sched`` →
    ``results/SCHED_BENCH.json``), or None when absent/unreadable — the
    dashboard must render fine on a checkout that never ran the bench."""
    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            "results",
            "SCHED_BENCH.json",
        )
    try:
        with open(path, "r", encoding="utf-8") as f:
            record = json.load(f)
    except (OSError, ValueError):
        return None
    return record if isinstance(record, dict) else None


def _render_time_section(
    samples: Samples, sched_bench: dict[str, Any] | None = None
) -> list[str]:
    """The "where did the time go" panel: sched-tick phase costs, event
    loop lag per role, the wire's top talkers — all reconstructed from
    the attribution metric families, all optional (a pre-PR-16 endpoint
    or an idle cluster just renders nothing here) — plus, when a
    committed ``results/SCHED_BENCH.json`` exists, the before/after
    control-plane A/B (assignments/s and share_scan p99 per tick mode)."""
    lines: list[str] = []

    phases = sorted(
        {
            labels.get("phase", "")
            for labels, _value in samples.get("sched_tick_seconds_count", ())
        }
        - {""}
    )
    phase_rows: list[str] = []
    for phase in phases:
        count = sum(
            value
            for labels, value in samples.get("sched_tick_seconds_count", ())
            if labels.get("phase") == phase
        )
        if count <= 0:
            continue
        total = sum(
            value
            for labels, value in samples.get("sched_tick_seconds_sum", ())
            if labels.get("phase") == phase
        )
        quantiles = histogram_quantiles(
            samples, "sched_tick_seconds", (0.5, 0.99), where={"phase": phase}
        ) or {}
        phase_rows.append(
            f"{phase:<20} {count:>7.0f} {_fmt_seconds(total / count):>9} "
            f"{_fmt_seconds(quantiles.get(0.5)):>9} "
            f"{_fmt_seconds(quantiles.get(0.99)):>9}"
        )
    if phase_rows:
        lines.append("")
        lines.append(
            f"{'sched tick phase':<20} {'ticks':>7} {'mean':>9} "
            f"{'p50':>9} {'p99':>9}"
        )
        lines.extend(phase_rows)
        budget = _sample_value(samples, "sched_tick_budget_ratio")
        if budget is not None and math.isfinite(budget):
            lines.append(f"tick budget used: {budget:.2f}x")

    roles = sorted(
        {
            labels.get("role", "")
            for labels, _value in samples.get("obs_loop_lag_seconds_count", ())
        }
        - {""}
    )
    lag_rows: list[str] = []
    for role in roles:
        count = sum(
            value
            for labels, value in samples.get("obs_loop_lag_seconds_count", ())
            if labels.get("role") == role
        )
        if count <= 0:
            continue
        quantiles = histogram_quantiles(
            samples, "obs_loop_lag_seconds", (0.99,), where={"role": role}
        ) or {}
        episodes = sum(
            value
            for labels, value in samples.get(
                "obs_loop_blocked_episodes_total", ()
            )
            if labels.get("role") == role
        )
        lag_rows.append(
            f"{role:<12} {count:>7.0f} {_fmt_seconds(quantiles.get(0.99)):>9} "
            f"{episodes:>8.0f}"
        )
    if lag_rows:
        lines.append("")
        lines.append(
            f"{'loop lag':<12} {'samples':>7} {'p99':>9} {'blocked':>8}"
        )
        lines.extend(lag_rows)

    by_tag: dict[str, dict[str, float]] = {}
    for labels, value in samples.get("transport_message_bytes_total", ()):
        tag = labels.get("tag", "?")
        entry = by_tag.setdefault(tag, {"send": 0.0, "recv": 0.0})
        direction = labels.get("direction", "send")
        entry[direction if direction in entry else "send"] += value
    talkers = sorted(
        by_tag.items(), key=lambda kv: -(kv[1]["send"] + kv[1]["recv"])
    )[:5]
    if talkers:
        lines.append("")
        lines.append(
            f"{'wire top talkers':<36} {'send':>10} {'recv':>10}"
        )
        for tag, entry in talkers:
            lines.append(
                f"{tag:<36.36} {_fmt_bytes(entry['send']):>10} "
                f"{_fmt_bytes(entry['recv']):>10}"
            )

    if sched_bench:
        rows: list[str] = []
        for mode in ("scan", "heap"):
            entry = sched_bench.get(mode)
            if not isinstance(entry, dict):
                continue
            rate = entry.get("assignments_per_s")
            p99 = entry.get("share_scan_p99_s")
            rows.append(
                f"{str(entry.get('tick_mode', mode)):<32.32} "
                f"{rate if rate is not None else '-':>9} "
                f"{_fmt_seconds(p99):>9}"
            )
        if rows:
            lines.append("")
            lines.append(
                f"{'sched A/B (SCHED_BENCH.json)':<32} {'assign/s':>9} "
                f"{'scan p99':>9}"
            )
            lines.extend(rows)
            speedup = sched_bench.get("speedup_assignments_per_s")
            if isinstance(speedup, (int, float)):
                lines.append(
                    f"speedup {speedup:.2f}x @ "
                    f"{sched_bench.get('jobs', '?')} concurrent jobs"
                )
    return lines


def _ha_shard_ids(samples: Samples) -> list[str]:
    """Shard ids present in the federated HA families ('all' fan-out rows
    excluded — they aggregate, they aren't a shard)."""
    shards: set[str] = set()
    for name in (
        "ha_router_requests_total",
        "ha_router_jobs_routed_total",
        "ha_router_scrapes_total",
        "ha_ledger_append_seconds_count",
        "ha_failover_mttr_seconds",
    ):
        for labels, _value in samples.get(name, ()):
            shard = labels.get("shard")
            if shard is not None and shard != "all":
                shards.add(shard)
    return sorted(shards, key=lambda s: (len(s), s))


def _render_ha_section(samples: Samples) -> list[str]:
    shards = _ha_shard_ids(samples)
    if not shards:
        return []
    lines = ["", f"{'HA shard':<9} {'requests':>8} {'jobs':>5} "
                 f"{'append p99':>11} {'last MTTR':>10}"]
    for shard in shards:
        requests = sum(
            value
            for labels, value in samples.get("ha_router_requests_total", ())
            if labels.get("shard") == shard
        )
        jobs = sum(
            value
            for labels, value in samples.get("ha_router_jobs_routed_total", ())
            if labels.get("shard") == shard
        )
        append_quantiles = histogram_quantiles(
            samples,
            "ha_ledger_append_seconds",
            (0.99,),
            where={"shard": shard},
        )
        mttr = _sample_value(
            samples, "ha_failover_mttr_seconds", shard=shard
        )
        lines.append(
            f"{'s' + shard:<9} {requests:>8.0f} {jobs:>5.0f} "
            f"{_fmt_seconds(append_quantiles.get(0.99) if append_quantiles else None):>11} "
            f"{_fmt_seconds(mttr):>10}"
        )
    return lines


def render_dashboard(
    samples: Samples,
    clusterz: dict[str, Any],
    *,
    history: dict[str, Any] | None = None,
    now: float | None = None,
    sched_bench: dict[str, Any] | None = None,
) -> str:
    """One dashboard frame as plain text (pure: canned payloads in, text
    out — the tests and --once path share it with the live loop)."""
    lines: list[str] = []
    cluster = clusterz.get("cluster") or {}
    stamp = time.strftime(
        "%H:%M:%S", time.localtime(now if now is not None else time.time())
    )
    lines.append(f"tpu-render-cluster telemetry  [{stamp}]")
    lines.append("=" * 72)

    frames_total = cluster.get("frames_total", 0)
    frames_finished = cluster.get("frames_finished", 0)
    frames_pending = cluster.get("frames_pending", 0)
    lines.append(
        f"units: {frames_finished}/{frames_total} finished, "
        f"{frames_pending} pending"
    )

    workers = cluster.get("workers") or {}
    if workers:
        lines.append("")
        lines.append(f"{'worker':<28} {'queue':>5} {'stolen':>6}  state")
        for worker_id, info in sorted(workers.items()):
            state = "DEAD" if info.get("is_dead") else "live"
            lines.append(
                f"{worker_id:<28} {info.get('queue_depth', 0):>5} "
                f"{info.get('frames_stolen', 0):>6}  {state}"
            )

    jobs = clusterz.get("jobs") or {}
    if jobs:
        lines.append("")
        lines.append(
            f"{'job':<24} {'state':<9} {'done':>9} "
            f"{'share':>6} {'target':>6}"
        )
        for name, info in sorted(jobs.items()):
            done = f"{info.get('frames_finished', 0)}/{info.get('frames_total', 0)}"
            lines.append(
                f"{name:<24} {str(info.get('state', '-')):<9} {done:>9} "
                f"{_fmt_share(info.get('share_achieved')):>6} "
                f"{_fmt_share(info.get('share_target')):>6}"
            )

    quantiles = histogram_quantiles(
        samples, "master_unit_latency_seconds", (0.5, 0.9, 0.99)
    )
    if quantiles:
        lines.append("")
        lines.append(
            "unit latency  p50 "
            f"{_fmt_seconds(quantiles.get(0.5))}   p90 "
            f"{_fmt_seconds(quantiles.get(0.9))}   p99 "
            f"{_fmt_seconds(quantiles.get(0.99))}"
        )

    speculation = clusterz.get("speculation") or {}
    if speculation.get("launched"):
        outcomes = speculation.get("outcomes") or {}
        lines.append(
            f"speculation   launched {speculation['launched']}  "
            + "  ".join(f"{k} {v}" for k, v in sorted(outcomes.items()))
        )

    assembled = [
        (name, info["assembly"])
        for name, info in sorted(jobs.items())
        if isinstance(info.get("assembly"), dict)
    ]
    for name, assembly in assembled:
        lines.append(
            f"assembly      {name}: {assembly.get('frames_assembled', 0)} "
            f"stitched, {assembly.get('frames_partial', 0)} partial "
            f"({assembly.get('tiles_per_frame', 1)} tiles/frame)"
        )

    slo = clusterz.get("slo") or {}
    slo_jobs = slo.get("jobs") or {}
    if slo_jobs:
        lines.append("")
        lines.append(
            f"{'SLO job':<24} {'attain':>7} {'burn_s':>7} {'burn_l':>7}  firing"
        )
        for name, info in sorted(slo_jobs.items()):
            attainment = info.get("attainment")
            attain_str = f"{attainment:.3f}" if attainment is not None else "-"
            burn = info.get("burn") or {}
            firing = ",".join(info.get("firing") or ()) or "-"
            lines.append(
                f"{name:<24} {attain_str:>7} "
                f"{burn.get('short', 0.0):>7.2f} "
                f"{burn.get('long', 0.0):>7.2f}  {firing}"
            )
    alerts = slo.get("alerts") or []
    for alert in alerts[-5:]:
        at = time.strftime("%H:%M:%S", time.localtime(alert.get("at", 0)))
        lines.append(
            f"alert  [{at}] {alert.get('job_name')} {alert.get('kind')} "
            f"{str(alert.get('transition', '')).upper()}"
        )

    lines.extend(_render_time_section(samples, sched_bench=sched_bench))
    lines.extend(_render_ha_section(samples))

    if history:
        rows = _history_sparkline_rows(history)
        if rows:
            lines.append("")
            lines.append("history")
            lines.extend(rows)

    flight = clusterz.get("flight") or {}
    if flight.get("triggers"):
        lines.append(
            "flight rec    "
            + "  ".join(
                f"{trigger} {count}"
                for trigger, count in sorted(flight["triggers"].items())
            )
            + f"  ({len(flight.get('dumps') or [])} bundle(s))"
        )

    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Live terminal dashboard over the telemetry endpoints"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--port", type=int, required=True,
        help="The master's --telemetryPort (or TRC_OBS_PORT)",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--once", action="store_true",
        help="Print one frame and exit (scripts, smoke tests)",
    )
    args = parser.parse_args(argv)
    sched_bench = load_sched_bench()  # static artifact: load once, not per frame
    while True:
        try:
            samples, clusterz = fetch_endpoints(args.host, args.port)
            try:
                history = fetch_history(args.host, args.port)
            except (OSError, urllib.error.URLError, ValueError):
                history = {}  # sparklines degrade; the snapshot view stays
        except (OSError, urllib.error.URLError, ValueError) as e:
            frame = f"telemetry endpoint unreachable: {e}\n"
        else:
            frame = render_dashboard(
                samples, clusterz, history=history, sched_bench=sched_bench
            )
        if args.once:
            sys.stdout.write(frame)
            return 0
        sys.stdout.write(_CLEAR + frame)
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
