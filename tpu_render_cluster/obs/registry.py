"""Dependency-free in-process metrics registry.

The live counterpart of the frozen ``traces/`` dataclasses: counters,
gauges, and fixed-log-bucket histograms, all label-aware and thread-safe,
queryable at any point while a job runs. The paper's whole contribution is
*measured* cluster behavior; this registry is the substrate every layer
(master, worker, transport, render) reports into, replacing the ad-hoc
module-global counters that used to be sprinkled through the scheduler.

Design constraints:

- zero dependencies (stdlib only) so the worker daemon, the render CLI,
  and bench.py can all share it;
- one lock per registry (metric mutation is a dict update + float add —
  far below contention at cluster event rates, and a single lock keeps
  ``snapshot()`` consistent);
- histograms use FIXED log-scale bucket bounds shared by every process,
  so per-worker histograms shipped over the heartbeat wire
  (``to_wire``/``merge_wire``) merge bucket-by-bucket without resampling.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Iterable, Mapping

__all__ = [
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "DEFAULT_BUCKETS",
    "log_buckets",
    "merge_wire",
]


def log_buckets(
    start: float = 1e-4, stop: float = 1e3, per_decade: int = 3
) -> tuple[float, ...]:
    """Fixed log-scale bucket upper bounds from ``start`` to ``stop``.

    ``per_decade`` bounds per factor of 10, inclusive of both endpoints.
    The final +inf bucket is implicit (every histogram stores one extra
    overflow count).
    """
    lo = math.log10(start)
    hi = math.log10(stop)
    steps = round((hi - lo) * per_decade)
    return tuple(10.0 ** (lo + i / per_decade) for i in range(steps + 1))


# 100 µs .. 1000 s at 3 buckets/decade: covers WS round-trips, frame
# phases, and whole-job durations with one shared shape (22 bounds).
DEFAULT_BUCKETS = log_buckets(1e-4, 1e3, 3)


def _label_key(
    label_names: tuple[str, ...], labels: Mapping[str, Any]
) -> tuple[str, ...]:
    if set(labels) != set(label_names):
        raise ValueError(
            f"Expected labels {label_names}, got {tuple(sorted(labels))}"
        )
    return tuple(str(labels[name]) for name in label_names)


class _Metric:
    """Base: one named metric with zero or more label dimensions."""

    kind = "metric"

    def __init__(self, name: str, help: str, label_names: tuple[str, ...], lock):
        self.name = name
        self.help = help
        self.label_names = label_names
        self._lock = lock
        self._series: dict[tuple[str, ...], Any] = {}

    def _series_items(self) -> list[tuple[tuple[str, ...], Any]]:
        with self._lock:
            return list(self._series.items())


class Counter(_Metric):
    """Monotonically increasing float."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError("Counters only go up.")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0.0)


class Gauge(_Metric):
    """Point-in-time float; set/add from any thread."""

    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = float(value)

    def add(self, amount: float, **labels: Any) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0.0)


class _HistogramSeries:
    __slots__ = ("counts", "overflow", "count", "sum", "min", "max")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * n_buckets
        self.overflow = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf


class Histogram(_Metric):
    """Fixed-bound histogram (log-scale by default) with sum/count/min/max."""

    kind = "histogram"

    def __init__(self, name, help, label_names, lock, buckets: tuple[float, ...]):
        super().__init__(name, help, label_names, lock)
        if list(buckets) != sorted(buckets):
            raise ValueError("Histogram bounds must be sorted ascending.")
        self.buckets = tuple(float(b) for b in buckets)

    def _series_items(self) -> list[tuple[tuple[str, ...], Any]]:
        # Histogram series are mutable; exports must copy their fields
        # under the lock or a concurrent observe() between counts[i] += 1
        # and count += 1 yields a snapshot where sum(buckets) != count.
        with self._lock:
            out = []
            for key, series in self._series.items():
                copy = _HistogramSeries(len(self.buckets))
                copy.counts = list(series.counts)
                copy.overflow = series.overflow
                copy.count = series.count
                copy.sum = series.sum
                copy.min = series.min
                copy.max = series.max
                out.append((key, copy))
            return out

    def observe(self, value: float, **labels: Any) -> None:
        value = float(value)
        key = _label_key(self.label_names, labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistogramSeries(len(self.buckets))
            # First bound with value <= bound (linear scan: 22 bounds, and
            # observation rates are per-frame / per-message, not per-ray).
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    series.counts[i] += 1
                    break
            else:
                series.overflow += 1
            series.count += 1
            series.sum += value
            series.min = min(series.min, value)
            series.max = max(series.max, value)

    def series(self, **labels: Any) -> _HistogramSeries | None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key)


class MetricsRegistry:
    """A named set of metrics; get-or-create accessors are idempotent."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    # -- get-or-create -------------------------------------------------------

    def _get_or_create(self, cls, name: str, help: str, labels, **kwargs):
        label_names = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.label_names != label_names:
                    raise ValueError(
                        f"Metric {name!r} already registered as "
                        f"{existing.kind}{existing.label_names}"
                    )
                # Bucket shape is part of a histogram's identity: silently
                # returning one with different bounds would file the second
                # caller's observations into buckets it never asked for.
                buckets = kwargs.get("buckets")
                if buckets is not None and existing.buckets != tuple(
                    float(b) for b in buckets
                ):
                    raise ValueError(
                        f"Histogram {name!r} already registered with bounds "
                        f"{existing.buckets}"
                    )
                return existing
            metric = cls(name, help, label_names, self._lock, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labels: Iterable[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Iterable[str] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels, buckets=buckets)

    # -- export --------------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Full JSON-able view: one entry per metric, series keyed by labels."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict[str, Any] = {}
        for metric in metrics:
            series_out = {}
            for key, value in metric._series_items():
                label_str = ",".join(
                    f"{n}={v}" for n, v in zip(metric.label_names, key)
                )
                if isinstance(value, _HistogramSeries):
                    series_out[label_str] = {
                        "count": value.count,
                        "sum": value.sum,
                        "min": value.min if value.count else None,
                        "max": value.max if value.count else None,
                        "bucket_counts": list(value.counts) + [value.overflow],
                    }
                else:
                    series_out[label_str] = value
            entry: dict[str, Any] = {
                "type": metric.kind,
                "help": metric.help,
                "labels": list(metric.label_names),
                "series": series_out,
            }
            if isinstance(metric, Histogram):
                entry["bucket_bounds"] = list(metric.buckets)
            out[metric.name] = entry
        return out

    # -- compact wire form (heartbeat payload) -------------------------------

    def to_wire(self) -> dict[str, Any]:
        """Compact form for the heartbeat's optional metrics payload.

        ``{"c": {...}, "g": {...}, "h": {...}}`` keyed by
        ``name|label=value,...``; histogram entries carry their bounds so
        the master can verify shape compatibility before merging.
        """
        with self._lock:
            metrics = list(self._metrics.values())
        counters: dict[str, float] = {}
        gauges: dict[str, float] = {}
        histograms: dict[str, dict[str, Any]] = {}
        for metric in metrics:
            for key, value in metric._series_items():
                label_str = ",".join(
                    f"{n}={v}" for n, v in zip(metric.label_names, key)
                )
                wire_key = f"{metric.name}|{label_str}" if label_str else metric.name
                if metric.kind == "counter":
                    counters[wire_key] = value
                elif metric.kind == "gauge":
                    gauges[wire_key] = value
                else:
                    histograms[wire_key] = {
                        "n": value.count,
                        "s": value.sum,
                        "min": value.min if value.count else None,
                        "max": value.max if value.count else None,
                        "le": list(metric.buckets),
                        "b": list(value.counts) + [value.overflow],
                    }
        return {"c": counters, "g": gauges, "h": histograms}


def _check_wire_histogram(key: str, entry: Mapping[str, Any]) -> None:
    """Reject malformed histogram wire entries BEFORE they fold in.

    The bucket-count vector must carry exactly one count per bound plus
    the +inf overflow; a shorter/longer vector zipped element-wise would
    silently drop or misfile counts, which is worse than failing loud.
    """
    bounds = entry["le"]
    counts = entry["b"]
    if len(counts) != len(bounds) + 1:
        raise ValueError(
            f"Histogram {key!r}: bucket count vector has {len(counts)} "
            f"entries for {len(bounds)} bounds (expected {len(bounds) + 1} "
            f"including the +inf overflow bucket)"
        )


def merge_wire(payloads: Iterable[Mapping[str, Any]]) -> dict[str, Any]:
    """Aggregate compact wire payloads into one cluster-wide view.

    Counters, gauges, and histogram counts/sums are summed per series key;
    histogram min/max combine; bucket vectors add element-wise (all
    processes share DEFAULT_BUCKETS — mismatched or malformed bucket
    layouts raise instead of silently misfolding counts).
    """
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    histograms: dict[str, dict[str, Any]] = {}
    for payload in payloads:
        for key, value in (payload.get("c") or {}).items():
            counters[key] = counters.get(key, 0.0) + float(value)
        for key, value in (payload.get("g") or {}).items():
            gauges[key] = gauges.get(key, 0.0) + float(value)
        for key, entry in (payload.get("h") or {}).items():
            _check_wire_histogram(key, entry)
            merged = histograms.get(key)
            if merged is None:
                histograms[key] = {
                    "n": int(entry["n"]),
                    "s": float(entry["s"]),
                    "min": entry.get("min"),
                    "max": entry.get("max"),
                    "le": list(entry["le"]),
                    "b": list(entry["b"]),
                }
                continue
            if merged["le"] != list(entry["le"]):
                raise ValueError(
                    f"Histogram bounds mismatch for {key!r}: a previous "
                    f"payload declared {len(merged['le'])} bounds "
                    f"{merged['le'][:3]}..., this one declares "
                    f"{len(list(entry['le']))} bounds "
                    f"{list(entry['le'])[:3]}... — refusing to misfold "
                    f"counts across layouts"
                )
            merged["n"] += int(entry["n"])
            merged["s"] += float(entry["s"])
            merged["b"] = [a + b for a, b in zip(merged["b"], entry["b"])]
            for field, pick in (("min", min), ("max", max)):
                ours, theirs = merged.get(field), entry.get(field)
                if theirs is not None:
                    merged[field] = pick(ours, theirs) if ours is not None else theirs
    return {"c": counters, "g": gauges, "h": histograms}
