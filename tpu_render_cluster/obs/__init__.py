"""Unified cluster observability: live metrics + span tracing.

- ``registry`` — thread-safe counters / gauges / log-bucket histograms
  with labels; compact wire form for the heartbeat metrics payload.
- ``tracer`` — spans (wall-clock anchor + monotonic duration) exported as
  Chrome trace-event JSON, loadable in Perfetto / chrome://tracing.
- ``snapshot`` — periodic atomic JSON snapshots for live inspection.
- ``clocksync`` — NTP-style per-worker clock-offset estimation from the
  heartbeat's four timestamps (median-of-window + drift tracking).
- ``timeline`` — merged cluster timeline: per-process events rebased onto
  the master clock by the estimated offsets, pids deduplicated.
- ``validate`` — trace-invariant checker backing scripts/validate_trace.py.

``get_registry()`` / ``get_tracer()`` return the process-global instances
used by process-scoped subsystems (the render path, ``ops/assignment``,
bench.py). Cluster components that can be colocated in one process (the
harness runs a master and N workers on one loop) create their OWN
instances so per-component views stay separable.
"""

from __future__ import annotations

from tpu_render_cluster.obs.clocksync import ClockOffsetEstimator
from tpu_render_cluster.obs.flightrec import (
    FlightRecorder,
    resolve_flight_directory,
)
from tpu_render_cluster.obs.history import HistorySampler, HistoryStore
from tpu_render_cluster.obs.loopmon import LoopLagMonitor
from tpu_render_cluster.obs.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_buckets,
    merge_wire,
)
from tpu_render_cluster.obs.snapshot import SnapshotWriter, write_metrics_snapshot
from tpu_render_cluster.obs.timeline import (
    TimelineProcess,
    export_cluster_trace,
    merge_timeline,
    tracer_process,
)
from tpu_render_cluster.obs.tracer import Tracer, export_chrome_trace
from tpu_render_cluster.obs.validate import (
    validate_trace_document,
    validate_trace_file,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "ClockOffsetEstimator",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "HistorySampler",
    "HistoryStore",
    "LoopLagMonitor",
    "MetricsRegistry",
    "SnapshotWriter",
    "TimelineProcess",
    "Tracer",
    "export_chrome_trace",
    "export_cluster_trace",
    "get_registry",
    "get_tracer",
    "log_buckets",
    "merge_timeline",
    "merge_wire",
    "render_fps_gauge",
    "resolve_flight_directory",
    "tracer_process",
    "validate_trace_document",
    "validate_trace_file",
    "write_metrics_snapshot",
]

_global_registry = MetricsRegistry()
_global_tracer = Tracer("process", pid=0)


def get_registry() -> MetricsRegistry:
    """The process-global registry (render path, ops, bench)."""
    return _global_registry


def get_tracer() -> Tracer:
    """The process-global tracer."""
    return _global_tracer


def render_fps_gauge(registry: MetricsRegistry | None = None) -> Gauge:
    """The frames/s gauge both bench.py and the TPU backend feed.

    One definition site so the two writers can't drift apart in name,
    help, or label shape (get-or-create raises on mismatch at runtime).
    """
    registry = registry if registry is not None else get_registry()
    return registry.gauge(
        "render_frames_per_second",
        "Instantaneous device throughput (1 / execute_seconds)",
    )
