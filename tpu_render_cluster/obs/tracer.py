"""Span tracer with Chrome trace-event export.

Spans carry BOTH clocks: wall-clock (``time.time``) anchors the span on the
trace timeline (and lets traces from different processes line up), and the
monotonic clock (``time.perf_counter``) measures the duration, immune to
NTP steps. Export is the Chrome trace-event JSON object format —
``{"traceEvents": [...]}`` with ``ph: "X"`` complete events — which loads
directly in Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Each ``Tracer`` is one *process row* in the viewer (``pid``); tracks within
it (``tid``) are named virtual threads, so asyncio tasks that interleave on
one OS thread still render as separate, properly-nested lanes. The in-
process harness merges the master tracer and every worker tracer into one
file via ``export_chrome_trace`` — indistinguishable from a multi-host
collection.
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Iterable, Mapping

__all__ = ["Tracer", "export_chrome_trace"]

logger = logging.getLogger(__name__)

_pid_counter = itertools.count(1)

# Bounded event buffers: a 14400-frame job emits ~5 events per frame; the
# cap keeps a runaway instrumentation site from eating the master's heap.
MAX_EVENTS = 200_000


class Tracer:
    """Thread-safe span collector for one logical process."""

    def __init__(
        self, process_name: str, *, pid: int | None = None, max_events: int = MAX_EVENTS
    ) -> None:
        self.process_name = process_name
        self.pid = next(_pid_counter) if pid is None else pid
        self._max_events = max_events
        self._lock = threading.Lock()
        self._events: list[dict[str, Any]] = []
        self._dropped = 0
        self._tracks: dict[str, int] = {}

    # -- recording -----------------------------------------------------------

    def _tid(self, track: str | None) -> int:
        if track is None:
            return threading.get_ident() & 0x7FFFFFFF
        with self._lock:
            tid = self._tracks.get(track)
            if tid is None:
                tid = len(self._tracks) + 1
                self._tracks[track] = tid
            return tid

    def _append(self, event: dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            self._events.append(event)

    def complete(
        self,
        name: str,
        *,
        cat: str = "",
        start_wall: float,
        duration: float,
        track: str | None = None,
        args: Mapping[str, Any] | None = None,
    ) -> None:
        """Record a finished span from explicit timestamps (seconds)."""
        event: dict[str, Any] = {
            "name": name,
            "cat": cat or "default",
            "ph": "X",
            "pid": self.pid,
            "tid": self._tid(track),
            "ts": round(start_wall * 1e6, 3),
            "dur": round(max(0.0, duration) * 1e6, 3),
        }
        if args:
            event["args"] = dict(args)
        self._append(event)

    def instant(
        self,
        name: str,
        *,
        cat: str = "",
        track: str | None = None,
        args: Mapping[str, Any] | None = None,
    ) -> None:
        event: dict[str, Any] = {
            "name": name,
            "cat": cat or "default",
            "ph": "i",
            "s": "t",
            "pid": self.pid,
            "tid": self._tid(track),
            "ts": round(time.time() * 1e6, 3),
        }
        if args:
            event["args"] = dict(args)
        self._append(event)

    # -- flow events ---------------------------------------------------------
    #
    # Perfetto flow events ("s" start / "t" step / "f" end, matched by id)
    # draw arrows between spans on different process rows — the causal link
    # from a master-side assignment to the worker-side frame phases. A flow
    # event binds to the slice that encloses its ``ts`` on its (pid, tid)
    # track, so emitters place the flow timestamp INSIDE the span it should
    # attach to (mid-span is the safe choice for zero-duration spans).

    def _flow(
        self,
        phase: str,
        name: str,
        *,
        id: str,
        ts: float,
        cat: str = "",
        track: str | None = None,
        args: Mapping[str, Any] | None = None,
    ) -> None:
        event: dict[str, Any] = {
            "name": name,
            "cat": cat or "flow",
            "ph": phase,
            "id": id,
            "pid": self.pid,
            "tid": self._tid(track),
            "ts": round(ts * 1e6, 3),
        }
        if phase == "f":
            event["bp"] = "e"  # bind the arrowhead to the enclosing slice
        if args:
            event["args"] = dict(args)
        self._append(event)

    def flow_start(self, name: str, *, id: str, ts: float, **kwargs: Any) -> None:
        """Open a flow arrow (source side) at wall time ``ts`` (seconds)."""
        self._flow("s", name, id=id, ts=ts, **kwargs)

    def flow_step(self, name: str, *, id: str, ts: float, **kwargs: Any) -> None:
        """Route an open flow through the span enclosing ``ts``."""
        self._flow("t", name, id=id, ts=ts, **kwargs)

    def flow_end(self, name: str, *, id: str, ts: float, **kwargs: Any) -> None:
        """Terminate a flow arrow (sink side) at wall time ``ts``."""
        self._flow("f", name, id=id, ts=ts, **kwargs)

    @contextmanager
    def span(
        self,
        name: str,
        *,
        cat: str = "",
        track: str | None = None,
        args: Mapping[str, Any] | None = None,
    ):
        """Context manager span: wall-clock anchor, monotonic duration."""
        start_wall = time.time()
        start_mono = time.perf_counter()
        try:
            yield
        finally:
            self.complete(
                name,
                cat=cat,
                start_wall=start_wall,
                duration=time.perf_counter() - start_mono,
                track=track,
                args=args,
            )

    # -- export --------------------------------------------------------------

    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        """Drop buffered events (and the dropped counter); track-name
        assignments persist so tids stay stable across exports. Exporters
        of long-lived shared tracers (the process-global one) call this
        after a write so the next artifact holds only its own run's
        spans."""
        with self._lock:
            self._events.clear()
            self._dropped = 0

    @property
    def dropped(self) -> int:
        return self._dropped

    def metadata_events(self) -> list[dict[str, Any]]:
        """process_name / thread_name metadata for the viewer's labels."""
        out = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": self.process_name},
            }
        ]
        with self._lock:
            tracks = dict(self._tracks)
        for track, tid in tracks.items():
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self.pid,
                    "tid": tid,
                    "args": {"name": track},
                }
            )
        return out

    def to_chrome(self) -> dict[str, Any]:
        # Truncation must not be silent: a capped buffer drops the TAIL of
        # the run, and a viewer (or the analysis roll-up) reading a clean-
        # looking file would conclude the instrumented window covered the
        # whole job. The count rides in the document (otherData survives
        # the object format) and is also logged at export time.
        out: dict[str, Any] = {
            "traceEvents": self.metadata_events() + self.events(),
            "displayTimeUnit": "ms",
        }
        if self._dropped:
            out["otherData"] = {
                "dropped_events": {self.process_name: self._dropped}
            }
        return out

    def export(self, path: str | Path) -> Path:
        if self._dropped:
            logger.warning(
                "Tracer %r dropped %d events past the %d-event cap; the "
                "exported timeline is truncated.",
                self.process_name, self._dropped, self._max_events,
            )
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.to_chrome()), encoding="utf-8")
        return path


def export_chrome_trace(path: str | Path, tracers: Iterable[Tracer]) -> Path:
    """Merge several tracers (master + workers) into one loadable file."""
    events: list[dict[str, Any]] = []
    dropped: dict[str, int] = {}
    for tracer in tracers:
        events.extend(tracer.metadata_events())
        events.extend(tracer.events())
        if tracer.dropped:
            dropped[tracer.process_name] = tracer.dropped
            logger.warning(
                "Tracer %r dropped %d events past its cap; the exported "
                "timeline is truncated.", tracer.process_name, tracer.dropped,
            )
    document: dict[str, Any] = {"traceEvents": events, "displayTimeUnit": "ms"}
    if dropped:
        document["otherData"] = {"dropped_events": dropped}
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(document), encoding="utf-8")
    return path
