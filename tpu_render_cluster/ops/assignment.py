"""Batched assignment solvers on TPU (the `tpu-batch` scheduler's core).

Solves min-cost frame->slot assignment with a synchronous (Jacobi) auction
algorithm (Bertsekas) expressed with ``lax`` control flow so the whole solve
stays on device. Shapes are padded to fixed buckets so XLA compiles once per
bucket, and ``vmap`` batches independent solves.

This replaces the reference's sequential greedy bin-packing loops
(reference: master/src/cluster/strategies.rs:16-405) with a globally
near-optimal assignment per scheduling tick; the control plane only ships
the resulting frame->worker pairs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_PAD_COST = 1e9
_NEG_INF = -1e30


def _next_bucket(n: int) -> int:
    size = 8
    while size < n:
        size *= 2
    return size


@functools.partial(jax.jit, static_argnames=("iterations_per_phase", "phases"))
def _auction_solve(
    cost: jnp.ndarray, iterations_per_phase: int = 1500, phases: int = 6
) -> jnp.ndarray:
    """Min-cost assignment on a square [n, n] matrix.

    Rows are items (frames), columns are slots (worker queue positions).
    Returns [n] int32: the slot assigned to each item (a permutation).
    Uses epsilon-scaling (each phase restarts the assignment with the
    previous phase's prices and a 5x smaller epsilon), giving a final
    suboptimality bound of ~n * eps_final = spread * n / (2 * 5^(phases-1)).
    """
    n = cost.shape[0]
    benefit = -cost.astype(jnp.float32)
    spread = jnp.maximum(jnp.max(benefit) - jnp.min(benefit), 1.0)
    slots = jnp.arange(n)
    items = jnp.arange(n)

    def body(eps, carry):
        assignment, owner, prices = carry
        unassigned = assignment < 0
        values = benefit - prices[None, :]  # [items, slots]
        best_slot = jnp.argmax(values, axis=1)
        best_value = jnp.max(values, axis=1)
        masked = values.at[items, best_slot].set(_NEG_INF)
        second_value = jnp.max(masked, axis=1)
        bid = best_value - second_value + eps

        # bids_matrix[i, s] = item i's bid on slot s (only its best slot).
        one_hot = best_slot[:, None] == slots[None, :]
        bids_matrix = jnp.where(
            unassigned[:, None] & one_hot, bid[:, None], _NEG_INF
        )
        winning_bid = jnp.max(bids_matrix, axis=0)  # per slot
        winning_item = jnp.argmax(bids_matrix, axis=0)
        has_bid = winning_bid > _NEG_INF / 2

        # Evict previous owners of re-auctioned slots.
        evicted = jnp.any(
            has_bid[None, :] & (owner[None, :] == items[:, None]), axis=1
        )
        assignment = jnp.where(evicted, -1, assignment)

        # Award: each item wins at most one slot (it bids on exactly one).
        won_mask = has_bid[None, :] & (winning_item[None, :] == items[:, None])
        has_won = jnp.any(won_mask, axis=1)
        won_slot = jnp.argmax(won_mask, axis=1)
        assignment = jnp.where(has_won, won_slot, assignment)

        owner = jnp.where(has_bid, winning_item, owner)
        prices = jnp.where(has_bid, prices + winning_bid, prices)
        return assignment, owner, prices

    def run_phase(phase, carry):
        _, _, prices = carry
        eps = (spread / 2.0) / (5.0**phase)
        # Restart the assignment, keep the learned prices.
        assignment = jnp.full((n,), -1, dtype=jnp.int32)
        owner = jnp.full((n,), -1, dtype=jnp.int32)

        # while_loop (not a fixed-trip fori): the auction typically
        # converges in a few dozen rounds, and the scheduler calls this
        # every 50 ms tick — paying the full iteration cap per phase would
        # dominate the tick budget on the CPU backend.
        def not_done(loop_carry):
            iteration, (inner_assignment, _, _) = loop_carry
            return jnp.logical_and(
                iteration < iterations_per_phase, jnp.any(inner_assignment < 0)
            )

        def step(loop_carry):
            iteration, inner = loop_carry
            return iteration + 1, body(eps, inner)

        _, result = jax.lax.while_loop(
            not_done, step, (0, (assignment, owner, prices))
        )
        return result

    prices0 = jnp.zeros((n,), dtype=jnp.float32)
    assignment0 = jnp.full((n,), -1, dtype=jnp.int32)
    owner0 = jnp.full((n,), -1, dtype=jnp.int32)
    assignment, _, _ = jax.lax.fori_loop(
        0, phases, run_phase, (assignment0, owner0, prices0)
    )
    return assignment


# Observability: how often the auction failed to converge and the greedy
# host fallback decided a tick's assignment. A pathological cost matrix
# could otherwise quietly turn the "TPU scheduler" into "host greedy" for
# a whole job with no trace of it in the results (VERDICT round-4 weak #5)
# — the masters reset this per job and surface it in the
# *_processed-results.json "scheduler" section.
_greedy_fallback_count = 0


def greedy_fallback_count() -> int:
    return _greedy_fallback_count


def reset_greedy_fallback_count() -> None:
    global _greedy_fallback_count
    _greedy_fallback_count = 0


def solve_assignment(cost_matrix: np.ndarray) -> np.ndarray:
    """Solve min-cost assignment for an [n_items, n_slots] cost matrix.

    Pads to a square power-of-two bucket (so jit caches per bucket size) and
    returns the slot index for each real item. Requires n_items <= n_slots.
    Phantom rows/columns carry zero cost against each other and a huge cost
    against real entries, so they pair off among themselves.
    """
    n_items, n_slots = cost_matrix.shape
    if n_items == 0:
        return np.zeros((0,), dtype=np.int32)
    from tpu_render_cluster.obs import get_registry

    get_registry().counter(
        "scheduler_auction_solves_total", "Assignment solves attempted"
    ).inc()
    if n_items > n_slots:
        raise ValueError(f"More items ({n_items}) than slots ({n_slots}).")
    size = _next_bucket(max(n_items, n_slots))
    # Pad relative to the real cost scale: a huge constant would dominate the
    # benefit spread and destroy the auction's epsilon precision.
    pad = float(np.max(cost_matrix)) + 1.0
    padded = np.full((size, size), pad, dtype=np.float32)
    padded[:n_items, :n_slots] = cost_matrix
    padded[n_items:, n_slots:] = 0.0  # phantoms pair with phantom slots
    assignment = np.asarray(_auction_solve(jnp.asarray(padded)))[:n_items]

    if (assignment < 0).any() or len(set(assignment.tolist())) != n_items:
        # Auction did not converge within the iteration cap (rare, tiny
        # matrices aside) — finish greedily on host.
        global _greedy_fallback_count
        _greedy_fallback_count += 1
        get_registry().counter(
            "scheduler_greedy_fallbacks_total",
            "Ticks whose auction failed to converge and fell back to the "
            "host greedy solve",
        ).inc()
        assignment = _greedy_fallback(cost_matrix)
    return assignment.astype(np.int32)


def _greedy_fallback(cost_matrix: np.ndarray) -> np.ndarray:
    n_items, n_slots = cost_matrix.shape
    order = np.argsort(cost_matrix.min(axis=1))
    taken = np.zeros(n_slots, dtype=bool)
    out = np.full(n_items, -1, dtype=np.int32)
    for item in order:
        row = np.where(taken, np.inf, cost_matrix[item])
        slot = int(np.argmin(row))
        out[item] = slot
        taken[slot] = True
    return out


_warmed_max_slots = 0


def warmup(max_slots: int) -> None:
    """Pre-compile the auction for every bucket size up to ``max_slots``.

    The jit cache is keyed on the padded (square, power-of-two) shape; the
    master calls this while waiting for workers at the barrier so the first
    scheduling tick doesn't pay XLA compilation inside the timed job.
    """
    global _warmed_max_slots
    size = 8
    target = _next_bucket(max(1, max_slots))
    while size <= target:
        _auction_solve(jnp.zeros((size, size), dtype=jnp.float32)).block_until_ready()
        _warmed_max_slots = max(_warmed_max_slots, size)
        size *= 2


def warmed_max_slots() -> int:
    """Largest pre-compiled bucket size (0 when warmup never ran)."""
    return _warmed_max_slots


# Batched solve over a leading batch axis of square cost matrices.
solve_assignment_batched = jax.jit(jax.vmap(_auction_solve))
