"""JAX/Pallas compute kernels: assignment solvers and render ops."""
