"""Fault-injection wrapper for a single WebSocket connection.

``FaultyConnection`` conforms to the ``WebSocketConnection`` surface the
rest of the transport consumes (``send_text`` / ``receive_text`` /
``close`` / ``abort`` / ``is_closed`` / ``peer_address``), so
``ReconnectingClient`` and ``ReconnectableServerConnection`` are exercised
by chaos runs completely unmodified — faults look exactly like the real
network events they model. The wrapper itself holds no policy: every
decision is delegated to a ``FaultController`` (the seeded, plan-driven
implementation lives in ``chaos/inject.py``), and with no controller
actions the wrapper is a transparent pass-through.

Fault vocabulary at this seam:

- ``drop``       — the send appears to succeed but nothing hits the wire
                   (a message lost in flight);
- ``delay``      — the send completes only after a pause (a wedged socket;
                   because senders are serial actors, one delayed send
                   wedges everything queued behind it — by design);
- ``duplicate``  — the payload is written twice (a retransmit race);
- ``kill``       — the socket dies *before* the payload is written
                   (connection reset mid-send);
- the controller's ``gate`` hook can also refuse service on entry to
  either direction, which models partitions and permanent death.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Protocol

from tpu_render_cluster.transport.ws import WebSocketClosed, WebSocketConnection

SEND_ACTION_SEND = "send"
SEND_ACTION_DROP = "drop"
SEND_ACTION_DUPLICATE = "duplicate"
SEND_ACTION_KILL = "kill"


@dataclass(frozen=True)
class SendDecision:
    """What to do with one outgoing message."""

    action: str = SEND_ACTION_SEND
    delay_seconds: float = 0.0


# Shared pass-through instance (the overwhelmingly common decision).
PASS_DECISION = SendDecision()


class FaultController(Protocol):
    """Policy source for one connection's faults (see chaos/inject.py)."""

    def check_gate(self) -> None:
        """Raise ``WebSocketClosed`` if the link should refuse service now
        (partition window open, worker killed). Called on entry to both
        ``send_text`` and ``receive_text``."""

    def on_send(self, text: str) -> SendDecision:
        """Decide the fate of one outgoing message."""

    def after_send(self, text: str) -> None:
        """Called after a successful write — the crash-after-result seam."""


class FaultyConnection:
    """A ``WebSocketConnection`` with a fault controller in the send path."""

    def __init__(self, inner: WebSocketConnection, controller: FaultController) -> None:
        self._inner = inner
        self._controller = controller

    @property
    def is_closed(self) -> bool:
        return self._inner.is_closed

    def peer_address(self) -> str:
        return self._inner.peer_address()

    async def send_text(self, text: str) -> None:
        self._controller.check_gate()
        decision = self._controller.on_send(text)
        if decision.delay_seconds > 0.0:
            await asyncio.sleep(decision.delay_seconds)
            # The link may have died (or a partition opened) during the
            # stall — a real wedged socket discovers this on write too.
            self._controller.check_gate()
        if decision.action == SEND_ACTION_KILL:
            self._inner.abort()
            raise WebSocketClosed("chaos: socket killed before send")
        if decision.action == SEND_ACTION_DROP:
            return  # swallowed in flight; the caller believes it was sent
        await self._inner.send_text(text)
        if decision.action == SEND_ACTION_DUPLICATE:
            await self._inner.send_text(text)
        self._controller.after_send(text)

    async def receive_text(self) -> str:
        self._controller.check_gate()
        return await self._inner.receive_text()

    async def close(self) -> None:
        await self._inner.close()

    def abort(self) -> None:
        self._inner.abort()
