"""Minimal RFC 6455 WebSocket implementation over asyncio streams.

The reference rides tokio-tungstenite with 256 MB max message / 16 MB max
frame limits (reference: shared/src/websockets.rs:3-9); we keep the same
limits. Only what the job protocol needs is implemented: text messages,
ping/pong, close, and fragmentation on receive. Client-to-server frames are
masked per the RFC; masking uses a numpy XOR for large payloads (traces can
be tens of MB). A C++ codec (tpu_render_cluster/native) accelerates the
framing hot path when built; this pure-Python path is the always-available
fallback.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import os
import secrets
import struct
import threading

import numpy as np

MAX_MESSAGE_SIZE = 256 * 1024 * 1024  # reference: shared/src/websockets.rs:5
MAX_FRAME_SIZE = 16 * 1024 * 1024  # reference: shared/src/websockets.rs:7

_WS_MAGIC_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


class WebSocketError(Exception):
    """Protocol violation or I/O failure."""


class WebSocketClosed(WebSocketError):
    """The peer closed the connection (or the socket died)."""


_native_codec = None
_native_load_started = False
_native_load_lock = threading.Lock()


def _load_native_codec_blocking() -> None:
    """Build + load the C++ codec; runs on the loader thread only."""
    global _native_codec
    try:
        from tpu_render_cluster.native import load_codec

        _native_codec = load_codec()
    except Exception:  # noqa: BLE001 - any failure means Python fallback
        _native_codec = None


def _get_native_codec():
    """The C++ codec (tpu_render_cluster/native) once loaded; None until
    then (and forever when the toolchain is absent).

    The first call is made from inside a coroutine masking its first
    large frame, and ``load_codec`` may COMPILE the codec (``g++``, a
    multi-second ``subprocess.run``) — so the load runs on a background
    thread and callers use the numpy fallback until it lands, instead of
    parking the event loop behind a compiler on the first send.
    """
    global _native_load_started
    if _native_codec is None and not _native_load_started:
        with _native_load_lock:
            if not _native_load_started:
                _native_load_started = True
                threading.Thread(
                    target=_load_native_codec_blocking,
                    name="wscodec-load",
                    daemon=True,
                ).start()
    return _native_codec


def _mask_payload(payload: bytes, mask: bytes) -> bytes:
    if len(payload) >= 512:
        native = _get_native_codec()
        if native is not None:
            return native.mask_payload(payload, mask)
        data = np.frombuffer(payload, dtype=np.uint8)
        key = np.frombuffer(
            (mask * ((len(payload) + 3) // 4))[: len(payload)], dtype=np.uint8
        )
        return (data ^ key).tobytes()
    return bytes(b ^ mask[i & 3] for i, b in enumerate(payload))


def encode_frame(opcode: int, payload: bytes, *, masked: bool, fin: bool = True) -> bytes:
    """Encode one WebSocket frame."""
    header = bytearray()
    header.append((0x80 if fin else 0x00) | opcode)
    mask_bit = 0x80 if masked else 0x00
    length = len(payload)
    if length < 126:
        header.append(mask_bit | length)
    elif length < 1 << 16:
        header.append(mask_bit | 126)
        header += struct.pack(">H", length)
    else:
        header.append(mask_bit | 127)
        header += struct.pack(">Q", length)
    if masked:
        mask = secrets.token_bytes(4)
        header += mask
        return bytes(header) + _mask_payload(payload, mask)
    return bytes(header) + payload


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    try:
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
        raise WebSocketClosed(f"Socket closed while reading: {e}") from e


async def read_frame(reader: asyncio.StreamReader) -> tuple[int, bool, bytes]:
    """Read one frame; returns (opcode, fin, payload) with unmasking applied."""
    head = await _read_exact(reader, 2)
    fin = bool(head[0] & 0x80)
    opcode = head[0] & 0x0F
    masked = bool(head[1] & 0x80)
    length = head[1] & 0x7F
    if length == 126:
        length = struct.unpack(">H", await _read_exact(reader, 2))[0]
    elif length == 127:
        length = struct.unpack(">Q", await _read_exact(reader, 8))[0]
    if length > MAX_FRAME_SIZE:
        raise WebSocketError(f"Frame of {length} bytes exceeds the {MAX_FRAME_SIZE} limit.")
    mask = await _read_exact(reader, 4) if masked else None
    payload = await _read_exact(reader, length) if length else b""
    if mask:
        payload = _mask_payload(payload, mask)
    return opcode, fin, payload


class WebSocketConnection:
    """A single established WebSocket; handles control frames transparently."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        *,
        is_client: bool,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._is_client = is_client
        self._send_lock = asyncio.Lock()
        self._closed = False

    @property
    def is_closed(self) -> bool:
        return self._closed

    def peer_address(self) -> str:
        peer = self._writer.get_extra_info("peername")
        if peer is None:
            return "unknown"
        return f"{peer[0]}:{peer[1]}"

    async def _send_frame(self, opcode: int, payload: bytes) -> None:
        if self._closed:
            raise WebSocketClosed("Connection is closed.")
        frame = encode_frame(opcode, payload, masked=self._is_client)
        async with self._send_lock:
            try:
                self._writer.write(frame)
                await self._writer.drain()
            except (ConnectionError, OSError) as e:
                self._closed = True
                raise WebSocketClosed(f"Socket died on send: {e}") from e

    async def send_text(self, text: str) -> None:
        data = text.encode("utf-8")
        if len(data) > MAX_MESSAGE_SIZE:
            raise WebSocketError(
                f"Message of {len(data)} bytes exceeds the {MAX_MESSAGE_SIZE} limit."
            )
        # Fragment oversized messages under the frame limit.
        if len(data) <= MAX_FRAME_SIZE:
            await self._send_frame(OP_TEXT, data)
            return
        if self._closed:
            raise WebSocketClosed("Connection is closed.")
        async with self._send_lock:
            try:
                for start in range(0, len(data), MAX_FRAME_SIZE):
                    chunk = data[start : start + MAX_FRAME_SIZE]
                    opcode = OP_TEXT if start == 0 else OP_CONT
                    fin = start + MAX_FRAME_SIZE >= len(data)
                    self._writer.write(
                        encode_frame(opcode, chunk, masked=self._is_client, fin=fin)
                    )
                await self._writer.drain()
            except (ConnectionError, OSError) as e:
                self._closed = True
                raise WebSocketClosed(f"Socket died on send: {e}") from e

    async def receive_text(self) -> str:
        """Receive the next complete text message, answering pings en route."""
        buffer = bytearray()
        expecting_continuation = False
        while True:
            opcode, fin, payload = await read_frame(self._reader)
            if opcode == OP_PING:
                await self._send_frame(OP_PONG, payload)
                continue
            if opcode == OP_PONG:
                continue
            if opcode == OP_CLOSE:
                self._closed = True
                try:
                    await self._send_frame(OP_CLOSE, b"")
                except WebSocketError:
                    pass
                raise WebSocketClosed("Peer sent close frame.")
            if opcode == OP_TEXT or opcode == OP_BINARY:
                if expecting_continuation:
                    raise WebSocketError("New data frame while awaiting continuation.")
                buffer += payload
                expecting_continuation = not fin
            elif opcode == OP_CONT:
                if not expecting_continuation:
                    raise WebSocketError("Unexpected continuation frame.")
                buffer += payload
                expecting_continuation = not fin
            else:
                raise WebSocketError(f"Unsupported opcode: {opcode:#x}")
            if len(buffer) > MAX_MESSAGE_SIZE:
                raise WebSocketError("Incoming message exceeds the size limit.")
            if not expecting_continuation:
                return bytes(buffer).decode("utf-8")

    async def close(self) -> None:
        if not self._closed:
            try:
                await self._send_frame(OP_CLOSE, struct.pack(">H", 1000))
            except WebSocketError:
                pass
            self._closed = True
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except (ConnectionError, OSError):
            pass

    def abort(self) -> None:
        """Tear down the socket without a close handshake (used on swap)."""
        self._closed = True
        try:
            self._writer.close()
        except (ConnectionError, OSError):
            pass


def _compute_accept(key: str) -> str:
    digest = hashlib.sha1((key + _WS_MAGIC_GUID).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


async def _read_http_headers(reader: asyncio.StreamReader) -> tuple[str, dict[str, str]]:
    raw = await reader.readuntil(b"\r\n\r\n")
    if len(raw) > 64 * 1024:
        raise WebSocketError("HTTP header block too large.")
    lines = raw.decode("latin-1").split("\r\n")
    start_line = lines[0]
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    return start_line, headers


async def websocket_accept(
    reader: asyncio.StreamReader, writer: asyncio.StreamWriter
) -> WebSocketConnection:
    """Server side: perform the HTTP upgrade on a fresh TCP connection."""
    try:
        start_line, headers = await _read_http_headers(reader)
    except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
        raise WebSocketClosed(f"Connection died during upgrade: {e}") from e
    if not start_line.startswith("GET "):
        raise WebSocketError(f"Expected GET upgrade request, got: {start_line!r}")
    if headers.get("upgrade", "").lower() != "websocket":
        raise WebSocketError("Missing 'Upgrade: websocket' header.")
    key = headers.get("sec-websocket-key")
    if not key:
        raise WebSocketError("Missing Sec-WebSocket-Key header.")
    response = (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {_compute_accept(key)}\r\n"
        "\r\n"
    )
    writer.write(response.encode("ascii"))
    await writer.drain()
    return WebSocketConnection(reader, writer, is_client=False)


async def websocket_connect(
    host: str, port: int, *, path: str = "/", connect_timeout: float = 10.0
) -> WebSocketConnection:
    """Client side: open TCP, perform the HTTP upgrade, validate the accept key."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), connect_timeout
        )
    except (ConnectionError, OSError, asyncio.TimeoutError) as e:
        raise WebSocketClosed(f"TCP connect to {host}:{port} failed: {e}") from e
    key = base64.b64encode(os.urandom(16)).decode("ascii")
    request = (
        f"GET {path} HTTP/1.1\r\n"
        f"Host: {host}:{port}\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\n"
        "Sec-WebSocket-Version: 13\r\n"
        "\r\n"
    )
    try:
        writer.write(request.encode("ascii"))
        await writer.drain()
        start_line, headers = await _read_http_headers(reader)
    except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
        writer.close()
        raise WebSocketClosed(f"Connection died during upgrade: {e}") from e
    if "101" not in start_line:
        writer.close()
        raise WebSocketError(f"Upgrade rejected: {start_line!r}")
    if headers.get("sec-websocket-accept") != _compute_accept(key):
        writer.close()
        raise WebSocketError("Invalid Sec-WebSocket-Accept from server.")
    return WebSocketConnection(reader, writer, is_client=True)
