"""Connection actor runtime: sender queue, typed receiver fan-out, RPC.

The reference runs three cooperating tokio tasks per connection — a sender
draining an mpsc queue (with a oneshot fired when the message is actually
written), a receiver fanning each message variant into a per-type broadcast
channel, and a requester composing the two into RPC with request-id
correlation (reference: master/src/connection/{sender,receiver,requester}.rs,
worker/src/connection/{sender,receiver}.rs). This is the asyncio
re-expression of the same observable behavior: one sender task, one receiver
task, per-type subscriber queues, and ``wait_for_message(_with_predicate)``
typed awaits with a 60 s default timeout
(reference: master/src/connection/receiver.rs:27,299-367).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, TypeVar

from tpu_render_cluster.protocol.messages import Message

logger = logging.getLogger(__name__)

DEFAULT_WAIT_TIMEOUT = 60.0  # reference: master/src/connection/receiver.rs:27

M = TypeVar("M", bound=Message)


class SenderHandle:
    """Queue-backed message sender; ``send_message`` resolves when written.

    Reference semantics: shared/src/messages/mod.rs:41-75 (enqueue + await
    the "actually sent" oneshot).
    """

    def __init__(self, send_fn: Callable[[Message], Awaitable[None]]) -> None:
        self._send_fn = send_fn
        self._queue: asyncio.Queue[tuple[Message, asyncio.Future]] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._closed = False

    def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="sender")

    async def _run(self) -> None:
        while True:
            message, done = await self._queue.get()
            if message is None:  # shutdown sentinel
                if not done.done():
                    done.set_result(None)
                return
            try:
                await self._send_fn(message)
                if not done.done():
                    done.set_result(None)
            except Exception as e:  # propagate to the waiting caller
                if not done.done():
                    done.set_exception(e)

    async def send_message(self, message: Message) -> None:
        """Enqueue and wait until the message has actually been written."""
        if self._closed:
            raise ConnectionError("Sender is closed.")
        done: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((message, done))
        await done

    async def stop(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._task is not None:
            done: asyncio.Future = asyncio.get_running_loop().create_future()
            await self._queue.put((None, done))  # type: ignore[arg-type]
            try:
                await asyncio.wait_for(self._task, 5.0)
            except asyncio.TimeoutError:
                self._task.cancel()


class MessageRouter:
    """Receiver fan-out: parses incoming messages, dispatches by type.

    Each ``subscribe`` returns an independent queue (broadcast semantics,
    like the reference's per-type ``tokio::broadcast`` channels of capacity
    512 — master/src/connection/receiver.rs:30-47). Slow subscribers drop
    the oldest entries rather than erroring.
    """

    QUEUE_CAPACITY = 512

    def __init__(self, receive_fn: Callable[[], Awaitable[Message]]) -> None:
        self._receive_fn = receive_fn
        self._subscribers: dict[type[Message], list[asyncio.Queue[Message]]] = {}
        self._task: asyncio.Task | None = None
        self._dead: asyncio.Future | None = None

    def start(self) -> None:
        self._dead = asyncio.get_running_loop().create_future()
        self._task = asyncio.create_task(self._run(), name="receiver")

    @property
    def dead(self) -> asyncio.Future:
        """Resolves (with the exception) when the receive loop dies."""
        assert self._dead is not None
        return self._dead

    async def _run(self) -> None:
        try:
            while True:
                message = await self._receive_fn()
                self._dispatch(message)
        except asyncio.CancelledError:
            if self._dead and not self._dead.done():
                self._dead.set_result(None)
            raise
        except Exception as e:
            logger.debug("Receiver loop terminated: %s", e)
            if self._dead and not self._dead.done():
                self._dead.set_result(e)

    def _dispatch(self, message: Message) -> None:
        queues = self._subscribers.get(type(message))
        if not queues:
            logger.warning("No subscriber for %s; dropping.", type(message).__name__)
            return
        for queue in queues:
            if queue.full():
                try:
                    queue.get_nowait()  # drop-oldest
                except asyncio.QueueEmpty:
                    pass
            queue.put_nowait(message)

    def subscribe(self, message_type: type[M]) -> asyncio.Queue:
        queue: asyncio.Queue = asyncio.Queue(self.QUEUE_CAPACITY)
        self._subscribers.setdefault(message_type, []).append(queue)
        return queue

    def unsubscribe(self, message_type: type[M], queue: asyncio.Queue) -> None:
        queues = self._subscribers.get(message_type)
        if queues and queue in queues:
            queues.remove(queue)

    async def wait_for_message(
        self,
        message_type: type[M],
        *,
        predicate: Callable[[M], bool] | None = None,
        timeout: float = DEFAULT_WAIT_TIMEOUT,
        queue: asyncio.Queue | None = None,
    ) -> M:
        """Await the next message of a type (optionally matching a predicate).

        Pass an existing ``queue`` from ``subscribe()`` to avoid the
        subscribe-after-send race when correlating RPC responses.
        """
        own_queue = queue is None
        if queue is None:
            queue = self.subscribe(message_type)
        try:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + timeout
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError(
                        f"Timed out waiting for {message_type.__name__}"
                    )
                message = await asyncio.wait_for(queue.get(), remaining)
                if predicate is None or predicate(message):
                    return message  # type: ignore[return-value]
        finally:
            if own_queue:
                self.unsubscribe(message_type, queue)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass


async def request_response(
    sender: SenderHandle,
    router: MessageRouter,
    request: Message,
    response_type: type[M],
    *,
    timeout: float = DEFAULT_WAIT_TIMEOUT,
) -> M:
    """Send a request and await the response echoing its request id.

    Reference: master/src/connection/requester.rs:35-104. The response
    subscription is registered *before* the send so a fast responder can't
    race the correlation wait.
    """
    request_id = getattr(request, "message_request_id")
    queue = router.subscribe(response_type)
    try:
        await sender.send_message(request)
        return await router.wait_for_message(
            response_type,
            predicate=lambda m: getattr(m, "message_request_context_id") == request_id,
            timeout=timeout,
            queue=queue,
        )
    finally:
        router.unsubscribe(response_type, queue)
