"""Reconnection semantics for both sides of the cluster link.

The reference treats a connection as a *logical* entity that survives socket
death: the worker actively reconnects with exponential backoff (base 2.0,
30 s cap, max 12 retries — worker/src/connection/mod.rs:360-398,475-487) and
re-handshakes with ``handshake_type=reconnecting``; the master passively
accepts the reconnect handshake and swaps the new socket into the existing
connection object while in-flight send/receive calls wait for the swap
(master/src/cluster/mod.rs:45-231,453-477).
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import TYPE_CHECKING, Awaitable, Callable

from tpu_render_cluster.transport.ws import (
    WebSocketClosed,
    WebSocketConnection,
    websocket_connect,
)
from tpu_render_cluster.utils.env import env_float, env_int

if TYPE_CHECKING:
    from tpu_render_cluster.obs import MetricsRegistry

logger = logging.getLogger(__name__)


class TransportMetrics:
    """Message/byte/reconnect accounting for one logical connection.

    Thin adapter both logical-connection classes share: the WS layer below
    doesn't know which component owns the socket, and the components above
    shouldn't repeat counter bookkeeping — so the counting lives exactly at
    the logical-connection boundary, labeled by direction.
    """

    def __init__(self, registry: "MetricsRegistry") -> None:
        self._messages = registry.counter(
            "transport_messages_total",
            "WS text messages through the logical connection",
            labels=("direction",),
        )
        self._bytes = registry.counter(
            "transport_bytes_total",
            "Payload characters through the logical connection (~bytes; "
            "the protocol JSON is ASCII)",
            labels=("direction",),
        )
        self._reconnects = registry.counter(
            "transport_reconnects_total", "Socket replacements survived"
        )
        self._connect_attempts = registry.counter(
            "transport_connect_attempts_total",
            "TCP connect + WS upgrade attempts (incl. backoff retries)",
        )

    def sent(self, text: str) -> None:
        self._messages.inc(direction="sent")
        self._bytes.inc(len(text), direction="sent")

    def received(self, text: str) -> None:
        self._messages.inc(direction="received")
        self._bytes.inc(len(text), direction="received")

    def reconnected(self) -> None:
        self._reconnects.inc()

    def connect_attempt(self) -> None:
        self._connect_attempts.inc()

# Reference: worker/src/connection/mod.rs:360-398,475-487. All of these are
# defaults behind TRC_* environment overrides (utils/env.py): deployments
# with different failure profiles — and the chaos harness, which compresses
# every timeout — retune them without code changes.
BACKOFF_BASE = 2.0
BACKOFF_CAP_SECONDS = 30.0
MAX_CONNECT_RETRIES = 12
# Reference: worker/src/connection/mod.rs:133-274 (per-op reconnect budget).
MAX_RECONNECTS_PER_OP = 2
OP_DEADLINE_SECONDS = 30.0


def backoff_base() -> float:
    return env_float("TRC_BACKOFF_BASE", BACKOFF_BASE)


def backoff_cap_seconds() -> float:
    return env_float("TRC_BACKOFF_CAP_SECONDS", BACKOFF_CAP_SECONDS)


def max_connect_retries() -> int:
    return env_int("TRC_MAX_CONNECT_RETRIES", MAX_CONNECT_RETRIES)


def max_reconnects_per_op() -> int:
    return env_int("TRC_MAX_RECONNECTS_PER_OP", MAX_RECONNECTS_PER_OP)


def op_deadline_seconds() -> float:
    return env_float("TRC_OP_DEADLINE_SECONDS", OP_DEADLINE_SECONDS)


async def connect_with_exponential_backoff(
    host: str,
    port: int,
    *,
    max_retries: int | None = None,
    base: float | None = None,
    cap_seconds: float | None = None,
    metrics: TransportMetrics | None = None,
    wrap: Callable[[WebSocketConnection], WebSocketConnection] | None = None,
) -> WebSocketConnection:
    """TCP connect + WS upgrade with full-jitter exponential backoff.

    Each retry sleeps ``uniform(0, min(cap, base**attempt))`` (AWS
    "full jitter"): after a master restart every worker of a large cluster
    retries at an independently random moment instead of reconnecting in
    lockstep at the same deterministic ``base**attempt`` instants.

    ``wrap`` (when given) intercepts each freshly-upgraded connection
    before it is returned — the fault-injection seam (transport/faults.py);
    a wrapper that raises ``WebSocketClosed`` (e.g. a simulated partition)
    consumes a retry like any other connect failure.
    """
    max_retries = max_connect_retries() if max_retries is None else max_retries
    base = backoff_base() if base is None else base
    cap_seconds = backoff_cap_seconds() if cap_seconds is None else cap_seconds
    last_error: Exception | None = None
    for attempt in range(max_retries + 1):
        try:
            if metrics is not None:
                metrics.connect_attempt()
            connection = await websocket_connect(host, port)
            if wrap is not None:
                connection = wrap(connection)
            return connection
        except (WebSocketClosed, OSError) as e:
            last_error = e
            if attempt == max_retries:
                break
            delay = random.uniform(0.0, min(base**attempt, cap_seconds))
            logger.debug(
                "Connect attempt %d/%d to %s:%d failed (%s); retrying in %.2f s",
                attempt + 1, max_retries, host, port, e, delay,
            )
            await asyncio.sleep(delay)
    raise WebSocketClosed(
        f"Could not connect to {host}:{port} after {max_retries} retries: {last_error}"
    )


class ReconnectingClient:
    """Worker-side logical connection with transparent reconnect.

    ``reconnect_fn`` re-establishes the socket AND replays the application
    handshake (with ``handshake_type=reconnecting``); it returns the new
    ``WebSocketConnection``. Send/receive transparently retry through at
    most ``MAX_RECONNECTS_PER_OP`` reconnects within a 30 s op deadline,
    recording each outage window via ``on_reconnect(lost_at, restored_at)``.
    """

    def __init__(
        self,
        connection: WebSocketConnection,
        reconnect_fn: Callable[[], Awaitable[WebSocketConnection]],
        *,
        on_reconnect: Callable[[float, float], None] | None = None,
        metrics: TransportMetrics | None = None,
    ) -> None:
        self._connection = connection
        self._reconnect_fn = reconnect_fn
        self._on_reconnect = on_reconnect
        self._metrics = metrics
        self._reconnect_lock = asyncio.Lock()
        self._generation = 0
        self._closed = False

    @property
    def connection(self) -> WebSocketConnection:
        return self._connection

    def close(self) -> None:
        self._closed = True
        self._connection.abort()

    async def _reconnect(self, failed_generation: int, lost_at: float) -> None:
        """Re-establish the socket once (deduplicated across concurrent ops).

        ``lost_at`` is the wall-clock time of the failing op's FIRST
        exception, stamped by the caller before it contends for the
        reconnect lock: under concurrent op failures the lock is held for
        the whole reconnect, and stamping at lock *acquisition* (as this
        used to) would shorten every recorded outage window by however long
        the op queued behind its siblings.
        """
        import time

        async with self._reconnect_lock:
            if self._generation != failed_generation:
                return  # another task already reconnected
            if self._closed:
                raise WebSocketClosed("Client is closed.")
            self._connection.abort()
            self._connection = await self._reconnect_fn()
            self._generation += 1
            if self._metrics is not None:
                self._metrics.reconnected()
            if self._on_reconnect is not None:
                self._on_reconnect(lost_at, time.time())
            logger.info("Reconnected to master (generation %d).", self._generation)

    async def _with_retries(self, op: Callable[[WebSocketConnection], Awaitable]):
        import time

        loop = asyncio.get_running_loop()
        deadline = loop.time() + op_deadline_seconds()
        reconnect_budget = max_reconnects_per_op()
        reconnects = 0
        while True:
            connection = self._connection
            generation = self._generation
            try:
                return await op(connection)
            except WebSocketClosed:
                lost_at = time.time()
                if self._closed:
                    raise
                reconnects += 1
                if reconnects > reconnect_budget or loop.time() > deadline:
                    raise
                while True:
                    try:
                        await self._reconnect(generation, lost_at)
                        break
                    except WebSocketClosed:
                        # The reconnect ATTEMPT failed — e.g. the master
                        # died mid-handshake (TCP accepted, then the
                        # process was torn down before its
                        # acknowledgement). That must not kill the op (a
                        # worker racing a master failover would give up
                        # exactly when its standby is about to appear),
                        # and it must not burn the per-op reconnect
                        # budget either: a dying master can refuse
                        # handshakes in MILLISECONDS, faster than any
                        # budget survives. Attempt failures are bounded
                        # by the op DEADLINE instead, with a short pause
                        # so refusals don't spin the loop hot.
                        if self._closed or loop.time() > deadline:
                            raise
                        await asyncio.sleep(
                            min(0.25, backoff_cap_seconds())
                        )

    async def send_text(self, text: str) -> None:
        await self._with_retries(lambda c: c.send_text(text))
        if self._metrics is not None:
            self._metrics.sent(text)

    async def receive_text(self) -> str:
        text = await self._with_retries(lambda c: c.receive_text())
        if self._metrics is not None:
            self._metrics.received(text)
        return text


class ReconnectableServerConnection:
    """Master-side logical connection surviving socket swaps.

    Send/receive operations block while the status is Disconnected and
    resume when the accept loop swaps a fresh socket in via
    ``replace_inner_connection`` (reference: master/src/cluster/mod.rs:61-231).
    """

    MAX_WAIT_FOR_RECONNECT = 30.0

    def __init__(
        self,
        connection: WebSocketConnection,
        *,
        metrics: TransportMetrics | None = None,
    ) -> None:
        self._connection = connection
        self._connected = asyncio.Event()
        self._connected.set()
        self._closed = False
        self._metrics = metrics
        self.last_known_address = connection.peer_address()

    @property
    def is_connected(self) -> bool:
        return self._connected.is_set()

    def close(self) -> None:
        self._closed = True
        self._connected.set()  # release waiters; they'll observe _closed
        self._connection.abort()

    def replace_inner_connection(self, connection: WebSocketConnection) -> None:
        """Swap a freshly-handshaked socket into this logical connection."""
        self._connection.abort()
        self._connection = connection
        self.last_known_address = connection.peer_address()
        if self._metrics is not None:
            self._metrics.reconnected()
        self._connected.set()

    def _mark_disconnected(self) -> None:
        if not self._closed:
            self._connected.clear()

    async def _await_connection(self) -> WebSocketConnection:
        if self._closed:
            raise WebSocketClosed("Connection is closed.")
        if not self._connected.is_set():
            try:
                await asyncio.wait_for(
                    self._connected.wait(), self.MAX_WAIT_FOR_RECONNECT
                )
            except asyncio.TimeoutError:
                raise WebSocketClosed(
                    "Worker did not reconnect within the wait window."
                ) from None
            if self._closed:
                raise WebSocketClosed("Connection is closed.")
        return self._connection

    async def send_text(self, text: str) -> None:
        while True:
            connection = await self._await_connection()
            try:
                await connection.send_text(text)
                if self._metrics is not None:
                    self._metrics.sent(text)
                return
            except WebSocketClosed:
                if self._connection is connection:
                    self._mark_disconnected()
                if self._closed:
                    raise

    async def receive_text(self) -> str:
        while True:
            connection = await self._await_connection()
            try:
                text = await connection.receive_text()
                if self._metrics is not None:
                    self._metrics.received(text)
                return text
            except WebSocketClosed:
                if self._connection is connection:
                    self._mark_disconnected()
                if self._closed:
                    raise
