from tpu_render_cluster.transport.faults import (
    FaultController,
    FaultyConnection,
    SendDecision,
)
from tpu_render_cluster.transport.ws import (
    MAX_FRAME_SIZE,
    MAX_MESSAGE_SIZE,
    WebSocketClosed,
    WebSocketConnection,
    WebSocketError,
    websocket_accept,
    websocket_connect,
)

__all__ = [
    "FaultController",
    "FaultyConnection",
    "MAX_FRAME_SIZE",
    "MAX_MESSAGE_SIZE",
    "SendDecision",
    "WebSocketClosed",
    "WebSocketConnection",
    "WebSocketError",
    "websocket_accept",
    "websocket_connect",
]
