from tpu_render_cluster.transport.ws import (
    MAX_FRAME_SIZE,
    MAX_MESSAGE_SIZE,
    WebSocketClosed,
    WebSocketConnection,
    WebSocketError,
    websocket_accept,
    websocket_connect,
)

__all__ = [
    "MAX_FRAME_SIZE",
    "MAX_MESSAGE_SIZE",
    "WebSocketClosed",
    "WebSocketConnection",
    "WebSocketError",
    "websocket_accept",
    "websocket_connect",
]
