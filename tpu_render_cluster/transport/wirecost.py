"""Per-message-tag wire-cost accounting around the protocol codec.

Every control-plane byte crosses exactly one seam: ``encode_message`` on
the way out and ``decode_message`` on the way in (protocol/messages.py).
``WireAccounting`` wraps that seam with a metrics registry so both ends
of a socket price their traffic per message tag —

- ``transport_message_bytes_total{tag,direction}``: exact UTF-8 wire
  payload bytes. ``encode_message`` emits ASCII-escaped JSON
  (``json.dumps`` default ``ensure_ascii=True``), so ``len(text)`` IS
  the byte count the WebSocket layer frames; the sender's ``send``
  series and the receiver's ``recv`` series for a tag count the same
  bytes and must agree exactly.
- ``transport_serialize_seconds{tag,direction}``: time spent in
  ``json.dumps``/``json.loads`` per message — the host-glue cost the
  attribution report charges to transport, and the number ROADMAP
  item 3's preserialized-dispatch idea has to beat.

The accounting observes the text the codec already produces — it adds
ZERO bytes on the wire (PROTOCOL.md notes this) and, with
``metrics=None``, compiles down to the bare codec calls so call sites
can wrap unconditionally.
"""

from __future__ import annotations

import time

from tpu_render_cluster.protocol import messages as pm

__all__ = ["WireAccounting", "top_talkers"]

BYTES_METRIC = "transport_message_bytes_total"
SERIALIZE_METRIC = "transport_serialize_seconds"

_BYTES_HELP = "Wire payload bytes by message tag and direction"
_SERIALIZE_HELP = "Message JSON serialize/parse seconds by tag and direction"
_LABELS = ("tag", "direction")


class WireAccounting:
    """Codec wrapper recording per-tag byte and serialize-time series.

    One instance per connection endpoint (master handle, worker runtime,
    handshake site); instances sharing a registry share series. With
    ``metrics=None`` both methods are passthroughs to the codec.
    """

    def __init__(self, metrics=None) -> None:
        self.metrics = metrics
        if metrics is not None:
            self._bytes = metrics.counter(BYTES_METRIC, _BYTES_HELP, labels=_LABELS)
            self._seconds = metrics.histogram(
                SERIALIZE_METRIC, _SERIALIZE_HELP, labels=_LABELS
            )

    def encode(self, message: pm.Message) -> str:
        if self.metrics is None:
            return pm.encode_message(message)
        started = time.perf_counter()
        text = pm.encode_message(message)
        elapsed = time.perf_counter() - started
        tag = message.type_name
        self._seconds.observe(elapsed, tag=tag, direction="send")
        self._bytes.inc(len(text), tag=tag, direction="send")
        return text

    def record_send(self, tag: str, text: str, seconds: float) -> None:
        """Account an outbound frame the send site ALREADY encoded.

        The preserialized dispatch path (protocol/frames.py) produces
        its text outside the codec; accounting must observe that text
        as-is — re-running ``encode_message`` just to measure would
        double the very cost being eliminated. One serialize per message
        end-to-end is the contract (the call-count test pins it).
        ``seconds`` is the send site's measured encode time (a splice,
        not a ``json.dumps``, but charged to the same series so the A/B
        comparison reads off one metric).
        """
        if self.metrics is None:
            return
        self._seconds.observe(seconds, tag=tag, direction="send")
        self._bytes.inc(len(text), tag=tag, direction="send")

    def decode(self, text: str | bytes) -> pm.Message:
        if self.metrics is None:
            return pm.decode_message(text)
        started = time.perf_counter()
        message = pm.decode_message(text)
        elapsed = time.perf_counter() - started
        tag = message.type_name
        self._seconds.observe(elapsed, tag=tag, direction="recv")
        self._bytes.inc(len(text), tag=tag, direction="recv")
        return message


def top_talkers(snapshot: dict, *, limit: int = 5) -> list[dict]:
    """Per-tag wire totals from a registry ``snapshot()``, biggest first.

    Folds both directions per tag (on a single endpoint, send and recv
    cover disjoint traffic, so the sum is that endpoint's total bytes
    touching the wire). Returns ``[{tag, bytes, send_bytes, recv_bytes,
    serialize_s}, ...]`` — the dashboard's top-talkers table and the
    attribution report's transport detail both read off this.
    """
    by_tag: dict[str, dict] = {}
    counter = snapshot.get(BYTES_METRIC)
    if counter:
        for key, value in counter.get("series", {}).items():
            labels = _parse_label_key(key)
            tag = labels.get("tag", "?")
            row = by_tag.setdefault(
                tag,
                {"tag": tag, "bytes": 0.0, "send_bytes": 0.0, "recv_bytes": 0.0,
                 "serialize_s": 0.0},
            )
            row["bytes"] += value
            if labels.get("direction") == "send":
                row["send_bytes"] += value
            elif labels.get("direction") == "recv":
                row["recv_bytes"] += value
    histogram = snapshot.get(SERIALIZE_METRIC)
    if histogram:
        for key, series in histogram.get("series", {}).items():
            labels = _parse_label_key(key)
            tag = labels.get("tag", "?")
            row = by_tag.setdefault(
                tag,
                {"tag": tag, "bytes": 0.0, "send_bytes": 0.0, "recv_bytes": 0.0,
                 "serialize_s": 0.0},
            )
            row["serialize_s"] += float(series.get("sum", 0.0))
    rows = sorted(by_tag.values(), key=lambda r: r["bytes"], reverse=True)
    return rows[: max(0, limit)] if limit else rows


def _parse_label_key(key: str) -> dict[str, str]:
    """``"tag=ping,direction=send"`` -> labels dict (registry key form)."""
    labels: dict[str, str] = {}
    if not key:
        return labels
    for part in key.split(","):
        name, sep, value = part.partition("=")
        if sep:
            labels[name] = value
    return labels
