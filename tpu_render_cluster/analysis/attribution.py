"""Whole-stack time attribution: where did the wall time go?

Folds the independently-collected timing evidence — per-kernel roofline
execute seconds (obs/profiling.py), per-worker busy/idle windows from the
merged cluster timeline (analysis/critical_path.py), scheduler tick
phases (sched/tickprof.py), event-loop lag (obs/loopmon.py), and wire
serialize costs (transport/wirecost.py) — into ONE partition of the
run's worker-seconds:

- ``device_compute`` — seconds the accelerator was actually executing
  kernels (roofline measured-execute totals, capped by worker busy time);
- ``host_glue`` — worker busy time that was NOT device execute: Python
  driving, image encode, file IO, backend overhead;
- ``transport`` — control-plane JSON serialize/parse seconds on both
  socket ends;
- ``control_plane`` — scheduler tick seconds (share scan, fair-share,
  pricing, dispatch);
- ``queue_wait`` — worker idle: no unit queued, the residual.

The partition is residual-based and therefore sums to exactly 1.0 by
construction: device is carved out of busy time, transport and control
out of what remains, and the residual splits into queue wait (up to the
measured idle) and host glue. Each component is a *measured lower bound*
clamped so overlapping instrumentation (a tick that runs while a worker
renders) can never push the total past the denominator.

``summarize_attribution`` (analysis/obs_events.py) extracts the inputs
from exported artifacts and calls :func:`attribution_report`; bench.py
calls it directly with an explicit worker-seconds window.
"""

from __future__ import annotations

from typing import Any

__all__ = ["attribution_report", "FRACTION_KEYS"]

FRACTION_KEYS = (
    "device_compute",
    "host_glue",
    "queue_wait",
    "transport",
    "control_plane",
)


def _pool_from_sections(sections: dict[str, Any]) -> tuple[float, float]:
    """Total (busy_s, idle_s) across every run section's workers."""
    busy = idle = 0.0
    for section in sections.values():
        for worker in (section.get("workers") or {}).values():
            busy += float(worker.get("busy_s", 0.0))
            idle += float(worker.get("idle_s", 0.0))
    return busy, idle


def _partition(
    total: float,
    busy: float,
    idle: float,
    device_seconds: float,
    transport_seconds: float,
    control_seconds: float,
) -> dict[str, float]:
    """Carve ``total`` into the five components; sums to ``total`` exactly."""
    device = min(max(0.0, device_seconds), busy, total)
    remainder = total - device
    transport = min(max(0.0, transport_seconds), remainder)
    remainder -= transport
    control = min(max(0.0, control_seconds), remainder)
    remainder -= control
    queue_wait = min(max(0.0, idle), remainder)
    host_glue = remainder - queue_wait
    return {
        "device_compute": device,
        "host_glue": host_glue,
        "queue_wait": queue_wait,
        "transport": transport,
        "control_plane": control,
    }


def attribution_report(
    *,
    critical_sections: dict[str, Any] | None = None,
    worker_seconds: float | None = None,
    device_seconds: float = 0.0,
    transport_seconds: float = 0.0,
    control_seconds: float = 0.0,
    tick: dict[str, Any] | None = None,
    loop_lag: dict[str, Any] | None = None,
    top_talkers: list[dict[str, Any]] | None = None,
) -> dict[str, Any] | None:
    """Build the ``attribution`` section.

    The denominator is the run's total worker-seconds: summed per-worker
    ``busy_s + idle_s`` from ``critical_sections`` (the per-run
    ``summarize_critical_path`` outputs) when a merged timeline exists,
    else the explicit ``worker_seconds`` window (bench: elapsed x
    workers). None when neither yields a positive denominator.
    """
    busy = idle = 0.0
    if critical_sections:
        busy, idle = _pool_from_sections(critical_sections)
    total = busy + idle
    if total <= 0.0 and worker_seconds is not None:
        total = max(0.0, float(worker_seconds))
        busy, idle = total, 0.0
    if total <= 0.0:
        return None

    seconds = _partition(
        total, busy, idle, device_seconds, transport_seconds, control_seconds
    )
    fractions = {key: value / total for key, value in seconds.items()}
    out: dict[str, Any] = {
        "worker_seconds": round(total, 6),
        "seconds": {k: round(v, 6) for k, v in seconds.items()},
        "fractions": {k: round(v, 6) for k, v in fractions.items()},
        "fractions_sum": round(sum(fractions.values()), 6),
    }
    if tick:
        out["tick"] = tick
    if loop_lag:
        out["loop_lag"] = loop_lag
    if top_talkers:
        out["top_talkers"] = top_talkers

    if critical_sections and busy + idle > 0.0:
        # Per-run (per-job in the harness's one-trace-per-job naming):
        # device splits by each run's share of busy time, transport and
        # control-plane by its share of the total window — the master's
        # costs serve every job concurrently, so a wall-time share is
        # the fairest apportioning the evidence supports.
        per_run: dict[str, Any] = {}
        for stem, section in critical_sections.items():
            run_busy, run_idle = _pool_from_sections({stem: section})
            run_total = run_busy + run_idle
            if run_total <= 0.0:
                continue
            run_device = device_seconds * (run_busy / busy) if busy else 0.0
            run_transport = transport_seconds * (run_total / total)
            run_control = control_seconds * (run_total / total)
            run_seconds = _partition(
                run_total, run_busy, run_idle,
                run_device, run_transport, run_control,
            )
            per_run[stem] = {
                "worker_seconds": round(run_total, 6),
                "fractions": {
                    k: round(v / run_total, 6) for k, v in run_seconds.items()
                },
            }
        if per_run:
            out["per_run"] = per_run
    return out
