"""Analysis-side trace models (reference: analysis/core/models.py).

Loads the raw-trace JSON written by the master and exposes the derived
quantities the metric modules need. Validates the same invariants as the
reference loader: well-formed JSON, and worker count equal to the job's
``wait_for_number_of_workers`` (analysis/core/models.py:278-282).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path

from tpu_render_cluster.jobs.models import BlenderJob
from tpu_render_cluster.traces.worker_trace import WorkerTrace


@dataclass(frozen=True)
class JobTrace:
    job: BlenderJob
    job_started_at: float
    job_finished_at: float
    worker_traces: dict[str, WorkerTrace]

    @classmethod
    def load_from_trace_file(cls, trace_file_path: str | Path) -> "JobTrace":
        path = Path(trace_file_path)
        if not path.is_file():
            raise RuntimeError(f"Missing raw trace file: {path}!")
        data = json.loads(path.read_text(encoding="utf-8"))
        job = BlenderJob.from_dict(data["job"])
        master = data["master_trace"]
        worker_traces = {
            name: WorkerTrace.from_dict(raw)
            for name, raw in data["worker_traces"].items()
        }
        if len(worker_traces) != job.wait_for_number_of_workers:
            raise ValueError(
                f"Invalid data: len(worker_traces) = {len(worker_traces)}, but "
                f"wait_for_number_of_workers = {job.wait_for_number_of_workers}!"
            )
        return cls(
            job=job,
            job_started_at=float(master["job_start_time"]),
            job_finished_at=float(master["job_finish_time"]),
            worker_traces=worker_traces,
        )

    # -- derived quantities (reference: analysis/core/models.py:133-313) ----

    def job_duration(self) -> float:
        return self.job_finished_at - self.job_started_at

    def cluster_size(self) -> int:
        return self.job.wait_for_number_of_workers

    def strategy_type(self) -> str:
        return self.job.frame_distribution_strategy.strategy_type

    def get_last_frame_finished_at(self) -> float:
        return max(
            last_frame_finished_at(trace) for trace in self.worker_traces.values()
        )


def last_frame_finished_at(trace: WorkerTrace) -> float:
    if not trace.frame_render_traces:
        return trace.job_start_time
    return max(t.details.exited_process_at for t in trace.frame_render_traces)


def worker_tail_delay(trace: WorkerTrace, global_last_finish: float) -> float:
    """Gap between the global last frame finish and this worker's last frame
    finish (reference: analysis/core/models.py:175-181 'without teardown')."""
    return max(0.0, global_last_finish - last_frame_finished_at(trace))


def worker_active_time(trace: WorkerTrace) -> float:
    """Total wall time spent inside frame renders."""
    return sum(t.details.total_execution_time() for t in trace.frame_render_traces)


def mean_frame_time(trace: WorkerTrace) -> float:
    if not trace.frame_render_traces:
        return 0.0
    return worker_active_time(trace) / len(trace.frame_render_traces)
