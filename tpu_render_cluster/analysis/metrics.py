"""Metric computations A5-A12 (reference: analysis/*.py).

Each function takes loaded ``JobTrace`` objects and returns plain dicts so
tests and the report generator stay decoupled from plotting.
"""

from __future__ import annotations

import statistics
from collections import defaultdict
from dataclasses import dataclass

from tpu_render_cluster.analysis.models import (
    JobTrace,
    last_frame_finished_at,
    mean_frame_time,
    worker_active_time,
    worker_tail_delay,
)

SEQUENTIAL_BASELINE_STRATEGY = "eager-naive-coarse"  # reference: speedup.py:35-40


# -- A5: worker utilization --------------------------------------------------


@dataclass(frozen=True)
class WorkerUtilization:
    """active/total per worker (reference: worker_utilization.py:28-91)."""

    worker_name: str
    utilization: float
    utilization_without_tail: float


def worker_utilizations(trace: JobTrace) -> list[WorkerUtilization]:
    out = []
    for name, worker in trace.worker_traces.items():
        total = worker.job_finish_time - worker.job_start_time
        active = worker_active_time(worker)
        utilization = active / total if total > 0 else 0.0
        non_tail_window = last_frame_finished_at(worker) - worker.job_start_time
        without_tail = active / non_tail_window if non_tail_window > 0 else 0.0
        out.append(WorkerUtilization(name, utilization, min(1.0, without_tail)))
    return out


def utilization_stats(traces: list[JobTrace]) -> dict:
    """Utilization grouped by (cluster_size, strategy)."""
    grouped: dict[tuple[int, str], list[float]] = defaultdict(list)
    for trace in traces:
        for u in worker_utilizations(trace):
            grouped[(trace.cluster_size(), trace.strategy_type())].append(
                u.utilization
            )
    return {
        key: {
            "max": max(values),
            "mean": statistics.fmean(values),
            "median": statistics.median(values),
            "min": min(values),
            "count": len(values),
        }
        for key, values in grouped.items()
    }


# -- A6/A7: speedup + efficiency --------------------------------------------


def sequential_baseline_mean(traces: list[JobTrace]) -> float | None:
    """Mean duration of 1-worker eager-naive-coarse runs (reference:
    speedup.py:35-40)."""
    durations = [
        t.job_duration()
        for t in traces
        if t.cluster_size() == 1
        and t.strategy_type() == SEQUENTIAL_BASELINE_STRATEGY
    ]
    return statistics.fmean(durations) if durations else None


def speedup_stats(traces: list[JobTrace]) -> dict:
    baseline = sequential_baseline_mean(traces)
    if baseline is None:
        return {}
    grouped: dict[tuple[int, str], list[float]] = defaultdict(list)
    for trace in traces:
        grouped[(trace.cluster_size(), trace.strategy_type())].append(
            trace.job_duration()
        )
    return {
        key: {
            "speedup": baseline / statistics.fmean(durations),
            "efficiency": baseline / statistics.fmean(durations) / key[0],
            "runs": len(durations),
        }
        for key, durations in grouped.items()
    }


# -- A8: job duration --------------------------------------------------------


def job_duration_stats(traces: list[JobTrace]) -> dict:
    grouped: dict[tuple[int, str], list[float]] = defaultdict(list)
    for trace in traces:
        grouped[(trace.cluster_size(), trace.strategy_type())].append(
            trace.job_duration()
        )
    return {
        key: {
            "mean_seconds": statistics.fmean(durations),
            "mean_hours": statistics.fmean(durations) / 3600.0,
            "runs": len(durations),
        }
        for key, durations in grouped.items()
    }


# -- A9: job tail delay ------------------------------------------------------


def tail_delay_stats(traces: list[JobTrace]) -> dict:
    """Per-run max worker tail delay, absolute and scaled by mean frame time
    (reference: job_tail_delay.py)."""
    grouped: dict[tuple[int, str], list[tuple[float, float]]] = defaultdict(list)
    for trace in traces:
        global_last = trace.get_last_frame_finished_at()
        delays = [
            worker_tail_delay(worker, global_last)
            for worker in trace.worker_traces.values()
        ]
        run_tail = max(delays) if delays else 0.0
        frame_times = [
            mean_frame_time(worker)
            for worker in trace.worker_traces.values()
            if worker.frame_render_traces
        ]
        mean_ft = statistics.fmean(frame_times) if frame_times else 0.0
        scaled = run_tail / mean_ft if mean_ft > 0 else 0.0
        grouped[(trace.cluster_size(), trace.strategy_type())].append(
            (run_tail, scaled)
        )
    return {
        key: {
            "mean_tail_seconds": statistics.fmean(v[0] for v in values),
            "max_tail_seconds": max(v[0] for v in values),
            "mean_tail_scaled": statistics.fmean(v[1] for v in values),
            "runs": len(values),
        }
        for key, values in grouped.items()
    }


# -- A10: worker latency -----------------------------------------------------


def latency_stats(traces: list[JobTrace]) -> dict:
    """Heartbeat RTT in milliseconds, grouped by (cluster size, strategy)
    (reference: worker_latency.py:74-87 keeps the strategy axis — a
    strategy-specific latency pathology must stay visible)."""
    grouped: dict[tuple[int, str], list[float]] = defaultdict(list)
    for trace in traces:
        for worker in trace.worker_traces.values():
            for ping in worker.ping_traces:
                grouped[(trace.cluster_size(), trace.strategy_type())].append(
                    ping.latency() * 1000.0
                )
    return {
        key: {
            "mean_ms": statistics.fmean(values),
            "median_ms": statistics.median(values),
            "max_ms": max(values),
            "over_25ms": sum(1 for v in values if v > 25.0),
            "count": len(values),
        }
        for key, values in grouped.items()
        if values
    }


# -- A11: read/render/write split -------------------------------------------


def phase_split_stats(traces: list[JobTrace]) -> dict:
    """Mean fraction of frame time in load/render/save, grouped by
    (cluster size, strategy) (reference: reading_rendering_writing.py)."""
    grouped: dict[tuple[int, str], list[tuple[float, float, float]]] = (
        defaultdict(list)
    )
    for trace in traces:
        for worker in trace.worker_traces.values():
            for frame in worker.frame_render_traces:
                d = frame.details
                total = d.total_execution_time()
                if total <= 0:
                    continue
                read = d.finished_loading_at - d.started_process_at
                render = d.finished_rendering_at - d.started_rendering_at
                save = d.file_saving_finished_at - d.file_saving_started_at
                grouped[(trace.cluster_size(), trace.strategy_type())].append(
                    (read / total, render / total, save / total)
                )
    return {
        key: {
            "reading": statistics.fmean(v[0] for v in values),
            "rendering": statistics.fmean(v[1] for v in values),
            "writing": statistics.fmean(v[2] for v in values),
            "frames": len(values),
        }
        for key, values in grouped.items()
        if values
    }


# -- A12: run statistics -----------------------------------------------------


def run_statistics(traces: list[JobTrace]) -> dict:
    """Run + reconnect counts per (size, strategy), plus the analyzing
    process's peak RSS (reference: results_statistics.py:34-73; its
    optional pympler memory profiling maps to the RSS figure here)."""
    grouped: dict[tuple[int, str], dict] = defaultdict(
        lambda: {"runs": 0, "reconnects": 0, "frames": 0}
    )
    for trace in traces:
        entry = grouped[(trace.cluster_size(), trace.strategy_type())]
        entry["runs"] += 1
        entry["reconnects"] += sum(
            len(w.reconnection_traces) for w in trace.worker_traces.values()
        )
        entry["frames"] += sum(
            len(w.frame_render_traces) for w in trace.worker_traces.values()
        )
    out: dict = dict(grouped)
    try:
        import resource

        out["analysis_peak_rss_mb"] = round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1
        )
    except Exception:  # noqa: BLE001 - platform-dependent, best effort
        pass
    return out
