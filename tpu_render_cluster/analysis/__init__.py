"""Offline analysis suite — the metrics product.

Python re-implementation of the reference's ``analysis/`` package
(reference: analysis/run_all.py and modules A5-A12 in SURVEY.md §2.5),
operating on the same raw-trace JSON schema. Every metric definition
follows the reference exactly (utilization, speedup vs the 1-worker
eager-naive-coarse sequential mean, efficiency, job duration, absolute and
frame-time-scaled tail delay, heartbeat RTT latency, read/render/write
phase split, run statistics).
"""
