"""Pretty timing context manager (reference: analysis/core/timed_context.py)."""

from __future__ import annotations

import time
from contextlib import contextmanager


@contextmanager
def timed_section(name: str):
    start = time.perf_counter()
    print(f"[{name}] ...", flush=True)
    try:
        yield
    finally:
        print(f"[{name}] done in {time.perf_counter() - start:.2f} s", flush=True)
