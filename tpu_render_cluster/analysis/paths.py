"""Canonical results/analysis path conventions.

The glue that makes harness -> master -> analysis a one-command pipeline
(reference: analysis/core/paths.py:5-44, which pins
``blender-projects/04_very-simple/results/arnes-results`` as the canonical
run-results directory). Here the convention is repo-relative:

- ``results/cluster-runs/``   — raw traces; the SLURM scripts and the
  master's default ``--resultsDirectory`` write here (one subdirectory per
  experiment is fine: the loader globs recursively).
- ``results/analysis/``       — ``run_all`` output: statistics.json + plots.
- ``results/.trace-cache/``   — parsed-trace pickle cache.

Every path can be overridden by CLI flags; ``TRC_RESULTS_DIR`` /
``TRC_ANALYSIS_DIR`` environment variables override the defaults (useful on
clusters where the repo checkout is read-only). Unlike the reference, import
has no mkdir side effects — callers create what they write.
"""

from __future__ import annotations

from pathlib import Path
from tpu_render_cluster.utils.env import env_str

REPO_ROOT = Path(__file__).resolve().parents[2]

BLENDER_PROJECTS_DIR = REPO_ROOT / "blender-projects"

RESULTS_ROOT = Path(env_str("TRC_RESULTS_ROOT") or REPO_ROOT / "results")

DEFAULT_RESULTS_DIR = Path(env_str("TRC_RESULTS_DIR") or RESULTS_ROOT / "cluster-runs")
DEFAULT_ANALYSIS_DIR = Path(env_str("TRC_ANALYSIS_DIR") or RESULTS_ROOT / "analysis")
DEFAULT_CACHE_DIR = RESULTS_ROOT / ".trace-cache"
