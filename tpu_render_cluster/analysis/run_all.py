"""Run the full analysis pipeline (reference: analysis/run_all.py).

Usage:
  python -m tpu_render_cluster.analysis.run_all [--results <dir>] [--out <dir>]

With no arguments it uses the canonical convention from
``tpu_render_cluster.analysis.paths``: traces are read from
``results/cluster-runs`` (where the SLURM scripts and the master's default
``--resultsDirectory`` write) and output lands in ``results/analysis``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpu_render_cluster.analysis import metrics as M
from tpu_render_cluster.analysis.obs_events import (
    load_blackbox_bundles,
    load_cluster_traces,
    load_obs_artifacts,
    summarize_obs,
)
from tpu_render_cluster.analysis.parser import load_traces
from tpu_render_cluster.analysis.paths import DEFAULT_ANALYSIS_DIR, DEFAULT_RESULTS_DIR
from tpu_render_cluster.analysis.timed_context import timed_section


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="trc-analysis")
    parser.add_argument(
        "--results",
        default=str(DEFAULT_RESULTS_DIR),
        help="Directory of *_raw-trace.json (searched recursively)",
    )
    parser.add_argument(
        "--out",
        default=str(DEFAULT_ANALYSIS_DIR),
        help="Output directory for plots + stats",
    )
    parser.add_argument("--no-plots", action="store_true")
    args = parser.parse_args(argv)

    with timed_section("load traces"):
        traces = load_traces(args.results)
    if not traces:
        print(f"No raw traces found under {args.results}", file=sys.stderr)
        return 1
    print(f"Loaded {len(traces)} run(s).")

    # Obs artifacts (trace-event spans + metrics snapshots) ride alongside
    # the legacy raw traces when the run was instrumented; absent files
    # just mean an uninstrumented (or reference-produced) population.
    with timed_section("load obs artifacts"):
        on_obs_error = lambda path, e: print(  # noqa: E731
            f"Skipping malformed obs artifact {path}: {e}", file=sys.stderr
        )
        obs_traces, obs_metrics = load_obs_artifacts(
            args.results, on_error=on_obs_error
        )
        cluster_traces = load_cluster_traces(args.results, on_error=on_obs_error)
        flight_bundles = load_blackbox_bundles(
            args.results, on_error=on_obs_error
        )
    if obs_traces or obs_metrics or cluster_traces or flight_bundles:
        print(
            f"Loaded {len(obs_traces)} trace-event file(s), "
            f"{len(obs_metrics)} metrics snapshot(s), "
            f"{len(cluster_traces)} merged cluster timeline(s), "
            f"{len(flight_bundles)} flight-recorder bundle(s)."
        )

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    stats = {
        "utilization": {str(k): v for k, v in M.utilization_stats(traces).items()},
        "speedup": {str(k): v for k, v in M.speedup_stats(traces).items()},
        "job_duration": {str(k): v for k, v in M.job_duration_stats(traces).items()},
        "tail_delay": {str(k): v for k, v in M.tail_delay_stats(traces).items()},
        "latency": {str(k): v for k, v in M.latency_stats(traces).items()},
        "phase_split": {str(k): v for k, v in M.phase_split_stats(traces).items()},
        "run_statistics": {str(k): v for k, v in M.run_statistics(traces).items()},
    }
    if obs_traces or obs_metrics or cluster_traces or flight_bundles:
        stats["obs"] = summarize_obs(
            obs_traces, obs_metrics, cluster_traces, flight_bundles
        )
    stats_path = out / "statistics.json"
    stats_path.write_text(json.dumps(stats, indent=2))
    print(f"Statistics written to {stats_path}")

    if not args.no_plots:
        from tpu_render_cluster.analysis import plots

        with timed_section("plots"):
            for fn in (
                plots.plot_worker_utilization,
                plots.plot_speedup_and_efficiency,
                plots.plot_job_durations,
                plots.plot_tail_delay,
                plots.plot_tail_delay_grids,
                plots.plot_utilization_vs_strategy,
                plots.plot_latency,
                plots.plot_phase_split,
            ):
                try:
                    print(f"  wrote {fn(traces, out)}")
                except Exception as e:  # noqa: BLE001 - keep producing others
                    print(f"  {fn.__name__} failed: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
