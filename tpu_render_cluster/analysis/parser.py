"""Trace discovery + loading (reference: analysis/core/parser.py).

Globs ``*_raw-trace.json`` under a results directory and loads them
sequentially or with a thread pool; an optional on-disk cache (pickle —
the reference uses dill, same role) skips re-parsing unchanged files.
"""

from __future__ import annotations

import hashlib
import logging
import pickle
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

from tpu_render_cluster.analysis.models import JobTrace

logger = logging.getLogger(__name__)

RAW_TRACE_GLOB = "*_raw-trace.json"


def find_trace_files(results_directory: str | Path) -> list[Path]:
    return sorted(Path(results_directory).rglob(RAW_TRACE_GLOB))


def load_traces(
    results_directory: str | Path,
    *,
    workers: int = 4,
    cache_directory: str | Path | None = None,
) -> list[JobTrace]:
    """Load every raw trace under the directory (thread pool, optional cache)."""
    paths = find_trace_files(results_directory)
    if not paths:
        return []

    cache_dir = Path(cache_directory) if cache_directory else None
    if cache_dir is not None:
        cache_dir.mkdir(parents=True, exist_ok=True)

    def load_one(path: Path) -> JobTrace | None:
        cache_path = None
        if cache_dir is not None:
            digest = hashlib.sha1(
                f"{path}:{path.stat().st_mtime_ns}".encode()
            ).hexdigest()
            cache_path = cache_dir / f"{digest}.pkl"
            if cache_path.is_file():
                try:
                    return pickle.loads(cache_path.read_bytes())
                except Exception:  # noqa: BLE001 - stale cache
                    cache_path.unlink(missing_ok=True)
        try:
            trace = JobTrace.load_from_trace_file(path)
        except Exception as e:  # noqa: BLE001 - skip malformed, keep going
            logger.warning("Skipping malformed trace %s: %s", path, e)
            return None
        if cache_path is not None:
            cache_path.write_bytes(pickle.dumps(trace))
        return trace

    if workers <= 1:
        loaded = [load_one(p) for p in paths]
    else:
        with ThreadPoolExecutor(max_workers=workers) as pool:
            loaded = list(pool.map(load_one, paths))
    return [t for t in loaded if t is not None]
