"""Loaders for the obs subsystem's artifacts (reference has no analog).

Three file families land next to the legacy ``*_raw-trace.json``:

- ``*_trace-events.json`` — Chrome trace-event JSON (Perfetto-loadable)
  with master / worker / transport spans, one file per process clock;
- ``*_cluster_trace-events.json`` — the MERGED cluster timeline: every
  process's spans rebased onto the master clock by the heartbeat
  clock-offset estimates, with flow arrows per frame lifecycle
  (obs/timeline.py);
- ``*_metrics.json`` — metrics registry snapshots (+ the cluster view and
  per-worker heartbeat payload aggregation).

This module validates and loads all of them so ``run_all`` can fold
live-signal summaries (per-phase span statistics, span counts by
category, and the cluster timelines' critical-path/straggler analysis)
into ``statistics.json`` alongside the legacy post-hoc metrics.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable

TRACE_EVENTS_GLOB = "*_trace-events.json"
METRICS_SNAPSHOT_GLOB = "*_metrics.json"
# Merged, clock-corrected cluster timelines (obs/timeline.py). They match
# TRACE_EVENTS_GLOB too, so the per-process finder excludes them — their
# events are the per-process files' events re-based, and counting both
# would double every span in the roll-up. The leading underscore is part
# of the discriminator: exporters write "<prefix>_cluster_trace-events.json",
# and a run PREFIX that merely ends in "cluster" must not be misclassified.
CLUSTER_TRACE_SUFFIX = "_cluster_trace-events.json"


def find_trace_event_files(results_directory: str | Path) -> list[Path]:
    return sorted(
        path
        for path in Path(results_directory).rglob(TRACE_EVENTS_GLOB)
        if not path.name.endswith(CLUSTER_TRACE_SUFFIX)
    )


def find_cluster_trace_files(results_directory: str | Path) -> list[Path]:
    return sorted(Path(results_directory).rglob(f"*{CLUSTER_TRACE_SUFFIX}"))


def find_metrics_files(results_directory: str | Path) -> list[Path]:
    return sorted(Path(results_directory).rglob(METRICS_SNAPSHOT_GLOB))


# Flight-recorder post-mortem bundles (obs/flightrec.py).
BLACKBOX_GLOB = "*_blackbox.json"


def find_blackbox_files(results_directory: str | Path) -> list[Path]:
    return sorted(Path(results_directory).rglob(BLACKBOX_GLOB))


@dataclass(frozen=True)
class ObsTrace:
    """One loaded trace-event file."""

    path: Path
    events: list[dict[str, Any]]

    def spans(self) -> list[dict[str, Any]]:
        """Complete ('X') events only — the duration-carrying spans."""
        return [e for e in self.events if e.get("ph") == "X"]

    def span_seconds_by_name(self) -> dict[str, list[float]]:
        out: dict[str, list[float]] = {}
        for event in self.spans():
            out.setdefault(str(event.get("name")), []).append(
                float(event.get("dur", 0.0)) / 1e6
            )
        return out

    def span_count_by_category(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for event in self.spans():
            cat = str(event.get("cat", "default"))
            out[cat] = out.get(cat, 0) + 1
        return out


def load_trace_events(path: str | Path) -> ObsTrace:
    """Load + validate one Chrome trace-event file.

    Accepts both container formats the viewers accept: the JSON Object
    Format (``{"traceEvents": [...]}`` — what this repo writes) and the
    bare JSON Array Format.
    """
    path = Path(path)
    data = json.loads(path.read_text(encoding="utf-8"))
    if isinstance(data, dict):
        events = data.get("traceEvents")
    elif isinstance(data, list):
        events = data
    else:
        raise ValueError(f"{path}: not a Chrome trace-event document")
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents must be a list")
    for event in events:
        if not isinstance(event, dict) or "ph" not in event:
            raise ValueError(f"{path}: malformed trace event: {event!r}")
        if event["ph"] == "X" and ("ts" not in event or "dur" not in event):
            raise ValueError(f"{path}: complete event missing ts/dur: {event!r}")
    return ObsTrace(path=path, events=events)


def load_metrics_snapshot(path: str | Path) -> dict[str, Any]:
    """Load + validate one metrics snapshot file."""
    path = Path(path)
    data = json.loads(path.read_text(encoding="utf-8"))
    if not isinstance(data, dict) or "metrics" not in data:
        raise ValueError(f"{path}: not a metrics snapshot (missing 'metrics')")
    if not isinstance(data["metrics"], dict):
        raise ValueError(f"{path}: 'metrics' must be an object")
    return data


def load_obs_artifacts(
    results_directory: str | Path,
    *,
    on_error: "Callable[[Path, Exception], None] | None" = None,
) -> tuple[list[ObsTrace], list[dict[str, Any]]]:
    """Load every obs artifact under a results directory (both families).

    With ``on_error`` set, a malformed file is reported to it and skipped
    so one bad artifact doesn't discard the rest of the population;
    without it, the first malformed file raises.
    """
    traces: list[ObsTrace] = []
    metrics: list[dict[str, Any]] = []
    for loader, sink, paths in (
        (load_trace_events, traces, find_trace_event_files(results_directory)),
        (load_metrics_snapshot, metrics, find_metrics_files(results_directory)),
    ):
        for path in paths:
            try:
                sink.append(loader(path))
            except (ValueError, OSError, json.JSONDecodeError) as e:
                if on_error is None:
                    raise
                on_error(path, e)
    return traces, metrics


def load_cluster_traces(
    results_directory: str | Path,
    *,
    on_error: "Callable[[Path, Exception], None] | None" = None,
) -> list[ObsTrace]:
    """Load every merged cluster timeline under a results directory."""
    traces: list[ObsTrace] = []
    for path in find_cluster_trace_files(results_directory):
        try:
            traces.append(load_trace_events(path))
        except (ValueError, OSError, json.JSONDecodeError) as e:
            if on_error is None:
                raise
            on_error(path, e)
    return traces


def load_blackbox_bundles(
    results_directory: str | Path,
    *,
    on_error: "Callable[[Path, Exception], None] | None" = None,
) -> list[dict[str, Any]]:
    """Load every flight-recorder bundle under a results directory; each
    returned dict gains a ``path`` key for provenance."""
    bundles: list[dict[str, Any]] = []
    for path in find_blackbox_files(results_directory):
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
            if not isinstance(data, dict) or not isinstance(
                data.get("blackbox"), dict
            ):
                raise ValueError("not a flight-recorder bundle")
            bundles.append({**data, "path": str(path)})
        except (ValueError, OSError, json.JSONDecodeError) as e:
            if on_error is None:
                raise
            on_error(path, e)
    return bundles


def _percentile(sorted_values: list[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    index = min(len(sorted_values) - 1, int(round(q * (len(sorted_values) - 1))))
    return sorted_values[index]


def _consume_metric_snapshots(
    metrics: list[dict[str, Any]], take_registry, take_wire
) -> None:
    """Walk metric snapshots, consuming every series exactly once.

    ``take_registry(names) -> bool`` is fed registry-snapshot forms (a
    snapshot's own ``metrics``, the harness's per-worker ``workers``,
    and process_metrics — see below), returning whether it consumed
    anything; ``take_wire(wire)`` gets the compact heartbeat wire form
    (``cluster_metrics``), consumed only when no registry snapshot
    covered that file, so nothing is double-counted.

    The harness's process-global snapshots are CUMULATIVE per process
    (every job a harness process runs re-exports the same counters):
    only the NEWEST snapshot per pid is consumed, once — summing every
    file's copy would multiply counters by the job count and re-weight
    histogram means toward earlier jobs.
    """
    newest_per_pid: dict[Any, tuple[float, dict[str, Any]]] = {}
    snapshots_with_process_metrics: set[int] = set()
    for snapshot_index, snapshot in enumerate(metrics):
        process_entry = snapshot.get("process_metrics")
        if isinstance(process_entry, dict) and isinstance(
            process_entry.get("metrics"), dict
        ):
            snapshots_with_process_metrics.add(snapshot_index)
            pid = process_entry.get("pid")
            written_at = float(snapshot.get("written_at", 0.0))
            best = newest_per_pid.get(pid)
            if best is None or written_at >= best[0]:
                newest_per_pid[pid] = (written_at, process_entry["metrics"])

    for snapshot_index, snapshot in enumerate(metrics):
        took_registries = snapshot_index in snapshots_with_process_metrics
        take_registry(snapshot.get("metrics", {}))
        for worker_registry in (snapshot.get("workers") or {}).values():
            if isinstance(worker_registry, dict) and take_registry(worker_registry):
                took_registries = True
        if not took_registries:
            wire = snapshot.get("cluster_metrics")
            if isinstance(wire, dict):
                take_wire(wire)
    for _written_at, registry in newest_per_pid.values():
        take_registry(registry)


def summarize_wavefront(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the wavefront occupancy series (render/compaction.py) up.

    Extracts ``render_alive_fraction`` (per-bounce survival histogram),
    ``render_lane_occupancy`` (live/launch-width gauge) and
    ``render_compiles_total`` (bucket-ladder compile counter) from
    metrics snapshots — both shapes the snapshot families carry:
    registry-snapshot form (the snapshot's own ``metrics`` and the
    harness's per-worker ``workers``) and the compact heartbeat wire
    form (the master CLI's merged ``cluster_metrics``, consumed only
    when no per-worker registry snapshots are present, so nothing is
    double-counted). None when no snapshot carries the series (job
    never rendered wavefront-style).
    """
    found = False
    alive_count = 0
    alive_sum = 0.0
    by_bounce: dict[str, dict[str, float]] = {}
    occupancy: float | None = None
    compiles = 0.0

    def take_alive(label: str, count: int, total: float) -> None:
        nonlocal found, alive_count, alive_sum
        found = True
        alive_count += count
        alive_sum += total
        entry = by_bounce.setdefault(label, {"count": 0, "sum": 0.0})
        entry["count"] += count
        entry["sum"] += total

    def take_registry(names: dict[str, Any]) -> bool:
        nonlocal found, occupancy, compiles
        took = False
        histogram = names.get("render_alive_fraction")
        if histogram:
            took = True
            for label, series in histogram.get("series", {}).items():
                take_alive(
                    label,
                    int(series.get("count", 0)),
                    float(series.get("sum", 0.0)),
                )
        gauge = names.get("render_lane_occupancy")
        if gauge and gauge.get("series"):
            found = took = True
            occupancy = float(list(gauge["series"].values())[-1])
        counter = names.get("render_compiles_total")
        if counter:
            found = took = True
            compiles += sum(float(v) for v in counter.get("series", {}).values())
        return took

    def take_wire(wire: dict[str, Any]) -> None:
        nonlocal found, occupancy, compiles
        for key, entry in (wire.get("h") or {}).items():
            name, _, label = key.partition("|")
            if name == "render_alive_fraction":
                take_alive(label, int(entry.get("n", 0)), float(entry.get("s", 0.0)))
        for key, value in (wire.get("g") or {}).items():
            if key.partition("|")[0] == "render_lane_occupancy":
                found = True
                occupancy = float(value)
        for key, value in (wire.get("c") or {}).items():
            if key.partition("|")[0] == "render_compiles_total":
                found = True
                compiles += float(value)

    _consume_metric_snapshots(metrics, take_registry, take_wire)
    if not found:
        return None
    out: dict[str, Any] = {"compiles_total": compiles}
    if occupancy is not None:
        out["lane_occupancy_last"] = occupancy
    if alive_count:
        out["wasted_lane_fraction"] = 1.0 - alive_sum / alive_count
        out["alive_fraction_mean_by_bounce"] = {
            label: entry["sum"] / entry["count"]
            for label, entry in sorted(by_bounce.items())
            if entry["count"]
        }
    return out


def summarize_raypool(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the device ray-pool series (render/raypool.py) up.

    Extracts ``render_pool_live_fraction`` (per-iteration pool
    occupancy histogram — its complement is the raypool
    wasted_lane_fraction), ``render_pool_occupancy`` (last batch's mean
    gauge), the refill/iteration counters, and the worker backend's
    rendered-ahead ``render_raypool_cache_hits_total``. Same snapshot-
    family handling as summarize_wavefront: registry-snapshot form
    first (newest per pid for the cumulative process_metrics), compact
    wire form only when no registry snapshot covered that file. None
    when no snapshot carries the series (job never used the pool).
    """
    found = False
    live_count = 0
    live_sum = 0.0
    occupancy: float | None = None
    counters = {
        "render_pool_refill_rays_total": 0.0,
        "render_pool_iterations_total": 0.0,
        "render_raypool_cache_hits_total": 0.0,
        "render_pool_launched_lanes_total": 0.0,
        "render_pool_live_lanes_total": 0.0,
    }

    def take_registry(names: dict[str, Any]) -> bool:
        nonlocal found, live_count, live_sum, occupancy
        took = False
        histogram = names.get("render_pool_live_fraction")
        if histogram:
            found = took = True
            for series in histogram.get("series", {}).values():
                live_count += int(series.get("count", 0))
                live_sum += float(series.get("sum", 0.0))
        gauge = names.get("render_pool_occupancy")
        if gauge and gauge.get("series"):
            found = took = True
            occupancy = float(list(gauge["series"].values())[-1])
        for name in counters:
            counter = names.get(name)
            if counter:
                found = took = True
                counters[name] += sum(
                    float(v) for v in counter.get("series", {}).values()
                )
        return took

    def take_wire(wire: dict[str, Any]) -> None:
        nonlocal found, live_count, live_sum, occupancy
        for key, entry in (wire.get("h") or {}).items():
            if key.partition("|")[0] == "render_pool_live_fraction":
                found = True
                live_count += int(entry.get("n", 0))
                live_sum += float(entry.get("s", 0.0))
        for key, value in (wire.get("g") or {}).items():
            if key.partition("|")[0] == "render_pool_occupancy":
                found = True
                occupancy = float(value)
        for key, value in (wire.get("c") or {}).items():
            name = key.partition("|")[0]
            if name in counters:
                found = True
                counters[name] += float(value)

    _consume_metric_snapshots(metrics, take_registry, take_wire)
    if not found:
        return None
    out: dict[str, Any] = {
        "refill_rays_total": counters["render_pool_refill_rays_total"],
        "iterations_total": counters["render_pool_iterations_total"],
        "cache_hits_total": counters["render_raypool_cache_hits_total"],
    }
    if occupancy is not None:
        out["pool_occupancy_last_batch"] = occupancy
    launched = counters["render_pool_launched_lanes_total"]
    if launched > 0:
        # Lane-weighted (the true launched-lane fraction; the per-
        # iteration histogram below would overweight the drain tail's
        # tiny launches).
        live_lanes = counters["render_pool_live_lanes_total"]
        out["wasted_lane_fraction"] = 1.0 - live_lanes / launched
        out["pool_occupancy_mean"] = live_lanes / launched
    elif live_count:
        out["wasted_lane_fraction"] = 1.0 - live_sum / live_count
        out["pool_occupancy_mean"] = live_sum / live_count
    return out


def summarize_sched(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the multi-job scheduler's evidence up (sched/ artifacts).

    Metrics snapshots written by a ``sched.JobManager`` carry a top-level
    ``sched`` section (``scheduler_view()``) whose per-job views hold the
    lifecycle record: makespan, admission wait, achieved vs. target share
    over the multi-job overlap window, preemption counts, and the per-job
    exactly-once ledger. Jobs are keyed ``<job_name>:<job_id>``; when the
    same key appears in several snapshots (the live 1 Hz file plus the
    final one) the newest ``written_at`` wins. None when no snapshot came
    from a scheduler run — single-job runs get no ``sched`` section.
    """
    jobs: dict[str, tuple[float, dict[str, Any]]] = {}
    for snapshot in metrics:
        sched = snapshot.get("sched")
        if not isinstance(sched, dict):
            continue
        written_at = float(snapshot.get("written_at", 0.0))
        for job_id, view in (sched.get("jobs") or {}).items():
            if not isinstance(view, dict):
                continue
            key = f"{view.get('job_name', '?')}:{job_id}"
            share = view.get("share") if isinstance(view.get("share"), dict) else {}
            entry = {
                "job_id": job_id,
                "job_name": view.get("job_name"),
                "status": view.get("status"),
                "weight": view.get("weight"),
                "priority": view.get("priority"),
                "frames_total": view.get("frames_total"),
                "admission_wait_seconds": view.get("admission_wait_seconds"),
                "makespan_seconds": view.get("makespan_seconds"),
                "preemptions": view.get("preemptions", 0),
                "share_target": share.get("target"),
                "share_achieved": share.get("achieved"),
                "overlap_seconds": share.get("overlap_seconds"),
                "ledger": view.get("ledger"),
            }
            best = jobs.get(key)
            if best is None or written_at >= best[0]:
                jobs[key] = (written_at, entry)
    if not jobs:
        return None
    entries = {key: entry for key, (_at, entry) in sorted(jobs.items())}
    makespans = [
        e["makespan_seconds"]
        for e in entries.values()
        if isinstance(e.get("makespan_seconds"), (int, float))
    ]
    out: dict[str, Any] = {
        "jobs": entries,
        "jobs_total": len(entries),
        "finished": sum(1 for e in entries.values() if e["status"] == "finished"),
        "cancelled": sum(1 for e in entries.values() if e["status"] == "cancelled"),
        "preemptions_total": sum(
            int(e.get("preemptions") or 0) for e in entries.values()
        ),
    }
    if makespans:
        out["makespan_seconds_max"] = max(makespans)
        out["makespan_seconds_mean"] = sum(makespans) / len(makespans)
    return out


def summarize_tiles(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the master's tile-assembly evidence up (tiled jobs, PR 7).

    Aggregates ``master_frames_assembled_total`` (by stitch outcome) and
    the ``master_frame_assembly_seconds`` histogram from the master's
    registry snapshots, plus each job view's ``assembly`` section when
    present. None when no snapshot shows an assembled frame — untiled
    runs get no ``tiles`` section. The per-tile straggler scores and
    assembly-wait attribution live under ``critical_path.*.tiles``
    (analysis/critical_path.tile_statistics), derived from the merged
    cluster timeline's per-unit lifecycles.
    """
    assembled: dict[str, float] = {}
    stitch_count = 0
    stitch_sum = 0.0
    jobs: dict[str, Any] = {}
    for snapshot in metrics:
        names = snapshot.get("metrics", {})
        counter = names.get("master_frames_assembled_total")
        if counter:
            for label, value in counter.get("series", {}).items():
                key = label.partition("=")[2] or label or "total"
                assembled[key] = assembled.get(key, 0.0) + float(value)
        histogram = names.get("master_frame_assembly_seconds")
        if histogram:
            for series in histogram.get("series", {}).values():
                stitch_count += int(series.get("count", 0))
                stitch_sum += float(series.get("sum", 0.0))
        for job_name, view in (snapshot.get("jobs") or {}).items():
            if isinstance(view, dict) and isinstance(view.get("assembly"), dict):
                jobs[job_name] = view["assembly"]
    if not assembled:
        return None
    out: dict[str, Any] = {
        "frames_assembled": assembled,
        "stitch_count": stitch_count,
        "stitch_seconds_total": stitch_sum,
    }
    if stitch_count:
        out["stitch_seconds_mean"] = stitch_sum / stitch_count
    if jobs:
        out["jobs"] = jobs
    return out


def summarize_prediction(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the predictive-scheduling evidence up (sched/cost_model.py +
    master/speculate.py).

    Three families: the cost model's prediction quality
    (``sched_cost_model_abs_error_seconds`` — absolute error of each
    per-unit prediction at observation time, i.e. predicted vs actual),
    the per-unit winning-result latency distribution
    (``master_unit_latency_seconds`` — what speculation is judged on),
    and the speculation ledger (``sched_speculations_total{outcome}`` +
    the launched counter). The live ``prediction``/``speculation``
    sections a master's cluster_view stamps into its snapshots ride
    along (newest snapshot wins). None when no snapshot carries any of
    it — runs without the predictive layer get no ``prediction`` section.
    """
    found = False
    abs_error_count = 0
    abs_error_sum = 0.0
    latency_count = 0
    latency_sum = 0.0
    speculations: dict[str, float] = {}
    launched = 0.0
    live: dict[str, Any] = {}
    # Newest-wins PER SECTION: snapshots from different masters may each
    # carry only one of the two live views, and one must not age out the
    # other.
    live_at: dict[str, float] = {}

    def take_registry(names: dict[str, Any]) -> bool:
        nonlocal found, abs_error_count, abs_error_sum
        nonlocal latency_count, latency_sum, launched
        took = False
        histogram = names.get("sched_cost_model_abs_error_seconds")
        if histogram:
            found = took = True
            for series in histogram.get("series", {}).values():
                abs_error_count += int(series.get("count", 0))
                abs_error_sum += float(series.get("sum", 0.0))
        histogram = names.get("master_unit_latency_seconds")
        if histogram:
            found = took = True
            for series in histogram.get("series", {}).values():
                latency_count += int(series.get("count", 0))
                latency_sum += float(series.get("sum", 0.0))
        counter = names.get("sched_speculations_total")
        if counter:
            found = took = True
            for label, value in counter.get("series", {}).items():
                outcome = label.partition("=")[2] or label or "total"
                speculations[outcome] = speculations.get(outcome, 0.0) + float(
                    value
                )
        counter = names.get("sched_speculations_launched_total")
        if counter:
            found = took = True
            launched += sum(
                float(v) for v in counter.get("series", {}).values()
            )
        return took

    def take_wire(wire: dict[str, Any]) -> None:
        nonlocal found, abs_error_count, abs_error_sum
        nonlocal latency_count, latency_sum, launched
        for key, entry in (wire.get("h") or {}).items():
            name = key.partition("|")[0]
            if name == "sched_cost_model_abs_error_seconds":
                found = True
                abs_error_count += int(entry.get("n", 0))
                abs_error_sum += float(entry.get("s", 0.0))
            elif name == "master_unit_latency_seconds":
                found = True
                latency_count += int(entry.get("n", 0))
                latency_sum += float(entry.get("s", 0.0))
        for key, value in (wire.get("c") or {}).items():
            name, _, label = key.partition("|")
            if name == "sched_speculations_total":
                found = True
                outcome = label.partition("=")[2] or label or "total"
                speculations[outcome] = speculations.get(outcome, 0.0) + float(
                    value
                )
            elif name == "sched_speculations_launched_total":
                found = True
                launched += float(value)

    _consume_metric_snapshots(metrics, take_registry, take_wire)
    for snapshot in metrics:
        written_at = float(snapshot.get("written_at", 0.0))
        for section in ("prediction", "speculation"):
            view = snapshot.get(section)
            if isinstance(view, dict) and written_at >= live_at.get(section, -1.0):
                live[section] = view
                live_at[section] = written_at
                found = True
    if not found:
        return None
    out: dict[str, Any] = {}
    if abs_error_count:
        out["abs_error"] = {
            "count": abs_error_count,
            "mean_s": abs_error_sum / abs_error_count,
        }
    if latency_count:
        out["unit_latency"] = {
            "count": latency_count,
            "mean_s": latency_sum / latency_count,
        }
    if speculations or launched:
        out["speculations"] = {
            "launched": launched,
            "outcomes": speculations,
        }
    out.update(live)
    return out


def summarize_slo(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the SLO engine's evidence up (obs/slo.py).

    Two sources: the live ``slo`` section a master's cluster_view stamps
    into its snapshots (per-job attainment, burn windows, firing set, and
    the bounded alert log — newest snapshot wins, it is cumulative), and
    the ``slo_alerts_total`` registry counter (fire/clear edges per job
    and kind, summed across snapshot families). None when no snapshot
    carries either — jobs without an ``[slo]`` table get no section.
    """
    alerts_total: dict[str, float] = {}
    live: dict[str, Any] | None = None
    live_at = -1.0

    def take_registry(names: dict[str, Any]) -> bool:
        counter = names.get("slo_alerts_total")
        if not counter:
            return False
        for label, value in counter.get("series", {}).items():
            alerts_total[label or "total"] = alerts_total.get(
                label or "total", 0.0
            ) + float(value)
        return True

    def take_wire(wire: dict[str, Any]) -> None:
        for key, value in (wire.get("c") or {}).items():
            name, _, label = key.partition("|")
            if name == "slo_alerts_total":
                alerts_total[label or "total"] = alerts_total.get(
                    label or "total", 0.0
                ) + float(value)

    _consume_metric_snapshots(metrics, take_registry, take_wire)
    for snapshot in metrics:
        written_at = float(snapshot.get("written_at", 0.0))
        view = snapshot.get("slo")
        if isinstance(view, dict) and view and written_at >= live_at:
            live = view
            live_at = written_at
    if live is None and not alerts_total:
        return None
    out: dict[str, Any] = {}
    if live is not None:
        if isinstance(live.get("jobs"), dict):
            out["jobs"] = live["jobs"]
        if live.get("alerts"):
            out["alerts"] = live["alerts"]
    if alerts_total:
        out["alerts_total"] = alerts_total
    return out


def summarize_history(
    metrics: list[dict[str, Any]],
    flight_bundles: list[dict[str, Any]] | None = None,
) -> dict[str, Any] | None:
    """Roll the continuous-observability evidence up (obs/history.py +
    obs/flightrec.py).

    The ``history`` section a master stamps into its metrics snapshots
    carries per-counter increase/rate/trend and per-gauge envelopes over
    the run's sampled window — newest snapshot wins (it is cumulative
    over the retained ring). Flight-recorder bundles contribute a
    post-mortem ledger: dumps per trigger and each bundle's covered
    window. None when no snapshot carries a history section and no
    bundles exist — uninstrumented populations get no section.
    """
    live: dict[str, Any] | None = None
    live_at = -1.0
    for snapshot in metrics:
        written_at = float(snapshot.get("written_at", 0.0))
        section = snapshot.get("history")
        if isinstance(section, dict) and section and written_at >= live_at:
            live = section
            live_at = written_at
    out: dict[str, Any] = {}
    if live is not None:
        for key in (
            "interval_seconds",
            "retention_seconds",
            "samples",
            "resets_total",
            "window",
        ):
            if key in live:
                out[key] = live[key]
        # Rate trends: keep only series that actually moved — the roll-up
        # reads as "what was happening", not a registry dump.
        counters = {
            key: entry
            for key, entry in (live.get("counters") or {}).items()
            if isinstance(entry, dict) and entry.get("increase")
        }
        if counters:
            out["counters"] = counters
        if live.get("gauges"):
            out["gauges"] = live["gauges"]
    if flight_bundles:
        triggers: dict[str, int] = {}
        windows: list[dict[str, Any]] = []
        for bundle in flight_bundles:
            box = bundle.get("blackbox") or {}
            trigger = str(box.get("trigger", "unknown"))
            triggers[trigger] = triggers.get(trigger, 0) + 1
            windows.append(
                {
                    "trigger": trigger,
                    "window": box.get("window"),
                    "dumped_at": box.get("dumped_at"),
                    "path": bundle.get("path"),
                }
            )
        out["flight_bundles"] = {
            "count": len(flight_bundles),
            "triggers": triggers,
            "bundles": windows,
        }
    return out or None


def summarize_roofline(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the kernel roofline evidence up (obs/profiling.py).

    The full per-kernel view (FLOPs, bytes, executions, measured seconds,
    achieved-vs-peak placement) rides in the ``roofline`` section workers
    / the harness / bench stamp into their metrics snapshots; kernels are
    merged across snapshots with newest-wins per kernel key (each
    process's profiler is cumulative). The registry-only gauge family
    (``render_kernel_*``) additionally contributes FLOPs/bytes/achieved
    rows for kernels whose process exported metrics but no stamped
    section (e.g. a heartbeat-wire-only worker). None when nothing was
    profiled.
    """
    kernels: dict[str, dict[str, Any]] = {}
    kernel_at: dict[str, float] = {}
    peaks: dict[str, Any] | None = None
    peaks_at = -1.0
    gauge_rows: dict[str, dict[str, float]] = {}

    def _fold_gauge(names: dict[str, Any], metric: str, field: str) -> bool:
        entry = names.get(metric)
        if not entry:
            return False
        for label, value in entry.get("series", {}).items():
            kernel = label.partition("=")[2] or label
            gauge_rows.setdefault(kernel, {})[field] = float(value)
        return True

    def take_registry(names: dict[str, Any]) -> bool:
        took = False
        for metric, field in (
            ("render_kernel_flops", "flops"),
            ("render_kernel_bytes", "bytes_accessed"),
            (
                "render_kernel_achieved_flops_per_second",
                "achieved_flops_per_second",
            ),
        ):
            took = _fold_gauge(names, metric, field) or took
        return took

    def take_wire(wire: dict[str, Any]) -> None:
        for key, value in (wire.get("g") or {}).items():
            name, _, label = key.partition("|")
            kernel = label.partition("=")[2] or label
            if name == "render_kernel_flops":
                gauge_rows.setdefault(kernel, {})["flops"] = float(value)
            elif name == "render_kernel_bytes":
                gauge_rows.setdefault(kernel, {})["bytes_accessed"] = float(value)
            elif name == "render_kernel_achieved_flops_per_second":
                gauge_rows.setdefault(kernel, {})[
                    "achieved_flops_per_second"
                ] = float(value)

    _consume_metric_snapshots(metrics, take_registry, take_wire)
    for snapshot in metrics:
        written_at = float(snapshot.get("written_at", 0.0))
        section = snapshot.get("roofline")
        if not isinstance(section, dict):
            continue
        if isinstance(section.get("peaks"), dict) and written_at >= peaks_at:
            peaks = section["peaks"]
            peaks_at = written_at
        for kernel, entry in (section.get("kernels") or {}).items():
            if isinstance(entry, dict) and written_at >= kernel_at.get(
                kernel, -1.0
            ):
                kernels[kernel] = entry
                kernel_at[kernel] = written_at
    # Gauge-only kernels (no stamped section covered them) still get a row.
    for kernel, fields in gauge_rows.items():
        if kernel not in kernels:
            kernels[kernel] = dict(fields)
    if not kernels:
        return None
    out: dict[str, Any] = {"kernels": kernels}
    if peaks is not None:
        out["peaks"] = peaks
    return out


def _parse_series_labels(label: str) -> dict[str, str]:
    """Registry series key (``"tag=ping,direction=send"``) -> labels."""
    labels: dict[str, str] = {}
    for part in label.split(","):
        name, sep, value = part.partition("=")
        if sep:
            labels[name] = value
    return labels


def summarize_attribution(
    metrics: list[dict[str, Any]],
    critical_sections: dict[str, Any] | None = None,
    *,
    worker_seconds: float | None = None,
) -> dict[str, Any] | None:
    """Roll the whole-stack time-attribution inputs up and partition them.

    Extracts the tick-phase histogram (``sched_tick_seconds``,
    sched/tickprof.py), the loop-lag families (``obs_loop_lag_seconds``
    + ``obs_loop_blocked_episodes_total``, obs/loopmon.py), the wire
    accounting families (``transport_serialize_seconds`` +
    ``transport_message_bytes_total``, transport/wirecost.py), and the
    roofline execute totals, then hands them to
    ``analysis/attribution.attribution_report`` against the per-worker
    busy/idle windows in ``critical_sections`` (or the explicit
    ``worker_seconds`` denominator). Same snapshot-family handling as
    every other summarize_*: registry forms first, the compact wire form
    only for files no registry snapshot covered. None when no snapshot
    carries any attribution series.
    """
    found = False
    tick_phases: dict[str, dict[str, float]] = {}
    lag_roles: dict[str, dict[str, float]] = {}
    episode_roles: dict[str, float] = {}
    talker_rows: dict[str, dict[str, float]] = {}
    transport_s = 0.0

    def take_tick(phase: str, count: float, total: float) -> None:
        nonlocal found
        found = True
        entry = tick_phases.setdefault(phase, {"count": 0.0, "sum_s": 0.0})
        entry["count"] += count
        entry["sum_s"] += total

    def take_lag(role: str, count: float, total: float, peak: float) -> None:
        nonlocal found
        found = True
        entry = lag_roles.setdefault(
            role, {"samples": 0.0, "sum_s": 0.0, "max_s": 0.0}
        )
        entry["samples"] += count
        entry["sum_s"] += total
        entry["max_s"] = max(entry["max_s"], peak)

    def take_wire_bytes(labels: dict[str, str], value: float) -> None:
        nonlocal found
        found = True
        tag = labels.get("tag", "?")
        row = talker_rows.setdefault(
            tag, {"bytes": 0.0, "send_bytes": 0.0, "recv_bytes": 0.0,
                  "serialize_s": 0.0}
        )
        row["bytes"] += value
        direction = labels.get("direction")
        if direction == "send":
            row["send_bytes"] += value
        elif direction == "recv":
            row["recv_bytes"] += value

    def take_serialize(labels: dict[str, str], total: float) -> None:
        nonlocal found, transport_s
        found = True
        transport_s += total
        tag = labels.get("tag", "?")
        row = talker_rows.setdefault(
            tag, {"bytes": 0.0, "send_bytes": 0.0, "recv_bytes": 0.0,
                  "serialize_s": 0.0}
        )
        row["serialize_s"] += total

    def take_registry(names: dict[str, Any]) -> bool:
        nonlocal found
        took = False
        histogram = names.get("sched_tick_seconds")
        if histogram:
            took = True
            for label, series in histogram.get("series", {}).items():
                take_tick(
                    label.partition("=")[2] or label,
                    float(series.get("count", 0)),
                    float(series.get("sum", 0.0)),
                )
        histogram = names.get("obs_loop_lag_seconds")
        if histogram:
            took = True
            for label, series in histogram.get("series", {}).items():
                take_lag(
                    label.partition("=")[2] or label,
                    float(series.get("count", 0)),
                    float(series.get("sum", 0.0)),
                    float(series.get("max", 0.0) or 0.0),
                )
        counter = names.get("obs_loop_blocked_episodes_total")
        if counter:
            found = took = True
            for label, value in counter.get("series", {}).items():
                role = label.partition("=")[2] or label
                episode_roles[role] = episode_roles.get(role, 0.0) + float(value)
        counter = names.get("transport_message_bytes_total")
        if counter:
            took = True
            for label, value in counter.get("series", {}).items():
                take_wire_bytes(_parse_series_labels(label), float(value))
        histogram = names.get("transport_serialize_seconds")
        if histogram:
            took = True
            for label, series in histogram.get("series", {}).items():
                take_serialize(
                    _parse_series_labels(label), float(series.get("sum", 0.0))
                )
        return took

    def take_wire(wire: dict[str, Any]) -> None:
        nonlocal found
        for key, entry in (wire.get("h") or {}).items():
            name, _, label = key.partition("|")
            if name == "sched_tick_seconds":
                take_tick(
                    label.partition("=")[2] or label,
                    float(entry.get("n", 0)),
                    float(entry.get("s", 0.0)),
                )
            elif name == "obs_loop_lag_seconds":
                take_lag(
                    label.partition("=")[2] or label,
                    float(entry.get("n", 0)),
                    float(entry.get("s", 0.0)),
                    float(entry.get("max", 0.0) or 0.0),
                )
            elif name == "transport_serialize_seconds":
                take_serialize(
                    _parse_series_labels(label), float(entry.get("s", 0.0))
                )
        for key, value in (wire.get("c") or {}).items():
            name, _, label = key.partition("|")
            if name == "obs_loop_blocked_episodes_total":
                found = True
                role = label.partition("=")[2] or label
                episode_roles[role] = episode_roles.get(role, 0.0) + float(value)
            elif name == "transport_message_bytes_total":
                take_wire_bytes(_parse_series_labels(label), float(value))

    _consume_metric_snapshots(metrics, take_registry, take_wire)
    if not found:
        return None

    device_s = 0.0
    roofline = summarize_roofline(metrics)
    if roofline:
        for entry in roofline.get("kernels", {}).values():
            device_s += float(entry.get("execute_seconds_total", 0.0) or 0.0)

    # The tick's dispatch phase already spans its in-tick RPC awaits; the
    # off-tick dispatch_rpc_await/dispatch_serialize observations only
    # price the control plane when no scheduler loop ran (single-job).
    control_s = tick_phases.get("total", {}).get("sum_s", 0.0)
    if control_s <= 0.0:
        control_s = sum(
            entry["sum_s"]
            for phase, entry in tick_phases.items()
            if phase in ("dispatch_rpc_await", "dispatch_serialize")
        )

    loop_lag: dict[str, Any] = {}
    for role, entry in sorted(lag_roles.items()):
        samples = entry["samples"]
        loop_lag[role] = {
            "samples": int(samples),
            "mean_lag_s": (entry["sum_s"] / samples) if samples else 0.0,
            "max_lag_s": entry["max_s"],
            "blocked_episodes": int(episode_roles.get(role, 0.0)),
        }
    for role, count in sorted(episode_roles.items()):
        loop_lag.setdefault(
            role,
            {"samples": 0, "mean_lag_s": 0.0, "max_lag_s": 0.0,
             "blocked_episodes": int(count)},
        )

    top = [
        {"tag": tag, **{k: row[k] for k in
                        ("bytes", "send_bytes", "recv_bytes", "serialize_s")}}
        for tag, row in talker_rows.items()
    ]
    top.sort(key=lambda row: row["bytes"], reverse=True)

    from tpu_render_cluster.analysis.attribution import attribution_report

    tick_section: dict[str, Any] | None = None
    if tick_phases:
        tick_section = {
            "ticks": int(tick_phases.get("total", {}).get("count", 0)),
            "phases": {
                phase: {"count": int(entry["count"]),
                        "sum_s": round(entry["sum_s"], 6)}
                for phase, entry in sorted(tick_phases.items())
            },
        }
    return attribution_report(
        critical_sections=critical_sections,
        worker_seconds=worker_seconds,
        device_seconds=device_s,
        transport_seconds=transport_s,
        control_seconds=control_s,
        tick=tick_section,
        loop_lag=loop_lag or None,
        top_talkers=top[:8] or None,
    )


_CHAOS_LEDGER_COUNTERS = (
    "master_frame_results_total",
    "master_duplicate_results_total",
    "master_late_results_total",
    "master_stale_results_total",
    "master_worker_evictions_total",
    "master_worker_drains_total",
)


def accumulate_chaos_fault_counts(
    registry_snapshot: dict[str, Any], into: dict[str, float]
) -> dict[str, float]:
    """Fold one registry snapshot's ``chaos_faults_injected_total`` series
    into ``into`` keyed by fault kind. Single definition site — the chaos
    runner's live report and this module's statistics.json section must
    parse the series labels identically."""
    entry = registry_snapshot.get("chaos_faults_injected_total")
    if entry:
        for label, value in entry.get("series", {}).items():
            kind = label.partition("=")[2] or label
            into[kind] = into.get(kind, 0.0) + float(value)
    return into


def summarize_chaos(metrics: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Roll the fault-injection evidence up (chaos/ engine artifacts).

    Aggregates ``chaos_faults_injected_total`` (what was done to the
    cluster) across every registry family a snapshot carries, plus the
    master's exactly-once ledger counters (what the cluster did about it).
    None when no snapshot shows any injected fault — ordinary runs get no
    ``chaos`` section even though the ledger counters exist.
    """
    faults: dict[str, float] = {}
    ledger: dict[str, dict[str, float]] = {}

    def take_registry(names: dict[str, Any]) -> None:
        accumulate_chaos_fault_counts(names, faults)
        for counter in _CHAOS_LEDGER_COUNTERS:
            counter_entry = names.get(counter)
            if not counter_entry:
                continue
            sink = ledger.setdefault(counter, {})
            for label, value in counter_entry.get("series", {}).items():
                sink[label or "total"] = sink.get(label or "total", 0.0) + float(
                    value
                )

    for snapshot in metrics:
        take_registry(snapshot.get("metrics", {}))
        for worker_registry in (snapshot.get("workers") or {}).values():
            if isinstance(worker_registry, dict):
                take_registry(worker_registry)
    if not faults:
        return None
    return {"faults_injected": faults, "ledger": ledger}


def summarize_obs(
    traces: list[ObsTrace],
    metrics: list[dict[str, Any]],
    cluster_traces: list[ObsTrace] | None = None,
    flight_bundles: list[dict[str, Any]] | None = None,
) -> dict[str, Any]:
    """Roll obs artifacts into a ``statistics.json``-shaped summary.

    ``cluster_traces`` (the merged clock-corrected timelines from
    ``load_cluster_traces``) additionally contribute a ``critical_path``
    section — per-run makespan critical path, per-worker idle attribution,
    and straggler scores (``analysis/critical_path.py``) — keyed by the
    run's file stem. ``flight_bundles`` (``load_blackbox_bundles``) fold
    into the ``history`` section's post-mortem ledger.
    """
    span_counts: dict[str, int] = {}
    durations: dict[str, list[float]] = {}
    for trace in traces:
        for cat, count in trace.span_count_by_category().items():
            span_counts[cat] = span_counts.get(cat, 0) + count
        for name, values in trace.span_seconds_by_name().items():
            durations.setdefault(name, []).extend(values)
    span_stats = {}
    for name, values in sorted(durations.items()):
        values = sorted(values)
        span_stats[name] = {
            "count": len(values),
            "total_s": sum(values),
            "p50_s": _percentile(values, 0.50),
            "p95_s": _percentile(values, 0.95),
            "max_s": values[-1],
        }
    out: dict[str, Any] = {
        "trace_event_files": len(traces),
        "metrics_snapshot_files": len(metrics),
        "spans_by_category": span_counts,
        "span_duration_stats": span_stats,
    }
    wavefront = summarize_wavefront(metrics)
    if wavefront is not None:
        out["wavefront"] = wavefront
    raypool = summarize_raypool(metrics)
    if raypool is not None:
        out["raypool"] = raypool
    chaos = summarize_chaos(metrics)
    if chaos is not None:
        out["chaos"] = chaos
    tiles = summarize_tiles(metrics)
    if tiles is not None:
        out["tiles"] = tiles
    sched = summarize_sched(metrics)
    if sched is not None:
        out["sched"] = sched
    prediction = summarize_prediction(metrics)
    if prediction is not None:
        out["prediction"] = prediction
    slo = summarize_slo(metrics)
    if slo is not None:
        out["slo"] = slo
    history = summarize_history(metrics, flight_bundles)
    if history is not None:
        out["history"] = history
    roofline = summarize_roofline(metrics)
    if roofline is not None:
        out["roofline"] = roofline
    if cluster_traces:
        from tpu_render_cluster.analysis.critical_path import (
            summarize_critical_path,
        )

        sections = {}
        for trace in cluster_traces:
            section = summarize_critical_path(trace.events)
            if section is not None:
                sections[trace.path.stem] = section
        if sections:
            out["critical_path"] = sections
    attribution = summarize_attribution(
        metrics, out.get("critical_path")
    )
    if attribution is not None:
        out["attribution"] = attribution
    return out
