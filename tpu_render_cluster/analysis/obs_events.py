"""Loaders for the obs subsystem's artifacts (reference has no analog).

Two new file families land next to the legacy ``*_raw-trace.json``:

- ``*_trace-events.json`` — Chrome trace-event JSON (Perfetto-loadable)
  with master / worker / transport spans;
- ``*_metrics.json`` — metrics registry snapshots (+ the cluster view and
  per-worker heartbeat payload aggregation).

This module validates and loads both so ``run_all`` can fold live-signal
summaries (per-phase span statistics, span counts by category) into
``statistics.json`` alongside the legacy post-hoc metrics.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable

TRACE_EVENTS_GLOB = "*_trace-events.json"
METRICS_SNAPSHOT_GLOB = "*_metrics.json"


def find_trace_event_files(results_directory: str | Path) -> list[Path]:
    return sorted(Path(results_directory).rglob(TRACE_EVENTS_GLOB))


def find_metrics_files(results_directory: str | Path) -> list[Path]:
    return sorted(Path(results_directory).rglob(METRICS_SNAPSHOT_GLOB))


@dataclass(frozen=True)
class ObsTrace:
    """One loaded trace-event file."""

    path: Path
    events: list[dict[str, Any]]

    def spans(self) -> list[dict[str, Any]]:
        """Complete ('X') events only — the duration-carrying spans."""
        return [e for e in self.events if e.get("ph") == "X"]

    def span_seconds_by_name(self) -> dict[str, list[float]]:
        out: dict[str, list[float]] = {}
        for event in self.spans():
            out.setdefault(str(event.get("name")), []).append(
                float(event.get("dur", 0.0)) / 1e6
            )
        return out

    def span_count_by_category(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for event in self.spans():
            cat = str(event.get("cat", "default"))
            out[cat] = out.get(cat, 0) + 1
        return out


def load_trace_events(path: str | Path) -> ObsTrace:
    """Load + validate one Chrome trace-event file.

    Accepts both container formats the viewers accept: the JSON Object
    Format (``{"traceEvents": [...]}`` — what this repo writes) and the
    bare JSON Array Format.
    """
    path = Path(path)
    data = json.loads(path.read_text(encoding="utf-8"))
    if isinstance(data, dict):
        events = data.get("traceEvents")
    elif isinstance(data, list):
        events = data
    else:
        raise ValueError(f"{path}: not a Chrome trace-event document")
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents must be a list")
    for event in events:
        if not isinstance(event, dict) or "ph" not in event:
            raise ValueError(f"{path}: malformed trace event: {event!r}")
        if event["ph"] == "X" and ("ts" not in event or "dur" not in event):
            raise ValueError(f"{path}: complete event missing ts/dur: {event!r}")
    return ObsTrace(path=path, events=events)


def load_metrics_snapshot(path: str | Path) -> dict[str, Any]:
    """Load + validate one metrics snapshot file."""
    path = Path(path)
    data = json.loads(path.read_text(encoding="utf-8"))
    if not isinstance(data, dict) or "metrics" not in data:
        raise ValueError(f"{path}: not a metrics snapshot (missing 'metrics')")
    if not isinstance(data["metrics"], dict):
        raise ValueError(f"{path}: 'metrics' must be an object")
    return data


def load_obs_artifacts(
    results_directory: str | Path,
    *,
    on_error: "Callable[[Path, Exception], None] | None" = None,
) -> tuple[list[ObsTrace], list[dict[str, Any]]]:
    """Load every obs artifact under a results directory (both families).

    With ``on_error`` set, a malformed file is reported to it and skipped
    so one bad artifact doesn't discard the rest of the population;
    without it, the first malformed file raises.
    """
    traces: list[ObsTrace] = []
    metrics: list[dict[str, Any]] = []
    for loader, sink, paths in (
        (load_trace_events, traces, find_trace_event_files(results_directory)),
        (load_metrics_snapshot, metrics, find_metrics_files(results_directory)),
    ):
        for path in paths:
            try:
                sink.append(loader(path))
            except (ValueError, OSError, json.JSONDecodeError) as e:
                if on_error is None:
                    raise
                on_error(path, e)
    return traces, metrics


def _percentile(sorted_values: list[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    index = min(len(sorted_values) - 1, int(round(q * (len(sorted_values) - 1))))
    return sorted_values[index]


def summarize_obs(
    traces: list[ObsTrace], metrics: list[dict[str, Any]]
) -> dict[str, Any]:
    """Roll obs artifacts into a ``statistics.json``-shaped summary."""
    span_counts: dict[str, int] = {}
    durations: dict[str, list[float]] = {}
    for trace in traces:
        for cat, count in trace.span_count_by_category().items():
            span_counts[cat] = span_counts.get(cat, 0) + count
        for name, values in trace.span_seconds_by_name().items():
            durations.setdefault(name, []).extend(values)
    span_stats = {}
    for name, values in sorted(durations.items()):
        values = sorted(values)
        span_stats[name] = {
            "count": len(values),
            "total_s": sum(values),
            "p50_s": _percentile(values, 0.50),
            "p95_s": _percentile(values, 0.95),
            "max_s": values[-1],
        }
    return {
        "trace_event_files": len(traces),
        "metrics_snapshot_files": len(metrics),
        "spans_by_category": span_counts,
        "span_duration_stats": span_stats,
    }
