"""Critical-path and straggler analysis over the merged cluster timeline.

The merged ``*_cluster_trace-events.json`` (obs/timeline.py) puts every
frame's full lifecycle on ONE clock: the master's ``assign frame`` /
``frame result`` spans and each worker's ``queue_wait``/``read``/
``render``/``write`` phase spans, joined by the assignment's flow id.
That is enough to answer the questions per-process artifacts cannot:

- **Critical path**: which chain of spans actually gated the job's
  makespan? Worker queues are serial, so a frame's processing starts at
  ``max(assignment done, previous frame's processing end)``; walking back
  from the last-finishing frame along whichever of those two gated it
  yields the makespan-covering chain, attributed per phase and worker.
- **Idle attribution**: per worker, wall time inside the job window not
  covered by any frame's processing (read/render/write) — the capacity
  the scheduler failed to use.
- **Straggler scores**: each worker's median per-frame processing time
  against the cluster median (score > 1 means slower than the cluster),
  with per-phase percentiles to show WHERE the straggler loses time.

``summarize_critical_path`` is the ``statistics.json``-shaped roll-up
``analysis/obs_events.summarize_obs`` folds in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable

# Shared nearest-rank percentile (obs_events only imports THIS module
# lazily, so the top-level import is cycle-free).
from tpu_render_cluster.analysis.obs_events import _percentile

__all__ = [
    "FrameLifecycle",
    "extract_lifecycles",
    "compute_critical_path",
    "worker_utilization",
    "straggler_scores",
    "tile_statistics",
    "summarize_critical_path",
]

PHASES = ("queue_wait", "read", "render", "write")
PROCESSING_PHASES = ("read", "render", "write")

# Two spans "touch" (one gated the other) when the gap between them is
# below this: covers event-loop scheduling jitter between a frame's write
# end and the next frame's read start on a serial worker queue.
CHAIN_GAP_SECONDS = 0.050


@dataclass
class FrameLifecycle:
    """One work-unit ASSIGNMENT's reconstructed spans (seconds, master
    clock). ``tile`` is None for whole-frame units; tiled jobs yield one
    lifecycle per (frame, tile) assignment."""

    frame: int
    flow: str | None
    tile: int | None = None
    worker: str | None = None
    assign: tuple[float, float] | None = None
    phases: dict[str, tuple[float, float]] = field(default_factory=dict)
    result_at: float | None = None
    result: str | None = None

    @property
    def processing_start(self) -> float | None:
        starts = [self.phases[p][0] for p in PROCESSING_PHASES if p in self.phases]
        return min(starts) if starts else None

    @property
    def processing_end(self) -> float | None:
        ends = [self.phases[p][1] for p in PROCESSING_PHASES if p in self.phases]
        return max(ends) if ends else None

    @property
    def processing_seconds(self) -> float | None:
        return sum(
            (self.phases[p][1] - self.phases[p][0]
             for p in PROCESSING_PHASES if p in self.phases),
            0.0,
        ) if self.processing_start is not None else None

    @property
    def end(self) -> float | None:
        candidates = [self.result_at, self.processing_end]
        candidates = [c for c in candidates if c is not None]
        return max(candidates) if candidates else None


def _process_names(events: Iterable[dict[str, Any]]) -> dict[Any, str]:
    names: dict[Any, str] = {}
    for event in events:
        if event.get("ph") == "M" and event.get("name") == "process_name":
            names[event.get("pid")] = str((event.get("args") or {}).get("name"))
    return names


def extract_lifecycles(events: list[dict[str, Any]]) -> list[FrameLifecycle]:
    """Group the timeline's frame spans into per-assignment lifecycles.

    Spans join on the assignment's flow id when present (exact across
    re-queues and steals); spans without one — a worker predating trace
    context — fall back to joining on the frame index alone.
    """
    names = _process_names(events)
    lifecycles: dict[Any, FrameLifecycle] = {}

    def lifecycle_for(event: dict[str, Any]) -> FrameLifecycle | None:
        args = event.get("args") or {}
        frame = args.get("frame")
        if frame is None:
            return None
        flow = args.get("flow")
        tile = args.get("tile")
        tile = None if tile is None else int(tile)
        key = flow if flow is not None else ("frame", frame, tile)
        lc = lifecycles.get(key)
        if lc is None:
            lc = lifecycles[key] = FrameLifecycle(
                frame=int(frame), flow=flow, tile=tile
            )
        return lc

    for event in events:
        if event.get("ph") != "X":
            continue
        name = event.get("name")
        start = float(event.get("ts", 0.0)) / 1e6
        end = start + float(event.get("dur", 0.0)) / 1e6
        if name == "assign frame":
            lc = lifecycle_for(event)
            if lc is not None:
                lc.assign = (start, end)
        elif name in ("frame result", "frame stolen"):
            lc = lifecycle_for(event)
            if lc is not None:
                lc.result_at = end
                lc.result = (event.get("args") or {}).get("result")
        elif name in PHASES:
            lc = lifecycle_for(event)
            if lc is not None:
                lc.phases[name] = (start, end)
                worker = names.get(event.get("pid"))
                if worker is not None:
                    lc.worker = worker
    return list(lifecycles.values())


def compute_critical_path(
    lifecycles: list[FrameLifecycle],
) -> list[dict[str, Any]]:
    """Walk the makespan-gating chain back from the last-finishing frame.

    Returns segments in forward time order; each is
    ``{kind, frame, worker, start_s, end_s, duration_s}`` where ``kind``
    is a phase name, ``assign`` (the master-side RPC), or ``wait``
    (a gap on the path nobody's span covers — master think time).
    """
    candidates = [lc for lc in lifecycles if lc.end is not None]
    if not candidates:
        return []
    by_worker: dict[Any, list[FrameLifecycle]] = {}
    for lc in candidates:
        if lc.processing_end is not None:
            by_worker.setdefault(lc.worker, []).append(lc)
    for chains in by_worker.values():
        chains.sort(key=lambda lc: lc.processing_end)

    segments: list[dict[str, Any]] = []

    def add(kind: str, lc: FrameLifecycle | None, start: float, end: float) -> None:
        if end <= start:
            return
        segments.append(
            {
                "kind": kind,
                "frame": lc.frame if lc is not None else None,
                "worker": lc.worker if lc is not None else None,
                "start_s": start,
                "end_s": end,
                "duration_s": end - start,
            }
        )

    current: FrameLifecycle | None = max(candidates, key=lambda lc: lc.end)
    seen: set[int] = set()
    terminal = True
    while current is not None and id(current) not in seen:
        seen.add(id(current))
        # Only the LAST-finishing frame's result-received hop is on the
        # path; intermediate chained frames were gating through their
        # worker's serial queue, not through the master's receipt.
        if (
            terminal
            and current.result_at is not None
            and current.processing_end is not None
        ):
            add("result", current, current.processing_end, current.result_at)
        terminal = False
        for phase in reversed(PROCESSING_PHASES):
            if phase in current.phases:
                start, end = current.phases[phase]
                add(phase, current, start, end)
        proc_start = current.processing_start
        if proc_start is None:
            break
        # What gated this frame's processing start: the previous frame on
        # the same serial worker queue, or the master's assignment?
        previous = None
        for lc in by_worker.get(current.worker, ()):
            if lc is current:
                continue
            if lc.processing_end <= proc_start + CHAIN_GAP_SECONDS and (
                previous is None or lc.processing_end > previous.processing_end
            ):
                previous = lc
        if (
            previous is not None
            and proc_start - previous.processing_end <= CHAIN_GAP_SECONDS
        ):
            current = previous
            continue
        # Master-gated: the frame sat queued (or the worker sat empty)
        # until the assignment landed.
        if current.assign is not None:
            assign_start, assign_end = current.assign
            add("wait", current, assign_end, proc_start)
            add("assign", current, assign_start, assign_end)
        break
    segments.reverse()
    return segments


def worker_utilization(
    lifecycles: list[FrameLifecycle],
) -> tuple[tuple[float, float] | None, dict[str, dict[str, float]]]:
    """Job window + per-worker busy/idle split inside it.

    Busy is the union of each frame's processing interval (read through
    write) on that worker; idle is the window remainder — time the worker
    existed but rendered nothing (queue starvation, barrier waits, tail).
    """
    starts = [lc.assign[0] for lc in lifecycles if lc.assign is not None]
    starts += [s for lc in lifecycles if (s := lc.processing_start) is not None]
    ends = [e for lc in lifecycles if (e := lc.end) is not None]
    if not starts or not ends:
        return None, {}
    window = (min(starts), max(ends))
    window_seconds = window[1] - window[0]
    out: dict[str, dict[str, float]] = {}
    intervals_by_worker: dict[str, list[tuple[float, float]]] = {}
    for lc in lifecycles:
        if lc.worker is None or lc.processing_start is None:
            continue
        intervals_by_worker.setdefault(lc.worker, []).append(
            (lc.processing_start, lc.processing_end)
        )
    for worker, intervals in intervals_by_worker.items():
        intervals.sort()
        busy = 0.0
        cursor = window[0]
        for start, end in intervals:
            start = max(start, cursor)
            if end > start:
                busy += end - start
                cursor = end
        out[worker] = {
            "frames": float(len(intervals)),
            "busy_s": busy,
            "idle_s": max(0.0, window_seconds - busy),
            "idle_fraction": (
                max(0.0, window_seconds - busy) / window_seconds
                if window_seconds > 0
                else 0.0
            ),
        }
    return window, out


def straggler_scores(
    lifecycles: list[FrameLifecycle],
) -> dict[str, dict[str, Any]]:
    """Per-worker phase percentiles vs the cluster distribution.

    ``score`` is the worker's median per-frame processing time over the
    cluster median: 1.0 is a typical worker, 2.0 renders frames twice as
    slowly as the cluster's midpoint. Phase percentiles localize the loss
    (slow read = I/O, slow render = compute, slow write = storage).
    """
    per_worker_processing: dict[str, list[float]] = {}
    per_worker_phase: dict[str, dict[str, list[float]]] = {}
    cluster_processing: list[float] = []
    for lc in lifecycles:
        if lc.worker is None:
            continue
        seconds = lc.processing_seconds
        if seconds is None:
            continue
        per_worker_processing.setdefault(lc.worker, []).append(seconds)
        cluster_processing.append(seconds)
        phases = per_worker_phase.setdefault(lc.worker, {})
        for phase in PHASES:
            if phase in lc.phases:
                start, end = lc.phases[phase]
                phases.setdefault(phase, []).append(end - start)
    cluster_processing.sort()
    cluster_p50 = _percentile(cluster_processing, 0.50)
    out: dict[str, dict[str, Any]] = {}
    for worker, values in per_worker_processing.items():
        values.sort()
        p50 = _percentile(values, 0.50)
        phase_p50 = {}
        phase_p95 = {}
        for phase, durations in per_worker_phase[worker].items():
            durations.sort()
            phase_p50[phase] = _percentile(durations, 0.50)
            phase_p95[phase] = _percentile(durations, 0.95)
        out[worker] = {
            "frames": len(values),
            "processing_p50_s": p50,
            "processing_p95_s": _percentile(values, 0.95),
            "straggler_score": (p50 / cluster_p50) if cluster_p50 > 0 else 1.0,
            "phase_p50_s": phase_p50,
            "phase_p95_s": phase_p95,
        }
    return out


def tile_statistics(
    lifecycles: list[FrameLifecycle],
) -> dict[str, Any] | None:
    """Per-tile lifecycles rolled up: tile straggler scores + the
    per-frame ASSEMBLY WAIT the master pays holding a frame's finished
    tiles until its straggler tile lands.

    - ``per_tile``: each tile index's median processing time against the
      cluster median over all tiled units (score > 1 = that grid cell is
      systematically slower — e.g. the scene's geometry concentrates
      there), plus its assignment count.
    - ``assembly``: per frame, wait = last tile end - first tile end
      (what completed tiles waited on the straggler). The TERMINAL
      frame's wait sits on the makespan-gating chain by construction —
      reported as ``terminal_frame_wait_s``.

    None when the timeline carries no tiled units.
    """
    tiled = [lc for lc in lifecycles if lc.tile is not None]
    if not tiled:
        return None
    per_tile_processing: dict[int, list[float]] = {}
    cluster: list[float] = []
    for lc in tiled:
        seconds = lc.processing_seconds
        if seconds is None:
            continue
        per_tile_processing.setdefault(lc.tile, []).append(seconds)
        cluster.append(seconds)
    cluster.sort()
    cluster_p50 = _percentile(cluster, 0.50) if cluster else 0.0
    per_tile: dict[str, dict[str, Any]] = {}
    for tile, values in sorted(per_tile_processing.items()):
        values.sort()
        p50 = _percentile(values, 0.50)
        per_tile[str(tile)] = {
            "units": len(values),
            "processing_p50_s": p50,
            "straggler_score": (p50 / cluster_p50) if cluster_p50 > 0 else 1.0,
        }
    # Assembly wait per frame: the spread of the frame's tile end times.
    ends_by_frame: dict[int, list[float]] = {}
    for lc in tiled:
        end = lc.end
        if end is not None:
            ends_by_frame.setdefault(lc.frame, []).append(end)
    waits = {
        frame: max(ends) - min(ends)
        for frame, ends in ends_by_frame.items()
        if len(ends) > 1
    }
    sorted_waits = sorted(waits.values())
    terminal_frame = (
        max(ends_by_frame, key=lambda f: max(ends_by_frame[f]))
        if ends_by_frame
        else None
    )
    return {
        "units": len(tiled),
        "tiles_seen": len(per_tile_processing),
        "per_tile": per_tile,
        "tile_stragglers": sorted(
            per_tile, key=lambda t: per_tile[t]["straggler_score"], reverse=True
        ),
        "assembly": {
            "frames": len(waits),
            "wait_mean_s": (
                sum(sorted_waits) / len(sorted_waits) if sorted_waits else 0.0
            ),
            "wait_p95_s": _percentile(sorted_waits, 0.95) if sorted_waits else 0.0,
            "wait_max_s": sorted_waits[-1] if sorted_waits else 0.0,
            # The last-finishing frame's wait gates the makespan: its
            # earlier tiles were DONE while the chain walked through the
            # straggler tile.
            "terminal_frame": terminal_frame,
            "terminal_frame_wait_s": (
                waits.get(terminal_frame, 0.0)
                if terminal_frame is not None
                else 0.0
            ),
        },
    }


def summarize_critical_path(events: list[dict[str, Any]]) -> dict[str, Any] | None:
    """The ``statistics.json`` roll-up for one merged cluster timeline.

    None when the timeline carries no frame lifecycles (an uninstrumented
    or non-cluster trace file).
    """
    lifecycles = extract_lifecycles(events)
    if not any(lc.phases or lc.assign for lc in lifecycles):
        return None
    window, utilization = worker_utilization(lifecycles)
    segments = compute_critical_path(lifecycles)
    scores = straggler_scores(lifecycles)
    workers: dict[str, dict[str, Any]] = {}
    for worker, entry in scores.items():
        workers[worker] = dict(entry)
    for worker, entry in utilization.items():
        workers.setdefault(worker, {}).update(
            {k: v for k, v in entry.items() if k != "frames"}
        )
    by_kind: dict[str, float] = {}
    by_worker: dict[str, float] = {}
    for segment in segments:
        by_kind[segment["kind"]] = (
            by_kind.get(segment["kind"], 0.0) + segment["duration_s"]
        )
        if segment["worker"] is not None:
            by_worker[segment["worker"]] = (
                by_worker.get(segment["worker"], 0.0) + segment["duration_s"]
            )
    tiles = tile_statistics(lifecycles)
    out: dict[str, Any] = {
        "frames": len([lc for lc in lifecycles if lc.phases]),
        "assignments": len(lifecycles),
        "makespan_s": (window[1] - window[0]) if window is not None else 0.0,
        "critical_path": {
            "segments": segments,
            "total_s": sum(s["duration_s"] for s in segments),
            "seconds_by_kind": by_kind,
            "seconds_by_worker": by_worker,
        },
        "workers": workers,
        "stragglers": sorted(
            scores, key=lambda w: scores[w]["straggler_score"], reverse=True
        ),
    }
    if tiles is not None:
        out["tiles"] = tiles
    return out
