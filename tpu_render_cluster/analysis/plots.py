"""Plot generation for the A5-A12 metrics (reference: analysis/*.py plots).

Matplotlib with the Agg backend; each function writes one PNG and returns
its path. Axis conventions follow the reference where they matter
(utilization emphasised on [0.95, 1.0], latency on [0, 5] ms, scaled tail
delay on [0, 2] — reference: worker_utilization.py:154-157,
worker_latency.py:129-132, job_tail_delay.py).
"""

from __future__ import annotations

from collections import defaultdict
from pathlib import Path

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from tpu_render_cluster.analysis.models import JobTrace  # noqa: E402
from tpu_render_cluster.analysis import metrics as M  # noqa: E402


def _strategy_groups(traces: list[JobTrace]):
    groups = defaultdict(list)
    for trace in traces:
        groups[trace.strategy_type()].append(trace)
    return groups


def plot_worker_utilization(traces: list[JobTrace], output_directory: Path) -> Path:
    """Boxplots of per-worker utilization vs cluster size, per strategy."""
    output_directory.mkdir(parents=True, exist_ok=True)
    groups = _strategy_groups(traces)
    fig, axes = plt.subplots(
        1, max(len(groups), 1), figsize=(5 * max(len(groups), 1), 4), squeeze=False
    )
    for axis, (strategy, strategy_traces) in zip(axes[0], sorted(groups.items())):
        by_size = defaultdict(list)
        for trace in strategy_traces:
            for u in M.worker_utilizations(trace):
                by_size[trace.cluster_size()].append(u.utilization)
        sizes = sorted(by_size)
        axis.boxplot([by_size[s] for s in sizes], tick_labels=[str(s) for s in sizes])
        axis.set_title(f"Utilization — {strategy}")
        axis.set_xlabel("cluster size")
        axis.set_ylabel("utilization")
        axis.set_ybound(0.0, 1.02)
    path = output_directory / "worker_utilization.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_speedup_and_efficiency(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.speedup_stats(traces)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    strategies = sorted({key[1] for key in stats})
    sizes = sorted({key[0] for key in stats})
    width = 0.8 / max(len(strategies), 1)
    for i, strategy in enumerate(strategies):
        xs, speedups, efficiencies = [], [], []
        for j, size in enumerate(sizes):
            if (size, strategy) in stats:
                xs.append(j + i * width)
                speedups.append(stats[(size, strategy)]["speedup"])
                efficiencies.append(stats[(size, strategy)]["efficiency"])
        ax1.bar(xs, speedups, width=width, label=strategy)
        ax2.bar(xs, efficiencies, width=width, label=strategy)
    for axis, title in ((ax1, "Speedup"), (ax2, "Efficiency")):
        axis.set_xticks(range(len(sizes)))
        axis.set_xticklabels([str(s) for s in sizes])
        axis.set_xlabel("cluster size")
        axis.set_title(title)
        axis.legend(fontsize=7)
    ax2.set_ybound(0.0, 1.05)
    path = output_directory / "speedup_efficiency.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_job_durations(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.job_duration_stats(traces)
    fig, axis = plt.subplots(figsize=(7, 4))
    labels = [f"{size}w/{strategy}" for size, strategy in sorted(stats)]
    values = [stats[key]["mean_seconds"] for key in sorted(stats)]
    axis.bar(range(len(values)), values)
    axis.set_xticks(range(len(values)))
    axis.set_xticklabels(labels, rotation=30, ha="right", fontsize=7)
    axis.set_ylabel("mean job duration (s)")
    path = output_directory / "job_duration.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_tail_delay(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.tail_delay_stats(traces)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    keys = sorted(stats)
    labels = [f"{size}w/{strategy}" for size, strategy in keys]
    ax1.bar(range(len(keys)), [stats[k]["mean_tail_seconds"] for k in keys])
    ax1.set_title("Tail delay (s)")
    ax2.bar(range(len(keys)), [stats[k]["mean_tail_scaled"] for k in keys])
    ax2.set_title("Tail delay (x mean frame time)")
    ax2.set_ybound(0.0, 2.0)
    for axis in (ax1, ax2):
        axis.set_xticks(range(len(keys)))
        axis.set_xticklabels(labels, rotation=30, ha="right", fontsize=7)
    path = output_directory / "job_tail_delay.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_latency(traces: list[JobTrace], output_directory: Path) -> Path:
    """Heartbeat RTT boxplots per cluster size, one panel per strategy
    (reference: worker_latency.py keeps the strategy axis)."""
    output_directory.mkdir(parents=True, exist_ok=True)
    groups = _strategy_groups(traces)
    fig, axes = plt.subplots(
        1, max(len(groups), 1), figsize=(5 * max(len(groups), 1), 4),
        squeeze=False,
    )
    for axis, (strategy, strategy_traces) in zip(axes[0], sorted(groups.items())):
        by_size = defaultdict(list)
        for trace in strategy_traces:
            for worker in trace.worker_traces.values():
                for ping in worker.ping_traces:
                    by_size[trace.cluster_size()].append(ping.latency() * 1000.0)
        sizes = sorted(by_size)
        if sizes:
            axis.boxplot(
                [by_size[s] for s in sizes], tick_labels=[str(s) for s in sizes]
            )
        axis.set_title(f"RTT — {strategy}")
        axis.set_xlabel("cluster size")
        axis.set_ylabel("heartbeat RTT (ms)")
        axis.set_ybound(0.0, 5.0)
    path = output_directory / "worker_latency.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_phase_split(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.phase_split_stats(traces)
    keys = sorted(stats)
    fig, axis = plt.subplots(figsize=(8, max(4, 0.4 * len(keys))))
    left = [0.0] * len(keys)
    for phase, color in (("reading", "#4878a8"), ("rendering", "#e8a33d"), ("writing", "#6aa56a")):
        values = [stats[k][phase] for k in keys]
        axis.barh(range(len(keys)), values, left=left, label=phase, color=color)
        left = [l + v for l, v in zip(left, values)]
    axis.set_yticks(range(len(keys)))
    axis.set_yticklabels([f"{size}w/{strategy}" for size, strategy in keys], fontsize=7)
    axis.set_xlabel("fraction of frame time")
    axis.legend(fontsize=8)
    path = output_directory / "reading_rendering_writing.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_tail_delay_grids(traces: list[JobTrace], output_directory: Path) -> list[Path]:
    """Per-cluster-size panels of per-strategy tail-delay boxplots.

    Two figures, absolute seconds and scaled by mean frame render time,
    mirroring the reference's 3x2 grids (reference: job_tail_delay.py
    plot_tail_delay — one panel per measured cluster size, strategies on
    the x axis; scaled plot bounded to [0, 2] x mean frame time).
    """
    from tpu_render_cluster.analysis.models import (
        mean_frame_time,
        worker_tail_delay,
    )
    import statistics

    output_directory.mkdir(parents=True, exist_ok=True)
    # (size, strategy) -> per-run (absolute, scaled) tail delays.
    per_run: dict[tuple[int, str], list[tuple[float, float]]] = defaultdict(list)
    for trace in traces:
        global_last = trace.get_last_frame_finished_at()
        delays = [
            worker_tail_delay(worker, global_last)
            for worker in trace.worker_traces.values()
        ]
        if not delays:
            continue
        run_tail = max(delays)
        frame_times = [
            mean_frame_time(w)
            for w in trace.worker_traces.values()
            if w.frame_render_traces
        ]
        mean_ft = statistics.fmean(frame_times) if frame_times else 0.0
        per_run[(trace.cluster_size(), trace.strategy_type())].append(
            (run_tail, run_tail / mean_ft if mean_ft > 0 else 0.0)
        )

    sizes = sorted({size for size, _ in per_run})
    strategies = sorted({strategy for _, strategy in per_run})
    if not sizes:
        return []
    n_cols = 2
    n_rows = -(-len(sizes) // n_cols)
    global_max = max(v[0] for values in per_run.values() for v in values)

    paths = []
    for which, suffix, y_label, y_max in (
        (0, "seconds", "tail delay (s)", max(global_max * 1.1, 1e-3)),
        (1, "scaled", "tail delay (x mean frame time)", 2.0),
    ):
        fig, axes = plt.subplots(
            n_rows, n_cols, figsize=(5 * n_cols, 3.4 * n_rows), squeeze=False
        )
        for i in range(n_rows * n_cols):
            axis = axes[i // n_cols][i % n_cols]
            if i >= len(sizes):
                fig.delaxes(axis)
                continue
            size = sizes[i]
            data = [
                [v[which] for v in per_run.get((size, strategy), [])]
                for strategy in strategies
            ]
            axis.boxplot(
                [d if d else [0.0] for d in data],
                tick_labels=[s.replace("-", chr(10)) for s in strategies],
            )
            axis.set_title(f"{size} workers", fontsize=9)
            axis.set_ybound(0.0, y_max)
            axis.tick_params(labelsize=6)
            if i % n_cols == 0:
                axis.set_ylabel(y_label, fontsize=8)
        fig.suptitle(f"Job tail delay ({suffix})")
        path = output_directory / f"job_tail_delay_{suffix}_grid.png"
        fig.tight_layout()
        fig.savefig(path, dpi=110)
        plt.close(fig)
        paths.append(path)
    return paths


def plot_utilization_vs_strategy(
    traces: list[JobTrace], output_directory: Path
) -> Path:
    """Utilization boxplots with the STRATEGY on the x axis, one panel per
    cluster size (reference: worker_utilization.py
    plot_utilization_rate_against_strategies:188-296, including the
    emphasised [0.95, 1.0] bound, widened only when data falls below)."""
    output_directory.mkdir(parents=True, exist_ok=True)
    per_key: dict[tuple[int, str], list[float]] = defaultdict(list)
    for trace in traces:
        for u in M.worker_utilizations(trace):
            per_key[(trace.cluster_size(), trace.strategy_type())].append(
                u.utilization
            )
    sizes = sorted({size for size, _ in per_key})
    strategies = sorted({strategy for _, strategy in per_key})
    fig, axes = plt.subplots(
        1, max(len(sizes), 1), figsize=(4.2 * max(len(sizes), 1), 4),
        squeeze=False,
    )
    lowest = min((min(v) for v in per_key.values() if v), default=1.0)
    lower_bound = min(0.95, max(0.0, lowest - 0.02))
    for axis, size in zip(axes[0], sizes):
        data = [per_key.get((size, strategy), []) for strategy in strategies]
        axis.boxplot(
            [d if d else [0.0] for d in data],
            tick_labels=[s.replace("-", chr(10)) for s in strategies],
        )
        axis.set_title(f"{size} workers", fontsize=9)
        axis.set_xlabel("strategy", fontsize=8)
        axis.set_ylabel("utilization", fontsize=8)
        axis.set_ybound(lower_bound, 1.0)
        axis.tick_params(labelsize=6)
    path = output_directory / "worker_utilization_vs_strategy.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path
