"""Plot generation for the A5-A12 metrics (reference: analysis/*.py plots).

Matplotlib with the Agg backend; each function writes one PNG and returns
its path. Axis conventions follow the reference where they matter
(utilization emphasised on [0.95, 1.0], latency on [0, 5] ms, scaled tail
delay on [0, 2] — reference: worker_utilization.py:154-157,
worker_latency.py:129-132, job_tail_delay.py).
"""

from __future__ import annotations

from collections import defaultdict
from pathlib import Path

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from tpu_render_cluster.analysis.models import JobTrace  # noqa: E402
from tpu_render_cluster.analysis import metrics as M  # noqa: E402


def _strategy_groups(traces: list[JobTrace]):
    groups = defaultdict(list)
    for trace in traces:
        groups[trace.strategy_type()].append(trace)
    return groups


def plot_worker_utilization(traces: list[JobTrace], output_directory: Path) -> Path:
    """Boxplots of per-worker utilization vs cluster size, per strategy."""
    output_directory.mkdir(parents=True, exist_ok=True)
    groups = _strategy_groups(traces)
    fig, axes = plt.subplots(
        1, max(len(groups), 1), figsize=(5 * max(len(groups), 1), 4), squeeze=False
    )
    for axis, (strategy, strategy_traces) in zip(axes[0], sorted(groups.items())):
        by_size = defaultdict(list)
        for trace in strategy_traces:
            for u in M.worker_utilizations(trace):
                by_size[trace.cluster_size()].append(u.utilization)
        sizes = sorted(by_size)
        axis.boxplot([by_size[s] for s in sizes], tick_labels=[str(s) for s in sizes])
        axis.set_title(f"Utilization — {strategy}")
        axis.set_xlabel("cluster size")
        axis.set_ylabel("utilization")
        axis.set_ybound(0.0, 1.02)
    path = output_directory / "worker_utilization.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_speedup_and_efficiency(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.speedup_stats(traces)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    strategies = sorted({key[1] for key in stats})
    sizes = sorted({key[0] for key in stats})
    width = 0.8 / max(len(strategies), 1)
    for i, strategy in enumerate(strategies):
        xs, speedups, efficiencies = [], [], []
        for j, size in enumerate(sizes):
            if (size, strategy) in stats:
                xs.append(j + i * width)
                speedups.append(stats[(size, strategy)]["speedup"])
                efficiencies.append(stats[(size, strategy)]["efficiency"])
        ax1.bar(xs, speedups, width=width, label=strategy)
        ax2.bar(xs, efficiencies, width=width, label=strategy)
    for axis, title in ((ax1, "Speedup"), (ax2, "Efficiency")):
        axis.set_xticks(range(len(sizes)))
        axis.set_xticklabels([str(s) for s in sizes])
        axis.set_xlabel("cluster size")
        axis.set_title(title)
        axis.legend(fontsize=7)
    ax2.set_ybound(0.0, 1.05)
    path = output_directory / "speedup_efficiency.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_job_durations(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.job_duration_stats(traces)
    fig, axis = plt.subplots(figsize=(7, 4))
    labels = [f"{size}w/{strategy}" for size, strategy in sorted(stats)]
    values = [stats[key]["mean_seconds"] for key in sorted(stats)]
    axis.bar(range(len(values)), values)
    axis.set_xticks(range(len(values)))
    axis.set_xticklabels(labels, rotation=30, ha="right", fontsize=7)
    axis.set_ylabel("mean job duration (s)")
    path = output_directory / "job_duration.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_tail_delay(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.tail_delay_stats(traces)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    keys = sorted(stats)
    labels = [f"{size}w/{strategy}" for size, strategy in keys]
    ax1.bar(range(len(keys)), [stats[k]["mean_tail_seconds"] for k in keys])
    ax1.set_title("Tail delay (s)")
    ax2.bar(range(len(keys)), [stats[k]["mean_tail_scaled"] for k in keys])
    ax2.set_title("Tail delay (x mean frame time)")
    ax2.set_ybound(0.0, 2.0)
    for axis in (ax1, ax2):
        axis.set_xticks(range(len(keys)))
        axis.set_xticklabels(labels, rotation=30, ha="right", fontsize=7)
    path = output_directory / "job_tail_delay.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_latency(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    by_size = defaultdict(list)
    for trace in traces:
        for worker in trace.worker_traces.values():
            for ping in worker.ping_traces:
                by_size[trace.cluster_size()].append(ping.latency() * 1000.0)
    sizes = sorted(by_size)
    fig, axis = plt.subplots(figsize=(7, 4))
    if sizes:
        axis.boxplot([by_size[s] for s in sizes], tick_labels=[str(s) for s in sizes])
    axis.set_xlabel("cluster size")
    axis.set_ylabel("heartbeat RTT (ms)")
    axis.set_ybound(0.0, 5.0)
    path = output_directory / "worker_latency.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_phase_split(traces: list[JobTrace], output_directory: Path) -> Path:
    output_directory.mkdir(parents=True, exist_ok=True)
    stats = M.phase_split_stats(traces)
    sizes = sorted(stats)
    fig, axis = plt.subplots(figsize=(7, 4))
    left = [0.0] * len(sizes)
    for phase, color in (("reading", "#4878a8"), ("rendering", "#e8a33d"), ("writing", "#6aa56a")):
        values = [stats[s][phase] for s in sizes]
        axis.barh(range(len(sizes)), values, left=left, label=phase, color=color)
        left = [l + v for l, v in zip(left, values)]
    axis.set_yticks(range(len(sizes)))
    axis.set_yticklabels([f"{s} workers" for s in sizes])
    axis.set_xlabel("fraction of frame time")
    axis.legend(fontsize=8)
    path = output_directory / "reading_rendering_writing.png"
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path
