"""The `tpu-raytrace` render engine: a pure-JAX path tracer.

This is the compute plane that has no counterpart in the reference (which
shells out to Blender); it exists so the render farm's work can execute on
TPU. Design is TPU-first:

- scenes are structure-of-arrays with static shapes (`scene.py`), built as
  pure functions of the frame index so whole frame *batches* vmap;
- intersection is a rays x spheres batch computed with matmul-shaped
  contractions that XLA tiles onto the MXU (`geometry.py`), with a Pallas
  kernel variant for the hot loop (`pallas_kernels.py`);
- the integrator uses `lax.scan` over bounces with masked lanes instead of
  data-dependent control flow (`integrator.py`);
- multi-device execution shards tiles or samples over a
  `jax.sharding.Mesh` via `shard_map` with XLA collectives
  (tpu_render_cluster/parallel/).
"""

from tpu_render_cluster.render.scene import Scene, build_scene
from tpu_render_cluster.render.camera import camera_rays, scene_camera
from tpu_render_cluster.render.integrator import render_frame, render_tile

__all__ = [
    "Scene",
    "build_scene",
    "camera_rays",
    "scene_camera",
    "render_frame",
    "render_tile",
]
