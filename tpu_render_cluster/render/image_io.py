"""Image output: frame-placeholder expansion + PNG/JPEG writing.

The ``#####`` placeholder convention matches the reference's render script
(reference: scripts/render-timing-script.py:69-79): the run of ``#`` is
replaced by the zero-padded frame number.
"""

from __future__ import annotations

import re
from pathlib import Path

import numpy as np

_HASH_RUN = re.compile(r"#+")

_FORMAT_EXTENSIONS = {
    "PNG": ".png",
    "JPEG": ".jpg",
    "JPG": ".jpg",
    "BMP": ".bmp",
    "TIFF": ".tif",
}


def format_frame_placeholders(name_format: str, frame_number: int) -> str:
    """Replace the run of '#' with the zero-padded frame number."""
    match = _HASH_RUN.search(name_format)
    if match is None:
        return f"{name_format}{frame_number}"
    width = match.end() - match.start()
    return (
        name_format[: match.start()]
        + str(frame_number).rjust(width, "0")
        + name_format[match.end():]
    )


def output_path_for_frame(
    output_directory: Path, name_format: str, file_format: str, frame_number: int
) -> Path:
    extension = _FORMAT_EXTENSIONS.get(file_format.upper(), ".png")
    return output_directory / (
        format_frame_placeholders(name_format, frame_number) + extension
    )


def write_image(path: Path, pixels: np.ndarray, file_format: str = "PNG") -> None:
    """Write a [H, W, 3] uint8 array; falls back to PNG for unknown formats."""
    from PIL import Image

    image_format = file_format.upper()
    if image_format == "JPG":
        image_format = "JPEG"
    if image_format not in _FORMAT_EXTENSIONS:
        image_format = "PNG"
    path.parent.mkdir(parents=True, exist_ok=True)
    image = Image.fromarray(np.asarray(pixels))
    if image_format == "JPEG":
        image.save(path, image_format, quality=90)  # reference script: quality=90
    else:
        image.save(path, image_format)
