"""Image output: frame-placeholder expansion + PNG/JPEG writing.

The ``#####`` placeholder convention matches the reference's render script
(reference: scripts/render-timing-script.py:69-79): the run of ``#`` is
replaced by the zero-padded frame number.
"""

from __future__ import annotations

import re
from pathlib import Path

import numpy as np

_HASH_RUN = re.compile(r"#+")

_FORMAT_EXTENSIONS = {
    "PNG": ".png",
    "JPEG": ".jpg",
    "JPG": ".jpg",
    "BMP": ".bmp",
    "TIFF": ".tif",
}


def format_frame_placeholders(name_format: str, frame_number: int) -> str:
    """Replace the run of '#' with the zero-padded frame number."""
    match = _HASH_RUN.search(name_format)
    if match is None:
        return f"{name_format}{frame_number}"
    width = match.end() - match.start()
    return (
        name_format[: match.start()]
        + str(frame_number).rjust(width, "0")
        + name_format[match.end():]
    )


def output_path_for_frame(
    output_directory: Path, name_format: str, file_format: str, frame_number: int
) -> Path:
    extension = _FORMAT_EXTENSIONS.get(file_format.upper(), ".png")
    return output_directory / (
        format_frame_placeholders(name_format, frame_number) + extension
    )


def output_path_for_tile(
    output_directory: Path,
    name_format: str,
    file_format: str,
    frame_number: int,
    tile: int,
    grid: tuple[int, int],
) -> Path:
    """Where one tile of a tiled frame lands: the frame's own output path
    with a ``.tile_rRcC`` infix — always ``.png``. Tile intermediates are
    LOSSLESS regardless of the job's final format: encoding each tile of
    a JPEG job lossily and re-encoding the stitched frame would quantize
    twice (with independent per-tile block boundaries) and break the
    tiled-equals-untiled pixel contract. Workers (writing) and the
    master's assembler (reading/stitching) both resolve through here, so
    the naming cannot drift."""
    from tpu_render_cluster.jobs.tiles import tile_rc

    frame_path = output_path_for_frame(
        output_directory, name_format, file_format, frame_number
    )
    row, col = tile_rc(tile, grid)
    return frame_path.with_name(
        f"{frame_path.stem}.tile_r{row}c{col}.png"
    )


def write_image(path: Path, pixels: np.ndarray, file_format: str = "PNG") -> None:
    """Write a [H, W, 3] uint8 array; falls back to PNG for unknown formats.

    Atomic (write-temp-then-rename): a reader never sees a torn file.
    Load-bearing for tile assembly — a duplicate assignment of the same
    tile (queue-add ack timeout races) can still be writing the tile path
    when the master's stitcher reads it; both copies carry identical
    pixels, so with the rename either complete version is correct.
    """
    import os
    import tempfile

    from PIL import Image

    image_format = file_format.upper()
    if image_format == "JPG":
        image_format = "JPEG"
    if image_format not in _FORMAT_EXTENSIONS:
        image_format = "PNG"
    path.parent.mkdir(parents=True, exist_ok=True)
    image = Image.fromarray(np.asarray(pixels))
    fd, tmp_name = tempfile.mkstemp(
        prefix=f".{path.name}.", suffix=".tmp", dir=path.parent
    )
    try:
        with os.fdopen(fd, "wb") as f:
            if image_format == "JPEG":
                # reference script: quality=90
                image.save(f, image_format, quality=90)
            else:
                image.save(f, image_format)
        os.replace(tmp_name, path)
    except BaseException:
        try:
            os.unlink(tmp_name)
        except OSError:
            pass
        raise
