"""Standalone render CLI: render one frame of a procedural scene to a file.

Usage:
  python -m tpu_render_cluster.render.cli --scene 04_very-simple --frame 1 \
      --width 256 --height 256 --samples 4 --out /tmp/frame.png
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="trc-render")
    parser.add_argument("--scene", default="04_very-simple")
    parser.add_argument(
        "--obj",
        default=None,
        help="render this Wavefront OBJ on a turntable stage instead of a "
        "named procedural scene (normalized to stage scale; rotates with "
        "--frame)",
    )
    parser.add_argument("--frame", type=int, default=1)
    parser.add_argument("--width", type=int, default=512)
    parser.add_argument("--height", type=int, default=512)
    parser.add_argument("--samples", type=int, default=8)
    parser.add_argument("--bounces", type=int, default=4)
    parser.add_argument("--out", required=True)
    args = parser.parse_args(argv)

    import json

    import numpy as np

    from tpu_render_cluster.render.image_io import write_image
    from tpu_render_cluster.render.integrator import render_frame, tonemap

    obj_bvh = None
    if args.obj is not None:
        # Geometry ingest (disk read + parse + host BVH build) is the
        # analog of Blender's .blend load and belongs to the load phase.
        from tpu_render_cluster.render.mesh_io import cached_obj_bvh

        obj_bvh = cached_obj_bvh(args.obj)
    loaded_at = time.time()  # imports + geometry ingest = "project load"
    if args.obj is not None:
        linear = _render_obj_stage(args, obj_bvh)
    else:
        linear = render_frame(
            args.scene,
            args.frame,
            width=args.width,
            height=args.height,
            samples=args.samples,
            max_bounces=args.bounces,
        )
    linear.block_until_ready()
    finished_rendering_at = time.time()
    path = Path(args.out)
    write_image(path, np.asarray(tonemap(linear)), path.suffix.lstrip(".").upper() or "PNG")
    saved_at = time.time()
    print(
        f"Rendered {args.obj or args.scene} frame {args.frame} "
        f"({args.width}x{args.height}, {args.samples} spp) "
        f"in {finished_rendering_at - loaded_at:.2f} s -> {path}"
    )
    # Phase-timing contract consumed by worker daemons (same shape as the
    # Blender timing script, scripts/render-timing-script.py, plus explicit
    # save timestamps since we know them exactly).
    print(
        "RESULTS="
        + json.dumps(
            {
                "project_loaded_at": loaded_at,
                "project_started_rendering_at": loaded_at,
                "project_finished_rendering_at": finished_rendering_at,
                "file_saving_started_at": finished_rendering_at,
                "file_saving_finished_at": saved_at,
            }
        )
    )
    return 0


def _render_obj_stage(args, bvh):
    """One turntable frame of a user OBJ: same integrator, same Pallas BVH
    kernels as the built-in mesh scenes, geometry loaded from disk."""
    import jax.numpy as jnp

    from tpu_render_cluster.render.camera import look_at_camera
    from tpu_render_cluster.render.integrator import render_tile
    from tpu_render_cluster.render.mesh import (
        MeshInstances,
        MeshSet,
        rotation_y,
    )
    from tpu_render_cluster.render.scene import obj_stage_scene

    angle = jnp.asarray([args.frame * 0.06], jnp.float32)
    instances = MeshInstances(
        rotation=rotation_y(angle).astype(jnp.float32),
        translation=jnp.array([[0.0, 1.05, 0.0]], jnp.float32),
        albedo=jnp.array([[0.72, 0.7, 0.75]], jnp.float32),
        scale=jnp.array([1.0], jnp.float32),
    )
    camera = look_at_camera([4.0, 2.8, 4.2], [0.0, 1.0, 0.0])
    from tpu_render_cluster.render.integrator import resolve_bvh_config

    _tlas, bvh_quant, _builder, _wide = resolve_bvh_config()
    return render_tile(
        obj_stage_scene(args.frame),
        camera,
        float(args.frame),
        0,
        0,
        width=args.width,
        height=args.height,
        tile_height=args.height,
        tile_width=args.width,
        samples=args.samples,
        max_bounces=args.bounces,
        mesh=MeshSet(bvh=bvh, instances=instances),
        quant=bvh_quant,
    )


if __name__ == "__main__":
    sys.exit(main())
