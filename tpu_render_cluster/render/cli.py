"""Standalone render CLI: render one frame of a procedural scene to a file.

Usage:
  python -m tpu_render_cluster.render.cli --scene 04_very-simple --frame 1 \
      --width 256 --height 256 --samples 4 --out /tmp/frame.png
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="trc-render")
    parser.add_argument("--scene", default="04_very-simple")
    parser.add_argument("--frame", type=int, default=1)
    parser.add_argument("--width", type=int, default=512)
    parser.add_argument("--height", type=int, default=512)
    parser.add_argument("--samples", type=int, default=8)
    parser.add_argument("--bounces", type=int, default=4)
    parser.add_argument("--out", required=True)
    args = parser.parse_args(argv)

    import json

    import numpy as np

    from tpu_render_cluster.render.image_io import write_image
    from tpu_render_cluster.render.integrator import render_frame, tonemap

    loaded_at = time.time()  # imports above = the "project load" phase
    linear = render_frame(
        args.scene,
        args.frame,
        width=args.width,
        height=args.height,
        samples=args.samples,
        max_bounces=args.bounces,
    )
    linear.block_until_ready()
    finished_rendering_at = time.time()
    path = Path(args.out)
    write_image(path, np.asarray(tonemap(linear)), path.suffix.lstrip(".").upper() or "PNG")
    saved_at = time.time()
    print(
        f"Rendered {args.scene} frame {args.frame} "
        f"({args.width}x{args.height}, {args.samples} spp) "
        f"in {finished_rendering_at - loaded_at:.2f} s -> {path}"
    )
    # Phase-timing contract consumed by worker daemons (same shape as the
    # Blender timing script, scripts/render-timing-script.py, plus explicit
    # save timestamps since we know them exactly).
    print(
        "RESULTS="
        + json.dumps(
            {
                "project_loaded_at": loaded_at,
                "project_started_rendering_at": loaded_at,
                "project_finished_rendering_at": finished_rendering_at,
                "file_saving_started_at": finished_rendering_at,
                "file_saving_finished_at": saved_at,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
