"""Triangle meshes with a threaded BVH, TPU-first.

The reference's workers render arbitrary .blend content (reference:
worker/src/rendering/runner/mod.rs:165-176); this module is the TPU-native
counterpart for mesh geometry (SURVEY.md §7 hard part #4: "BVH on TPU").

Design for the TPU's execution model:

- **Static topology, host-built BVH.** Mesh topology never changes across
  frames; animation is rigid per-instance motion. The BVH is built once on
  the host (numpy, median split) over object-space triangles and becomes
  constant device arrays — no per-frame rebuild, no dynamic shapes.
- **Threaded (skip-link) layout = stackless traversal.** Nodes are stored
  in DFS preorder; each carries a ``skip`` link to the next subtree root.
  Traversal is a single moving index: AABB hit on an inner node -> step to
  ``i + 1``; leaf or miss -> jump to ``skip[i]``. No stack, one scalar of
  control state — exactly what ``lax.while_loop`` (and a Pallas scalar
  loop) wants.
- **Packet traversal.** One node sequence is walked per ray *block*; the
  AABB test is vectorized over the block and reduced with ``any``. The
  scalar unit steers, the vector unit tests — divergence costs extra node
  visits, not scalar-per-ray control flow. Camera/shadow packets are
  coherent, so the shared walk skips most of the tree in practice.
- **Instances, not world-space soup.** Rays are transformed into object
  space per instance (rigid transforms preserve t), so K animated
  instances share one static BVH.

``intersect_triangles_brute`` (batched Möller–Trumbore over all
triangles) is the correctness reference the BVH paths are tested against.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Plain Python floats: a module-level jnp constant would be created during
# whatever trace first imports this module (the integrator imports it
# lazily inside traced functions) and leak that trace's tracer into every
# later caller.
INF = 1e30
EPS = 1e-3
# Fixed leaf width: every leaf occupies its own LEAF_SIZE-aligned slot of
# exactly LEAF_SIZE triangle rows (real triangles first, degenerate padding
# after), and traversal always loads exactly LEAF_SIZE rows masked by the
# node's count. A static aligned width keeps the traversal free of
# shape-dependent Python AND makes the Pallas kernel's dynamic sublane
# slices tile-aligned (8 = the f32 sublane tile).
LEAF_SIZE = 16


class MeshBVH(NamedTuple):
    """Object-space triangle mesh + threaded BVH (all static device arrays).

    Triangles are stored leaf-reordered so every leaf references the
    contiguous range ``[first, first + count)``.
    """

    # Triangle data, leaf-contiguous order.
    v0: jnp.ndarray  # [T, 3]
    e1: jnp.ndarray  # [T, 3]  (v1 - v0)
    e2: jnp.ndarray  # [T, 3]  (v2 - v0)
    normal: jnp.ndarray  # [T, 3] unit geometric normals
    # Threaded BVH in DFS preorder.
    bounds_min: jnp.ndarray  # [N, 3]
    bounds_max: jnp.ndarray  # [N, 3]
    skip: jnp.ndarray  # [N] int32 — next subtree root (N = done)
    first: jnp.ndarray  # [N] int32 — leaf triangle start (0 for inner)
    count: jnp.ndarray  # [N] int32 — leaf triangle count (0 for inner)


# ---------------------------------------------------------------------------
# Procedural meshes


def make_box() -> tuple[np.ndarray, np.ndarray]:
    """Unit cube centered at the origin: 8 vertices, 12 triangles."""
    vertices = np.array(
        [
            [-0.5, -0.5, -0.5], [0.5, -0.5, -0.5],
            [0.5, 0.5, -0.5], [-0.5, 0.5, -0.5],
            [-0.5, -0.5, 0.5], [0.5, -0.5, 0.5],
            [0.5, 0.5, 0.5], [-0.5, 0.5, 0.5],
        ],
        np.float32,
    )
    faces = np.array(
        [
            [0, 2, 1], [0, 3, 2],  # -z
            [4, 5, 6], [4, 6, 7],  # +z
            [0, 1, 5], [0, 5, 4],  # -y
            [3, 6, 2], [3, 7, 6],  # +y
            [0, 7, 3], [0, 4, 7],  # -x
            [1, 2, 6], [1, 6, 5],  # +x
        ],
        np.int32,
    )
    return vertices, faces


def make_icosphere(subdivisions: int = 2) -> tuple[np.ndarray, np.ndarray]:
    """Unit icosphere (radius 0.5) via icosahedron midpoint subdivision."""
    phi = (1.0 + np.sqrt(5.0)) / 2.0
    raw = np.array(
        [
            [-1, phi, 0], [1, phi, 0], [-1, -phi, 0], [1, -phi, 0],
            [0, -1, phi], [0, 1, phi], [0, -1, -phi], [0, 1, -phi],
            [phi, 0, -1], [phi, 0, 1], [-phi, 0, -1], [-phi, 0, 1],
        ],
        np.float32,
    )
    vertices = raw / np.linalg.norm(raw, axis=1, keepdims=True)
    faces = np.array(
        [
            [0, 11, 5], [0, 5, 1], [0, 1, 7], [0, 7, 10], [0, 10, 11],
            [1, 5, 9], [5, 11, 4], [11, 10, 2], [10, 7, 6], [7, 1, 8],
            [3, 9, 4], [3, 4, 2], [3, 2, 6], [3, 6, 8], [3, 8, 9],
            [4, 9, 5], [2, 4, 11], [6, 2, 10], [8, 6, 7], [9, 8, 1],
        ],
        np.int32,
    )
    for _ in range(subdivisions):
        midpoint_cache: dict[tuple[int, int], int] = {}
        vertex_list = [v for v in vertices]
        new_faces = []

        def midpoint(a: int, b: int) -> int:
            key = (min(a, b), max(a, b))
            if key not in midpoint_cache:
                m = vertex_list[a] + vertex_list[b]
                m = m / np.linalg.norm(m)
                midpoint_cache[key] = len(vertex_list)
                vertex_list.append(m.astype(np.float32))
            return midpoint_cache[key]

        for f in faces:
            a, b, c = int(f[0]), int(f[1]), int(f[2])
            ab, bc, ca = midpoint(a, b), midpoint(b, c), midpoint(c, a)
            new_faces += [[a, ab, ca], [b, bc, ab], [c, ca, bc], [ab, bc, ca]]
        vertices = np.stack(vertex_list)
        faces = np.array(new_faces, np.int32)
    return (vertices * 0.5).astype(np.float32), faces


# ---------------------------------------------------------------------------
# Host-side BVH build (numpy — runs once per mesh, cached)


def build_bvh(vertices: np.ndarray, faces: np.ndarray) -> MeshBVH:
    """Median-split BVH over triangle centroids, threaded for traversal."""
    leaf_size = LEAF_SIZE
    tri = vertices[faces]  # [T, 3, 3]
    centroids = tri.mean(axis=1)
    order = np.arange(len(faces))

    # Recursive median split producing (bounds, leaf range | children).
    nodes: list[dict] = []

    def emit(indices: np.ndarray) -> int:
        node_index = len(nodes)
        pts = tri[indices].reshape(-1, 3)
        node = {
            "min": pts.min(axis=0),
            "max": pts.max(axis=0),
            "first": -1,
            "count": 0,
            "children": None,
        }
        nodes.append(node)
        if len(indices) <= leaf_size:
            node["first"] = indices  # placeholder; flattened below
            node["count"] = len(indices)
            return node_index
        extent = centroids[indices].max(axis=0) - centroids[indices].min(axis=0)
        axis = int(np.argmax(extent))
        mid = len(indices) // 2
        part = indices[np.argsort(centroids[indices, axis], kind="stable")]
        left = emit(part[:mid])
        right = emit(part[mid:])
        node["children"] = (left, right)
        return node_index

    emit(order)

    # Flatten leaves into aligned LEAF_SIZE-wide slots (-1 = degenerate pad).
    tri_order: list[int] = []
    first = np.zeros(len(nodes), np.int32)
    count = np.zeros(len(nodes), np.int32)
    for i, node in enumerate(nodes):
        if node["children"] is None:
            first[i] = len(tri_order)
            count[i] = node["count"]
            members = [int(t) for t in node["first"]]
            tri_order.extend(members + [-1] * (LEAF_SIZE - len(members)))

    # Skip links: nodes are already in DFS preorder (emit order); a node's
    # skip is the next node that is NOT in its subtree. Compute subtree
    # sizes by walking children.
    subtree = np.ones(len(nodes), np.int32)

    def size(i: int) -> int:
        node = nodes[i]
        if node["children"] is not None:
            left, right = node["children"]
            subtree[i] = 1 + size(left) + size(right)
        return subtree[i]

    size(0)
    skip = np.array([i + subtree[i] for i in range(len(nodes))], np.int32)

    order_array = np.array(tri_order, np.int64)
    real = order_array >= 0
    reordered = np.zeros((len(order_array), 3, 3), np.float32)
    reordered[real] = tri[order_array[real]]  # pad rows stay all-zero
    v0 = reordered[:, 0]
    e1 = reordered[:, 1] - reordered[:, 0]
    e2 = reordered[:, 2] - reordered[:, 0]
    n = np.cross(e1, e2)
    norm = np.linalg.norm(n, axis=1, keepdims=True)
    n = np.where(norm > 1e-12, n / np.maximum(norm, 1e-12), np.array([[0.0, 1.0, 0.0]], np.float32))
    # ensure_compile_time_eval: the first build may happen INSIDE a jit
    # trace (fused_frame_renderer -> scene_mesh_set -> cached_mesh_bvh),
    # where bare jnp.asarray would return trace-local tracers — which the
    # lru_cache would then hand to later EAGER callers (the wavefront
    # driver) as leaked tracers. This forces concrete, cache-safe arrays
    # regardless of the first caller's context.
    with jax.ensure_compile_time_eval():
        return MeshBVH(
            v0=jnp.asarray(v0),
            e1=jnp.asarray(e1),
            e2=jnp.asarray(e2),
            normal=jnp.asarray(n.astype(np.float32)),
            bounds_min=jnp.asarray(np.stack([nd["min"] for nd in nodes])),
            bounds_max=jnp.asarray(np.stack([nd["max"] for nd in nodes])),
            skip=jnp.asarray(skip),
            first=jnp.asarray(first),
            count=jnp.asarray(count),
        )


# Process-wide geometry-build memo: host-side BVH/TLAS builds keyed by
# every parameter that shapes the result — (kind, leaf_size) for BLAS
# builds, (k_count, tlas_leaf_size) for TLAS topologies — so the test
# suite and the bucket-ladder recompiles never rebuild a hierarchy they
# have already built this process. An explicit dict (not lru_cache) so
# tests can reset it: tests/conftest.py wires ``reset_geometry_cache``
# into the autouse fixture alongside ``compaction.reset_compile_tracking``.
_geometry_cache: dict[tuple, object] = {}


def reset_geometry_cache() -> None:
    """Forget memoized host-side BVH/TLAS builds (test isolation only:
    the builds are pure, so resetting merely makes the next call rebuild
    — per-test build-count assertions stay independent of earlier
    tests)."""
    _geometry_cache.clear()


def cached_mesh_bvh(kind: str) -> MeshBVH:
    key = ("bvh", kind, LEAF_SIZE)
    bvh = _geometry_cache.get(key)
    if bvh is None:
        if kind == "box":
            bvh = build_bvh(*make_box())
        elif kind == "icosphere":
            bvh = build_bvh(*make_icosphere(2))
        else:
            raise ValueError(f"Unknown mesh kind: {kind!r}")
        _geometry_cache[key] = bvh
    return bvh


# ---------------------------------------------------------------------------
# Intersection


def _moller_trumbore(origins, directions, v0, e1, e2):
    """Batched ray x triangle test: [R, T] hit distances (INF = miss)."""
    # pvec = d x e2; det = e1 . pvec  (per ray-triangle pair)
    pvec = jnp.cross(directions[:, None, :], e2[None, :, :])
    det = jnp.sum(e1[None, :, :] * pvec, axis=-1)
    inv_det = 1.0 / jnp.where(jnp.abs(det) < 1e-12, 1e-12, det)
    tvec = origins[:, None, :] - v0[None, :, :]
    u = jnp.sum(tvec * pvec, axis=-1) * inv_det
    qvec = jnp.cross(tvec, e1[None, :, :])
    v = jnp.sum(directions[:, None, :] * qvec, axis=-1) * inv_det
    t = jnp.sum(e2[None, :, :] * qvec, axis=-1) * inv_det
    hit = (
        (jnp.abs(det) > 1e-12)
        & (u >= 0.0)
        & (v >= 0.0)
        & (u + v <= 1.0)
        & (t > EPS)
    )
    return jnp.where(hit, t, INF)


def intersect_triangles_brute(bvh: MeshBVH, origins, directions):
    """Nearest triangle hit by brute force — the correctness reference.

    Returns (t [R], triangle_index [R] int32).
    """
    t = _moller_trumbore(origins, directions, bvh.v0, bvh.e1, bvh.e2)
    best = jnp.argmin(t, axis=-1).astype(jnp.int32)
    return jnp.take_along_axis(t, best[:, None], axis=-1)[:, 0], best


def intersect_bvh_packet(bvh: MeshBVH, origins, directions, init_t=None):
    """Threaded-BVH packet traversal in pure XLA (runs on any platform).

    One node walk is shared by the whole ray packet: the scalar walk index
    advances on the block-wide ``any`` of the per-ray AABB tests. Returns
    (t [R], triangle_index [R] int32) identical to the brute-force result.

    ``init_t`` seeds the per-ray cull distance (e.g. the nearest hit found
    on previously-scanned instances), letting the walk prune subtrees that
    cannot beat an existing hit.
    """
    n_nodes = bvh.skip.shape[0]
    inv_dir = 1.0 / jnp.where(
        jnp.abs(directions) < 1e-12, jnp.where(directions < 0, -1e-12, 1e-12),
        directions,
    )

    def aabb_any_hit(node, best_t):
        lo = (bvh.bounds_min[node][None, :] - origins) * inv_dir
        hi = (bvh.bounds_max[node][None, :] - origins) * inv_dir
        tmin = jnp.max(jnp.minimum(lo, hi), axis=-1)
        tmax = jnp.min(jnp.maximum(lo, hi), axis=-1)
        hit = (tmax >= jnp.maximum(tmin, 0.0)) & (tmin < best_t)
        return jnp.any(hit)

    def leaf_intersect(node, best_t, best_index):
        start = bvh.first[node]
        v0 = jax.lax.dynamic_slice(bvh.v0, (start, 0), (LEAF_SIZE, 3))
        e1 = jax.lax.dynamic_slice(bvh.e1, (start, 0), (LEAF_SIZE, 3))
        e2 = jax.lax.dynamic_slice(bvh.e2, (start, 0), (LEAF_SIZE, 3))
        t = _moller_trumbore(origins, directions, v0, e1, e2)  # [R, LEAF_SIZE]
        in_leaf = jnp.arange(LEAF_SIZE)[None, :] < bvh.count[node]
        t = jnp.where(in_leaf, t, INF)
        local = jnp.argmin(t, axis=-1)
        t_leaf = jnp.take_along_axis(t, local[:, None], axis=-1)[:, 0]
        closer = t_leaf < best_t
        best_t = jnp.where(closer, t_leaf, best_t)
        best_index = jnp.where(
            closer, (start + local).astype(jnp.int32), best_index
        )
        return best_t, best_index

    def cond(carry):
        node, _, _ = carry
        return node < n_nodes

    def body(carry):
        node, best_t, best_index = carry
        hit_any = aabb_any_hit(node, best_t)
        is_leaf = bvh.count[node] > 0

        def on_hit(args):
            best_t, best_index = args

            def leaf(args):
                return leaf_intersect(node, *args)

            best_t, best_index = jax.lax.cond(
                is_leaf, leaf, lambda args: args, (best_t, best_index)
            )
            next_node = jnp.where(is_leaf, bvh.skip[node], node + 1)
            return next_node, best_t, best_index

        def on_miss(args):
            best_t, best_index = args
            return bvh.skip[node], best_t, best_index

        return jax.lax.cond(hit_any, on_hit, on_miss, (best_t, best_index))

    r = origins.shape[0]
    start_t = (
        jnp.full((r,), INF, jnp.float32) if init_t is None else init_t
    )
    init = (jnp.int32(0), start_t, jnp.zeros((r,), jnp.int32))
    _, best_t, best_index = jax.lax.while_loop(cond, body, init)
    return best_t, best_index


def intersect_mesh(bvh: MeshBVH, origins, directions, init_t=None):
    """Nearest mesh hit: Pallas packet kernel on TPU, XLA walk elsewhere."""
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        return pallas_kernels.intersect_bvh_pallas(
            bvh, origins, directions, init_t
        )
    return intersect_bvh_packet(bvh, origins, directions, init_t)


def occluded_bvh_packet(bvh: MeshBVH, origins, directions, already) -> jnp.ndarray:
    """Any-hit packet walk: True per ray once ANY triangle is hit.

    ``already`` marks rays occluded by earlier instances — they stop
    driving traversal (pruning whole subtrees), with no nearest-hit
    ordering or argmin bookkeeping. Deliberately NO data-dependent early
    exit of the walk itself: a per-step all() reduce costs more on TPU
    than the node visits it saves (measured -6% on the mesh bench).
    """
    n_nodes = bvh.skip.shape[0]
    inv_dir = 1.0 / jnp.where(
        jnp.abs(directions) < 1e-12, jnp.where(directions < 0, -1e-12, 1e-12),
        directions,
    )

    def cond(carry):
        node, _ = carry
        return node < n_nodes

    def body(carry):
        node, occluded = carry
        lo = (bvh.bounds_min[node][None, :] - origins) * inv_dir
        hi = (bvh.bounds_max[node][None, :] - origins) * inv_dir
        tmin = jnp.max(jnp.minimum(lo, hi), axis=-1)
        tmax = jnp.min(jnp.maximum(lo, hi), axis=-1)
        packet_hit = (tmax >= jnp.maximum(tmin, 0.0)) & ~occluded
        hit_any = jnp.any(packet_hit)
        is_leaf = bvh.count[node] > 0

        def on_leaf(occluded):
            start = bvh.first[node]
            v0 = jax.lax.dynamic_slice(bvh.v0, (start, 0), (LEAF_SIZE, 3))
            e1 = jax.lax.dynamic_slice(bvh.e1, (start, 0), (LEAF_SIZE, 3))
            e2 = jax.lax.dynamic_slice(bvh.e2, (start, 0), (LEAF_SIZE, 3))
            t = _moller_trumbore(origins, directions, v0, e1, e2)
            in_leaf = jnp.arange(LEAF_SIZE)[None, :] < bvh.count[node]
            return occluded | jnp.any(jnp.where(in_leaf, t, INF) < INF, axis=-1)

        def on_hit(occluded):
            occluded = jax.lax.cond(
                is_leaf, on_leaf, lambda occluded: occluded, occluded
            )
            return jnp.where(is_leaf, bvh.skip[node], node + 1), occluded

        def on_miss(occluded):
            return bvh.skip[node], occluded

        return jax.lax.cond(hit_any, on_hit, on_miss, occluded)

    _, occluded = jax.lax.while_loop(
        cond, body, (jnp.int32(0), already)
    )
    return occluded


def occluded_mesh(bvh: MeshBVH, origins, directions, already) -> jnp.ndarray:
    """Any-hit dispatch: Pallas kernel on TPU, XLA walk elsewhere."""
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        return pallas_kernels.occluded_bvh_pallas(
            bvh, origins, directions, already
        )
    return occluded_bvh_packet(bvh, origins, directions, already)


# ---------------------------------------------------------------------------
# Instances


class MeshInstances(NamedTuple):
    """K similarity-transformed instances of one object-space mesh.

    ``x_world = scale * rotation @ x_obj + translation``. Rays are pulled
    back with the inverse; dividing BOTH the local origin and direction by
    ``scale`` preserves the ray parameter t, so per-instance hits compare
    directly in world units and one static BVH serves every animated
    instance.
    """

    rotation: jnp.ndarray  # [K, 3, 3] pure rotations
    translation: jnp.ndarray  # [K, 3]
    albedo: jnp.ndarray  # [K, 3]
    scale: jnp.ndarray  # [K] uniform per-instance scale


def _rays_to_object_space(instances: MeshInstances, k, origins, directions):
    """World -> object: x' = R^T (x - t) / s; the direction is scaled by
    1/s too, which keeps the ray parameter t in world units.

    The rotation is applied elementwise (the 3-wide contraction unrolled):
    it stays on the VPU in full f32 — precision="highest" einsum forces a
    slow multi-pass MXU lowering, while the default bf16 matmul path puts
    ~0.4% relative error on ray origins (centimeters at scene scale).
    """
    rot = instances.rotation[k]
    inv_scale = 1.0 / instances.scale[k]
    shifted = origins - instances.translation[k][None, :]
    local_origins = (
        shifted[:, 0:1] * rot[0][None, :]
        + shifted[:, 1:2] * rot[1][None, :]
        + shifted[:, 2:3] * rot[2][None, :]
    ) * inv_scale
    local_directions = (
        directions[:, 0:1] * rot[0][None, :]
        + directions[:, 1:2] * rot[1][None, :]
        + directions[:, 2:3] * rot[2][None, :]
    ) * inv_scale
    return local_origins, local_directions


def _normals_to_world(rot, normal_obj):
    """World normal = R n_obj (rigid: inverse transpose == R).

    ``rot`` may be one [3, 3] rotation or a per-ray [R, 3, 3] batch.
    Unrolled elementwise so it stays on the VPU in full f32: the default
    matmul precision rounds through bf16 and visibly tilts shading normals
    (~0.2%).
    """
    return (
        rot[..., :, 0] * normal_obj[:, 0:1]
        + rot[..., :, 1] * normal_obj[:, 1:2]
        + rot[..., :, 2] * normal_obj[:, 2:3]
    )


def intersect_instances(
    bvh: MeshBVH, instances: MeshInstances, origins, directions, init_t=None
):
    """Nearest hit over all instances.

    Returns (t [R], normal [R, 3] world-space, albedo [R, 3]). Rigid
    transforms preserve ray parameter t, so per-instance results compare
    directly. ``init_t`` (optional, [R]) seeds the best-t with a hit the
    caller already knows (the same bounce's sphere/plane t): lanes whose
    seed beats an instance's AABB entry stop driving that instance's walk,
    and a mesh miss returns t == init_t (never closer, so callers using a
    strict ``<`` comparison see it as a miss).

    On TPU this is ONE instanced-kernel launch (grid = ray blocks x
    instances, world-AABB top-level cull per block) followed by XLA
    gathers for the winning triangle's normal and instance's
    rotation/albedo; elsewhere it is a lax.scan of per-instance walks.
    """
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        t, tri, inst = pallas_kernels.intersect_instances_pallas(
            bvh, instances, origins, directions, init_t
        )
        # A seeded miss comes back with t == init_t (< INF), so the hit
        # test must compare against the seed, not INF — otherwise the
        # tri=0/inst=0 gathers below leak garbage normals/albedo where the
        # scan branch returns zeros.
        seed = INF if init_t is None else init_t
        hit = (t < seed)[:, None]
        normal_obj = bvh.normal[tri]
        rot = instances.rotation[inst]  # [R, 3, 3]
        normal_world = _normals_to_world(rot, normal_obj)
        facing = jnp.sum(normal_world * directions, axis=-1) < 0.0
        normal_world = jnp.where(facing[:, None], normal_world, -normal_world)
        # Misses keep the scan path's zero normal/albedo contract.
        best_normal = jnp.where(hit, normal_world, 0.0)
        best_albedo = jnp.where(hit, instances.albedo[inst], 0.0)
        return t, best_normal, best_albedo

    def per_instance(carry, k):
        best_t, best_normal, best_albedo = carry
        rot = instances.rotation[k]
        local_origins, local_directions = _rays_to_object_space(
            instances, k, origins, directions
        )
        # Seed the walk with the best hit so far: t is in world units for
        # every instance, so earlier instances' hits prune this walk.
        t, tri = intersect_mesh(bvh, local_origins, local_directions, best_t)
        normal_obj = bvh.normal[tri]
        normal_world = _normals_to_world(rot, normal_obj)
        closer = t < best_t
        best_t = jnp.where(closer, t, best_t)
        best_normal = jnp.where(closer[:, None], normal_world, best_normal)
        best_albedo = jnp.where(
            closer[:, None], instances.albedo[k][None, :], best_albedo
        )
        return (best_t, best_normal, best_albedo), None

    r = origins.shape[0]
    init = (
        jnp.full((r,), INF, jnp.float32) if init_t is None else init_t,
        jnp.zeros((r, 3), jnp.float32),
        jnp.zeros((r, 3), jnp.float32),
    )
    k_count = instances.translation.shape[0]
    (best_t, best_normal, best_albedo), _ = jax.lax.scan(
        per_instance, init, jnp.arange(k_count)
    )
    # Flip normals to face the incoming ray.
    facing = jnp.sum(best_normal * directions, axis=-1) < 0.0
    best_normal = jnp.where(facing[:, None], best_normal, -best_normal)
    return best_t, best_normal, best_albedo


def occluded_instances(
    bvh: MeshBVH, instances: MeshInstances, origins, directions, already=None
):
    """Any-hit over all instances (shadow rays).

    Cheaper than ``intersect_instances``: shadow rays only need a boolean,
    so the per-instance scan skips the normal/albedo gathers and transform.
    ``already`` (optional, [R] bool) marks lanes the caller already knows
    are occluded (e.g. by the sphere any-hit): they stop driving the walks
    and come back True.
    """

    from tpu_render_cluster.render import pallas_kernels

    if already is None:
        already = jnp.zeros((origins.shape[0],), bool)
    if pallas_kernels.pallas_enabled():
        return pallas_kernels.occluded_instances_pallas(
            bvh, instances, origins, directions, already
        )

    def per_instance(occluded, k):
        local_origins, local_directions = _rays_to_object_space(
            instances, k, origins, directions
        )
        occluded = occluded_mesh(bvh, local_origins, local_directions, occluded)
        return occluded, None

    k_count = instances.translation.shape[0]
    occluded, _ = jax.lax.scan(
        per_instance,
        already,
        jnp.arange(k_count),
    )
    return occluded


def rotation_y(angle):
    """[..., 3, 3] rotation about +y for scalar or batched angles."""
    c, s = jnp.cos(angle), jnp.sin(angle)
    zero, one = jnp.zeros_like(c), jnp.ones_like(c)
    return jnp.stack(
        [
            jnp.stack([c, zero, s], axis=-1),
            jnp.stack([zero, one, zero], axis=-1),
            jnp.stack([-s, zero, c], axis=-1),
        ],
        axis=-2,
    )


# ---------------------------------------------------------------------------
# Two-level hierarchy: TLAS over instances (ISSUE 10)
#
# The flat in-kernel instance sweep visits every instance's world AABB per
# ray block; the TLAS replaces that with a threaded skip-link walk over a
# small tree of instance groups, so a block only descends into the
# subtrees its packet actually overlaps. Split of responsibilities under
# jit: instance transforms are TRACED (physics animation), so the tree
# TOPOLOGY must be frame-invariant — it is a median split over instance
# SLOTS (static numpy, memoized per (k_count, leaf_size)), while the
# slot -> instance assignment (a Morton sort of world-AABB centers) and
# the per-node bounds (segment unions over the sorted AABBs) are cheap
# XLA arithmetic recomputed per frame. A Morton-sorted median split is a
# spatial-median build — the SAH sweep of a classic host build needs
# data-dependent topology, which a jitted per-frame build cannot have.


class TlasTopology(NamedTuple):
    """Static (numpy) threaded TLAS topology over ``k_count`` instance
    slots: DFS preorder, skip links, leaves covering contiguous slot
    ranges. ``member`` is the [M, K] node->slot incidence mask the
    per-frame bounds reduction uses."""

    skip: np.ndarray  # [M] int32 — next subtree root (M = done)
    first: np.ndarray  # [M] int32 — leaf slot start (0 for inner)
    count: np.ndarray  # [M] int32 — leaf slot count (0 for inner)
    member: np.ndarray  # [M, K] bool — node covers instance slot
    depth: int  # tree depth (root = 1)


def build_tlas_topology(k_count: int, leaf_size: int) -> TlasTopology:
    """Median split over instance slot ranges, threaded like build_bvh."""
    if k_count < 1:
        raise ValueError("TLAS needs at least one instance")
    leaf_size = max(1, leaf_size)
    nodes: list[dict] = []

    def emit(lo: int, hi: int, level: int) -> tuple[int, int]:
        node_index = len(nodes)
        nodes.append({"lo": lo, "hi": hi, "leaf": hi - lo <= leaf_size})
        if nodes[node_index]["leaf"]:
            return node_index, level
        mid = (lo + hi) // 2
        _, left_depth = emit(lo, mid, level + 1)
        _, right_depth = emit(mid, hi, level + 1)
        return node_index, max(left_depth, right_depth)

    _, depth = emit(0, k_count, 1)
    m = len(nodes)
    # DFS preorder by construction; a node's subtree is the consecutive
    # run of nodes whose slot range nests inside its own.
    skip = np.zeros(m, np.int32)
    first = np.zeros(m, np.int32)
    count = np.zeros(m, np.int32)
    member = np.zeros((m, k_count), bool)
    for i, node in enumerate(nodes):
        j = i + 1
        while j < m and nodes[j]["lo"] >= node["lo"] and nodes[j]["hi"] <= node["hi"]:
            j += 1
        skip[i] = j
        member[i, node["lo"]:node["hi"]] = True
        if node["leaf"]:
            first[i] = node["lo"]
            count[i] = node["hi"] - node["lo"]
    return TlasTopology(
        skip=skip, first=first, count=count, member=member, depth=depth
    )


def tlas_build_counter(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.counter(
        "render_tlas_builds_total",
        "Host-side TLAS topology builds (cache misses of the process-wide "
        "geometry memo — bounded by distinct (instance count, leaf size) "
        "pairs, never frames)",
    )


def tlas_depth_gauge(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.gauge(
        "render_tlas_depth",
        "Depth of the most recently built TLAS topology (root = 1)",
    )


def cached_tlas_topology(k_count: int, leaf_size: int) -> TlasTopology:
    """Memoized ``build_tlas_topology`` (see ``_geometry_cache``)."""
    key = ("tlas", k_count, leaf_size)
    topology = _geometry_cache.get(key)
    if topology is None:
        topology = build_tlas_topology(k_count, leaf_size)
        _geometry_cache[key] = topology
        tlas_build_counter().inc()
        tlas_depth_gauge().set(topology.depth)
    return topology


def tlas_node_bounds(topology: TlasTopology, lo_sorted, hi_sorted):
    """Per-frame TLAS node AABBs from SORTED instance world AABBs.

    ``lo_sorted``/``hi_sorted`` are [K, 3] in slot order (the Morton
    permutation applied). Returns ([M, 3], [M, 3]) node unions — pure
    masked min/max off the static incidence mask, so it jits/vmaps.
    """
    mask = jnp.asarray(topology.member)[:, :, None]  # [M, K, 1]
    node_lo = jnp.min(jnp.where(mask, lo_sorted[None], INF), axis=1)
    node_hi = jnp.max(jnp.where(mask, hi_sorted[None], -INF), axis=1)
    return node_lo, node_hi


def morton_dilate5(v):
    """Spread the low 5 bits of a uint32 to every 3rd position (Morton
    dilation) — THE shared definition for the coherence-key quantization
    (instance slot assignment here, the kernels' fused sort-key epilogue
    and its XLA twin in pallas_kernels)."""
    v = (v | (v << 8)) & jnp.uint32(0x0300F)
    v = (v | (v << 4)) & jnp.uint32(0x030C3)
    v = (v | (v << 2)) & jnp.uint32(0x09249)
    return v


def instance_morton_order(lo_w, hi_w):
    """Morton order of instance world-AABB centers ([K] int32 permutation).

    The TLAS slot assignment: spatially-adjacent instances land in the
    same leaves, so subtree unions stay tight. Ray-INDEPENDENT by design
    (unlike the flat path's near-first anchor sort): a region launch and
    the whole-frame launch derive identical instance orders, keeping the
    tiled-equals-untiled contracts exact. Stable argsort, so equal codes
    (e.g. the degenerate all-overlapping field) keep their original
    relative order.
    """
    centers = 0.5 * (lo_w + hi_w)  # [K, 3]
    lo = jnp.min(centers, axis=0)
    span = jnp.maximum(jnp.max(centers, axis=0) - lo, 1e-6)
    cell = jnp.clip(
        (centers - lo) / span * 32.0, 0.0, 31.0
    ).astype(jnp.uint32)
    code = (
        morton_dilate5(cell[:, 0])
        | (morton_dilate5(cell[:, 1]) << 1)
        | (morton_dilate5(cell[:, 2]) << 2)
    )
    return jnp.argsort(code).astype(jnp.int32)


class MeshSet(NamedTuple):
    """A mesh-backed scene's geometry: one shared BVH + its instances."""

    bvh: MeshBVH
    instances: MeshInstances


def scene_mesh_set(scene_name: str, frame) -> "MeshSet | None":
    """The MeshSet for a scene (None for sphere-only scenes).

    The BVH is a cached constant (host-built once); only the instance
    transforms depend on the frame, so this composes into jit/vmap.
    """
    from tpu_render_cluster.render.scene import (
        build_mesh_instances,
        mesh_kind_for_scene,
    )

    kind = mesh_kind_for_scene(scene_name)
    if kind is None:
        return None
    return MeshSet(
        bvh=cached_mesh_bvh(kind),
        instances=build_mesh_instances(scene_name, frame),
    )


# NOTE: an instance-flattened variant (one K*R-ray traversal call instead
# of a K-step lax.scan) was tried and measured SLOWER on TPU at render ray
# counts (8.9 vs 9.6 f/s): the per-instance grids already fill the device,
# and materializing [K*R, 3] local-ray buffers multiplies HBM traffic by
# K. The scan keeps live buffers at [R, 3] and additionally benefits from
# cross-instance best_t cull seeding.
