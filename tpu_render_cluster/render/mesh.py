"""Triangle meshes with a threaded BVH, TPU-first.

The reference's workers render arbitrary .blend content (reference:
worker/src/rendering/runner/mod.rs:165-176); this module is the TPU-native
counterpart for mesh geometry (SURVEY.md §7 hard part #4: "BVH on TPU").

Design for the TPU's execution model:

- **Static topology, host-built BVH.** Mesh topology never changes across
  frames; animation is rigid per-instance motion. The BVH is built once on
  the host (numpy, median split) over object-space triangles and becomes
  constant device arrays — no per-frame rebuild, no dynamic shapes.
- **Threaded (skip-link) layout = stackless traversal.** Nodes are stored
  in DFS preorder; each carries a ``skip`` link to the next subtree root.
  Traversal is a single moving index: AABB hit on an inner node -> step to
  ``i + 1``; leaf or miss -> jump to ``skip[i]``. No stack, one scalar of
  control state — exactly what ``lax.while_loop`` (and a Pallas scalar
  loop) wants.
- **Packet traversal.** One node sequence is walked per ray *block*; the
  AABB test is vectorized over the block and reduced with ``any``. The
  scalar unit steers, the vector unit tests — divergence costs extra node
  visits, not scalar-per-ray control flow. Camera/shadow packets are
  coherent, so the shared walk skips most of the tree in practice.
- **Instances, not world-space soup.** Rays are transformed into object
  space per instance (rigid transforms preserve t), so K animated
  instances share one static BVH.

``intersect_triangles_brute`` (batched Möller–Trumbore over all
triangles) is the correctness reference the BVH paths are tested against.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Plain Python floats: a module-level jnp constant would be created during
# whatever trace first imports this module (the integrator imports it
# lazily inside traced functions) and leak that trace's tracer into every
# later caller.
INF = 1e30
EPS = 1e-3
# Fixed leaf width: every leaf occupies its own LEAF_SIZE-aligned slot of
# exactly LEAF_SIZE triangle rows (real triangles first, degenerate padding
# after), and traversal always loads exactly LEAF_SIZE rows masked by the
# node's count. A static aligned width keeps the traversal free of
# shape-dependent Python AND makes the Pallas kernel's dynamic sublane
# slices tile-aligned (8 = the f32 sublane tile).
LEAF_SIZE = 16


class OctantTables(NamedTuple):
    """Per-direction-octant threaded node tables ([8*N] rows, octant o's
    table at rows [o*N, (o+1)*N)): the SAME tree re-threaded eight times
    with children ordered NEAR-FIRST along each octant's sign vector.

    A packet whose direction lies in octant o walks table o and reaches
    near subtrees before far ones, so best-t shrinks early and the
    ``tnear < best_t`` cull rejects far subtrees the fixed-DFS walk
    still visits (measured ~1.4x fewer leaf visits on coherent
    packets). Skip links are LOCAL (0..N); leaf ``first`` slots point
    into the shared triangle rows, so only node order differs. Emitted
    by the ``sah`` builder; any order is exact (per-lane results are
    visit-order invariant, strict-< best-t updates).
    """

    bounds_min: jnp.ndarray  # [8N, 3]
    bounds_max: jnp.ndarray  # [8N, 3]
    skip: jnp.ndarray  # [8N] int32 — LOCAL skip links
    first: jnp.ndarray  # [8N] int32 — shared leaf triangle slots
    count: jnp.ndarray  # [8N] int32


class MeshBVH(NamedTuple):
    """Object-space triangle mesh + threaded BVH (all static device arrays).

    Triangles are stored leaf-reordered so every leaf references the
    contiguous range ``[first, first + count)``. ``octant`` (None on
    median builds) carries the eight near-first-ordered node tables the
    mesh trace kernels walk; the base arrays stay the canonical order
    for the XLA walks and standalone kernels.
    """

    # Triangle data, leaf-contiguous order.
    v0: jnp.ndarray  # [T, 3]
    e1: jnp.ndarray  # [T, 3]  (v1 - v0)
    e2: jnp.ndarray  # [T, 3]  (v2 - v0)
    normal: jnp.ndarray  # [T, 3] unit geometric normals
    # Threaded BVH in DFS preorder.
    bounds_min: jnp.ndarray  # [N, 3]
    bounds_max: jnp.ndarray  # [N, 3]
    skip: jnp.ndarray  # [N] int32 — next subtree root (N = done)
    first: jnp.ndarray  # [N] int32 — leaf triangle start (0 for inner)
    count: jnp.ndarray  # [N] int32 — leaf triangle count (0 for inner)
    octant: "OctantTables | None" = None


# ---------------------------------------------------------------------------
# Procedural meshes


def make_box() -> tuple[np.ndarray, np.ndarray]:
    """Unit cube centered at the origin: 8 vertices, 12 triangles."""
    vertices = np.array(
        [
            [-0.5, -0.5, -0.5], [0.5, -0.5, -0.5],
            [0.5, 0.5, -0.5], [-0.5, 0.5, -0.5],
            [-0.5, -0.5, 0.5], [0.5, -0.5, 0.5],
            [0.5, 0.5, 0.5], [-0.5, 0.5, 0.5],
        ],
        np.float32,
    )
    faces = np.array(
        [
            [0, 2, 1], [0, 3, 2],  # -z
            [4, 5, 6], [4, 6, 7],  # +z
            [0, 1, 5], [0, 5, 4],  # -y
            [3, 6, 2], [3, 7, 6],  # +y
            [0, 7, 3], [0, 4, 7],  # -x
            [1, 2, 6], [1, 6, 5],  # +x
        ],
        np.int32,
    )
    return vertices, faces


def make_icosphere(subdivisions: int = 2) -> tuple[np.ndarray, np.ndarray]:
    """Unit icosphere (radius 0.5) via icosahedron midpoint subdivision."""
    phi = (1.0 + np.sqrt(5.0)) / 2.0
    raw = np.array(
        [
            [-1, phi, 0], [1, phi, 0], [-1, -phi, 0], [1, -phi, 0],
            [0, -1, phi], [0, 1, phi], [0, -1, -phi], [0, 1, -phi],
            [phi, 0, -1], [phi, 0, 1], [-phi, 0, -1], [-phi, 0, 1],
        ],
        np.float32,
    )
    vertices = raw / np.linalg.norm(raw, axis=1, keepdims=True)
    faces = np.array(
        [
            [0, 11, 5], [0, 5, 1], [0, 1, 7], [0, 7, 10], [0, 10, 11],
            [1, 5, 9], [5, 11, 4], [11, 10, 2], [10, 7, 6], [7, 1, 8],
            [3, 9, 4], [3, 4, 2], [3, 2, 6], [3, 6, 8], [3, 8, 9],
            [4, 9, 5], [2, 4, 11], [6, 2, 10], [8, 6, 7], [9, 8, 1],
        ],
        np.int32,
    )
    for _ in range(subdivisions):
        midpoint_cache: dict[tuple[int, int], int] = {}
        vertex_list = [v for v in vertices]
        new_faces = []

        def midpoint(a: int, b: int) -> int:
            key = (min(a, b), max(a, b))
            if key not in midpoint_cache:
                m = vertex_list[a] + vertex_list[b]
                m = m / np.linalg.norm(m)
                midpoint_cache[key] = len(vertex_list)
                vertex_list.append(m.astype(np.float32))
            return midpoint_cache[key]

        for f in faces:
            a, b, c = int(f[0]), int(f[1]), int(f[2])
            ab, bc, ca = midpoint(a, b), midpoint(b, c), midpoint(c, a)
            new_faces += [[a, ab, ca], [b, bc, ab], [c, ca, bc], [ab, bc, ca]]
        vertices = np.stack(vertex_list)
        faces = np.array(new_faces, np.int32)
    return (vertices * 0.5).astype(np.float32), faces


# ---------------------------------------------------------------------------
# Host-side BVH build (numpy — runs once per mesh, cached)


def _half_area(lo: np.ndarray, hi: np.ndarray) -> float:
    """Half surface area of an AABB — the SAH's relative cost weight."""
    e = np.maximum(hi - lo, 0.0)
    return float(e[0] * e[1] + e[1] * e[2] + e[2] * e[0])


SAH_BINS = 16


def _sah_partition(
    tri: np.ndarray, centroids: np.ndarray, indices: np.ndarray
) -> tuple[np.ndarray, np.ndarray] | None:
    """Binned-SAH split of ``indices``: minimize area_L*n_L + area_R*n_R
    over SAH_BINS centroid bins on each axis. Returns (left, right) index
    arrays or None when no axis admits a non-degenerate split (the caller
    falls back to the median split, which always makes progress)."""
    c = centroids[indices]
    pts = tri[indices]  # [n, 3, 3] — axis-independent, gathered once
    best = None  # (cost, axis, threshold-bin, bin ids)
    for axis in range(3):
        lo = float(c[:, axis].min())
        hi = float(c[:, axis].max())
        if hi - lo < 1e-12:
            continue
        bins = np.clip(
            ((c[:, axis] - lo) / (hi - lo) * SAH_BINS).astype(np.int64),
            0, SAH_BINS - 1,
        )
        counts = np.bincount(bins, minlength=SAH_BINS)
        # Per-bin bounds over the member triangles' vertices.
        bin_lo = np.full((SAH_BINS, 3), np.inf)
        bin_hi = np.full((SAH_BINS, 3), -np.inf)
        for b in range(SAH_BINS):
            member = bins == b
            if member.any():
                p = pts[member].reshape(-1, 3)
                bin_lo[b] = p.min(axis=0)
                bin_hi[b] = p.max(axis=0)
        # Prefix/suffix sweep: split "after bin b" for b in [0, SAH_BINS-2].
        lo_acc, hi_acc = np.full(3, np.inf), np.full(3, -np.inf)
        left_area = np.zeros(SAH_BINS)
        left_count = np.cumsum(counts)
        for b in range(SAH_BINS):
            lo_acc = np.minimum(lo_acc, bin_lo[b])
            hi_acc = np.maximum(hi_acc, bin_hi[b])
            left_area[b] = _half_area(lo_acc, hi_acc)
        lo_acc, hi_acc = np.full(3, np.inf), np.full(3, -np.inf)
        right_area = np.zeros(SAH_BINS)
        for b in range(SAH_BINS - 1, 0, -1):
            lo_acc = np.minimum(lo_acc, bin_lo[b])
            hi_acc = np.maximum(hi_acc, bin_hi[b])
            right_area[b - 1] = _half_area(lo_acc, hi_acc)
        right_count = left_count[-1] - left_count  # tris in bins > b
        for b in range(SAH_BINS - 1):
            if left_count[b] == 0 or right_count[b] == 0:
                continue
            cost = (
                left_area[b] * left_count[b] + right_area[b] * right_count[b]
            )
            if best is None or cost < best[0]:
                best = (cost, axis, b, bins)
    if best is None:
        return None
    _, axis, threshold, bins = best
    # Split at the SAH bin boundary. (A leaf-aligned variant that snaps
    # the split count to multiples of LEAF_SIZE was tried — 20 perfectly
    # full leaves instead of 26 — and measured SLOWER on the deep scene:
    # the snapped planes make leaf boxes fat enough that extra packet
    # visits outweigh the saved leaf tests. Spatial tightness wins.)
    left = indices[bins <= threshold]
    right = indices[bins > threshold]
    return left, right


def build_bvh(
    vertices: np.ndarray,
    faces: np.ndarray,
    builder: str = "median",
    wide: int = 1,
) -> MeshBVH:
    """Host-side BLAS build, threaded for stackless traversal.

    ``builder`` selects the split strategy — ``median`` (the original
    spatial-median over centroids) or ``sah`` (binned surface-area
    heuristic: better-fitting subtrees and fuller leaves, so traversal
    visits fewer nodes). ``wide`` > 1 collapses the binary tree into an
    N-ary one by pulling grandchildren up (largest-area inner child
    first): the intermediate binary levels disappear, so the threaded
    skip-link walk — which is arity-agnostic — steps through ~half the
    inner nodes for the same leaves. Both knobs change only the ARRAY
    CONTENTS of the MeshBVH, never the traversal contract, so every
    kernel variant consumes any build unchanged.
    """
    leaf_size = LEAF_SIZE
    wide = max(1, min(int(wide), 8))
    if builder not in ("median", "sah"):
        raise ValueError(f"Unknown BVH builder: {builder!r}")
    tri = vertices[faces]  # [T, 3, 3]
    centroids = tri.mean(axis=1)
    order = np.arange(len(faces))

    # Recursive build producing (bounds, leaf range | child list).
    nodes: list[dict] = []

    def emit(indices: np.ndarray) -> int:
        node_index = len(nodes)
        pts = tri[indices].reshape(-1, 3)
        node = {
            "min": pts.min(axis=0),
            "max": pts.max(axis=0),
            "first": -1,
            "count": 0,
            "children": None,
        }
        nodes.append(node)
        if len(indices) <= leaf_size:
            node["first"] = indices  # placeholder; flattened below
            node["count"] = len(indices)
            return node_index
        part = None
        if builder == "sah":
            split = _sah_partition(tri, centroids, indices)
            if split is not None:
                part = split
        if part is None:
            # Median split (the only strategy guaranteed to make progress
            # on degenerate all-equal-centroid sets).
            extent = (
                centroids[indices].max(axis=0) - centroids[indices].min(axis=0)
            )
            axis = int(np.argmax(extent))
            mid = len(indices) // 2
            ordered = indices[
                np.argsort(centroids[indices, axis], kind="stable")
            ]
            part = (ordered[:mid], ordered[mid:])
        left = emit(part[0])
        right = emit(part[1])
        node["children"] = [left, right]
        return node_index

    emit(order)

    if wide > 1:
        # Collapse to N-ary: repeatedly replace the largest-area inner
        # child with its own children (in place, preserving order) until
        # the node has ``wide`` children or only leaves remain. Collapsed
        # inner nodes are dropped at flatten time (unreachable).
        def widen(i: int) -> None:
            node = nodes[i]
            if node["children"] is None:
                return
            children = list(node["children"])
            while len(children) < wide:
                inner = [
                    c for c in children if nodes[c]["children"] is not None
                ]
                if not inner:
                    break
                pick = max(
                    inner,
                    key=lambda c: _half_area(nodes[c]["min"], nodes[c]["max"]),
                )
                at = children.index(pick)
                children[at:at + 1] = nodes[pick]["children"]
            node["children"] = children
            for c in children:
                widen(c)

        widen(0)
        # Re-emit reachable nodes in DFS preorder (drops collapsed ones).
        remap: list[dict] = []

        def reindex(i: int) -> int:
            node = nodes[i]
            new_index = len(remap)
            remap.append(node)
            if node["children"] is not None:
                node["children"] = [reindex(c) for c in node["children"]]
            return new_index

        reindex(0)
        nodes = remap

    # Flatten leaves into aligned LEAF_SIZE-wide slots (-1 = degenerate pad).
    tri_order: list[int] = []
    first = np.zeros(len(nodes), np.int32)
    count = np.zeros(len(nodes), np.int32)
    for i, node in enumerate(nodes):
        if node["children"] is None:
            first[i] = len(tri_order)
            count[i] = node["count"]
            members = [int(t) for t in node["first"]]
            tri_order.extend(members + [-1] * (LEAF_SIZE - len(members)))

    # Skip links: nodes are already in DFS preorder (emit order); a node's
    # skip is the next node that is NOT in its subtree. Compute subtree
    # sizes by walking children (any arity).
    subtree = np.ones(len(nodes), np.int32)

    def size(i: int) -> int:
        node = nodes[i]
        if node["children"] is not None:
            subtree[i] = 1 + sum(size(c) for c in node["children"])
        return subtree[i]

    size(0)
    skip = np.array([i + subtree[i] for i in range(len(nodes))], np.int32)

    # Octant-ordered re-threadings (sah builds): eight DFS orders of the
    # SAME tree, children sorted near-first along each octant's sign
    # vector. Subtree sizes are order-invariant, so the local skip link
    # at position p is simply p + subtree[node]. Leaf slots are shared
    # with the canonical order — only node rows move.
    octant_tables = None
    if builder == "sah":
        centers = [0.5 * (nd["min"] + nd["max"]) for nd in nodes]
        ob_min, ob_max = [], []
        o_skip, o_first, o_count = [], [], []
        for octant in range(8):
            sgn = np.array(
                [
                    1.0 if octant & 1 else -1.0,
                    1.0 if octant & 2 else -1.0,
                    1.0 if octant & 4 else -1.0,
                ]
            )
            order: list[int] = []

            def emit_octant(i: int) -> None:
                order.append(i)
                ch = nodes[i]["children"]
                if ch is None:
                    return
                for c in sorted(
                    ch, key=lambda c: float(centers[c] @ sgn)
                ):
                    emit_octant(c)

            emit_octant(0)
            ob_min.append(np.stack([nodes[i]["min"] for i in order]))
            ob_max.append(np.stack([nodes[i]["max"] for i in order]))
            o_skip.append(
                np.array(
                    [p + subtree[i] for p, i in enumerate(order)], np.int32
                )
            )
            o_first.append(first[order])
            o_count.append(count[order])

    order_array = np.array(tri_order, np.int64)
    real = order_array >= 0
    reordered = np.zeros((len(order_array), 3, 3), np.float32)
    reordered[real] = tri[order_array[real]]  # pad rows stay all-zero
    v0 = reordered[:, 0]
    e1 = reordered[:, 1] - reordered[:, 0]
    e2 = reordered[:, 2] - reordered[:, 0]
    n = np.cross(e1, e2)
    norm = np.linalg.norm(n, axis=1, keepdims=True)
    n = np.where(norm > 1e-12, n / np.maximum(norm, 1e-12), np.array([[0.0, 1.0, 0.0]], np.float32))
    # ensure_compile_time_eval: the first build may happen INSIDE a jit
    # trace (fused_frame_renderer -> scene_mesh_set -> cached_mesh_bvh),
    # where bare jnp.asarray would return trace-local tracers — which the
    # lru_cache would then hand to later EAGER callers (the wavefront
    # driver) as leaked tracers. This forces concrete, cache-safe arrays
    # regardless of the first caller's context.
    with jax.ensure_compile_time_eval():
        if octant_tables is None and builder == "sah":
            octant_tables = OctantTables(
                bounds_min=jnp.asarray(
                    np.concatenate(ob_min).astype(np.float32)
                ),
                bounds_max=jnp.asarray(
                    np.concatenate(ob_max).astype(np.float32)
                ),
                skip=jnp.asarray(np.concatenate(o_skip)),
                first=jnp.asarray(np.concatenate(o_first)),
                count=jnp.asarray(np.concatenate(o_count)),
            )
        return MeshBVH(
            v0=jnp.asarray(v0),
            e1=jnp.asarray(e1),
            e2=jnp.asarray(e2),
            normal=jnp.asarray(n.astype(np.float32)),
            bounds_min=jnp.asarray(np.stack([nd["min"] for nd in nodes])),
            bounds_max=jnp.asarray(np.stack([nd["max"] for nd in nodes])),
            skip=jnp.asarray(skip),
            first=jnp.asarray(first),
            count=jnp.asarray(count),
            octant=octant_tables,
        )


# Process-wide geometry-build memo: host-side BVH/TLAS builds keyed by
# every parameter that shapes the result — (kind, leaf_size) for BLAS
# builds, (k_count, tlas_leaf_size) for TLAS topologies — so the test
# suite and the bucket-ladder recompiles never rebuild a hierarchy they
# have already built this process. An explicit dict (not lru_cache) so
# tests can reset it: tests/conftest.py wires ``reset_geometry_cache``
# into the autouse fixture alongside ``compaction.reset_compile_tracking``.
_geometry_cache: dict[tuple, object] = {}


def reset_geometry_cache() -> None:
    """Forget memoized host-side BVH/TLAS builds (test isolation only:
    the builds are pure, so resetting merely makes the next call rebuild
    — per-test build-count assertions stay independent of earlier
    tests)."""
    _geometry_cache.clear()


def bvh_builder() -> str:
    """``TRC_BVH_BUILDER``: ``sah`` (default, binned SAH) or ``median``.

    A static-jit-arg env tier: read by the UNTRACED drivers/factories and
    threaded into build keys and kernel identities — never read inside a
    traced function (the ``env-tiers`` lint pass pins this), so toggling
    it mid-process builds a fresh tree instead of serving a stale one.
    """
    from tpu_render_cluster.utils.env import env_str

    value = (env_str("TRC_BVH_BUILDER") or "sah").strip().lower()
    return value if value in ("sah", "median") else "sah"


def bvh_wide() -> int:
    """``TRC_BVH_WIDE``: BLAS branching factor after the wide collapse
    (default 4; 1 = binary; clamped to [1, 8]). Same static-jit-arg
    contract as ``bvh_builder``."""
    from tpu_render_cluster.utils.env import env_int

    return max(1, min(env_int("TRC_BVH_WIDE", 4), 8))


def cached_mesh_bvh(
    kind: str, builder: str | None = None, wide: int | None = None
) -> MeshBVH:
    """Memoized BLAS build. The key carries EVERY build parameter —
    (kind, leaf size, builder, wide arity) — so flipping
    ``TRC_BVH_BUILDER``/``TRC_BVH_WIDE`` mid-process can never serve a
    tree built under the old knobs. ``None`` resolves the env tiers
    (callers inside traced code must pass explicit values)."""
    builder = bvh_builder() if builder is None else builder
    wide = bvh_wide() if wide is None else max(1, min(int(wide), 8))
    key = ("bvh", kind, LEAF_SIZE, builder, wide)
    bvh = _geometry_cache.get(key)
    if bvh is None:
        if kind == "box":
            bvh = build_bvh(*make_box(), builder=builder, wide=wide)
        elif kind == "icosphere":
            bvh = build_bvh(*make_icosphere(2), builder=builder, wide=wide)
        else:
            raise ValueError(f"Unknown mesh kind: {kind!r}")
        _geometry_cache[key] = bvh
    return bvh


# ---------------------------------------------------------------------------
# Intersection


def _moller_trumbore(origins, directions, v0, e1, e2):
    """Batched ray x triangle test: [R, T] hit distances (INF = miss)."""
    # pvec = d x e2; det = e1 . pvec  (per ray-triangle pair)
    pvec = jnp.cross(directions[:, None, :], e2[None, :, :])
    det = jnp.sum(e1[None, :, :] * pvec, axis=-1)
    inv_det = 1.0 / jnp.where(jnp.abs(det) < 1e-12, 1e-12, det)
    tvec = origins[:, None, :] - v0[None, :, :]
    u = jnp.sum(tvec * pvec, axis=-1) * inv_det
    qvec = jnp.cross(tvec, e1[None, :, :])
    v = jnp.sum(directions[:, None, :] * qvec, axis=-1) * inv_det
    t = jnp.sum(e2[None, :, :] * qvec, axis=-1) * inv_det
    hit = (
        (jnp.abs(det) > 1e-12)
        & (u >= 0.0)
        & (v >= 0.0)
        & (u + v <= 1.0)
        & (t > EPS)
    )
    return jnp.where(hit, t, INF)


def intersect_triangles_brute(bvh: MeshBVH, origins, directions):
    """Nearest triangle hit by brute force — the correctness reference.

    Returns (t [R], triangle_index [R] int32).
    """
    t = _moller_trumbore(origins, directions, bvh.v0, bvh.e1, bvh.e2)
    best = jnp.argmin(t, axis=-1).astype(jnp.int32)
    return jnp.take_along_axis(t, best[:, None], axis=-1)[:, 0], best


def intersect_bvh_packet(bvh: MeshBVH, origins, directions, init_t=None):
    """Threaded-BVH packet traversal in pure XLA (runs on any platform).

    One node walk is shared by the whole ray packet: the scalar walk index
    advances on the block-wide ``any`` of the per-ray AABB tests. Returns
    (t [R], triangle_index [R] int32) identical to the brute-force result.

    ``init_t`` seeds the per-ray cull distance (e.g. the nearest hit found
    on previously-scanned instances), letting the walk prune subtrees that
    cannot beat an existing hit.
    """
    n_nodes = bvh.skip.shape[0]
    inv_dir = 1.0 / jnp.where(
        jnp.abs(directions) < 1e-12, jnp.where(directions < 0, -1e-12, 1e-12),
        directions,
    )

    def aabb_any_hit(node, best_t):
        lo = (bvh.bounds_min[node][None, :] - origins) * inv_dir
        hi = (bvh.bounds_max[node][None, :] - origins) * inv_dir
        tmin = jnp.max(jnp.minimum(lo, hi), axis=-1)
        tmax = jnp.min(jnp.maximum(lo, hi), axis=-1)
        hit = (tmax >= jnp.maximum(tmin, 0.0)) & (tmin < best_t)
        return jnp.any(hit)

    def leaf_intersect(node, best_t, best_index):
        start = bvh.first[node]
        v0 = jax.lax.dynamic_slice(bvh.v0, (start, 0), (LEAF_SIZE, 3))
        e1 = jax.lax.dynamic_slice(bvh.e1, (start, 0), (LEAF_SIZE, 3))
        e2 = jax.lax.dynamic_slice(bvh.e2, (start, 0), (LEAF_SIZE, 3))
        t = _moller_trumbore(origins, directions, v0, e1, e2)  # [R, LEAF_SIZE]
        in_leaf = jnp.arange(LEAF_SIZE)[None, :] < bvh.count[node]
        t = jnp.where(in_leaf, t, INF)
        local = jnp.argmin(t, axis=-1)
        t_leaf = jnp.take_along_axis(t, local[:, None], axis=-1)[:, 0]
        closer = t_leaf < best_t
        best_t = jnp.where(closer, t_leaf, best_t)
        best_index = jnp.where(
            closer, (start + local).astype(jnp.int32), best_index
        )
        return best_t, best_index

    def cond(carry):
        node, _, _ = carry
        return node < n_nodes

    def body(carry):
        node, best_t, best_index = carry
        hit_any = aabb_any_hit(node, best_t)
        is_leaf = bvh.count[node] > 0

        def on_hit(args):
            best_t, best_index = args

            def leaf(args):
                return leaf_intersect(node, *args)

            best_t, best_index = jax.lax.cond(
                is_leaf, leaf, lambda args: args, (best_t, best_index)
            )
            next_node = jnp.where(is_leaf, bvh.skip[node], node + 1)
            return next_node, best_t, best_index

        def on_miss(args):
            best_t, best_index = args
            return bvh.skip[node], best_t, best_index

        return jax.lax.cond(hit_any, on_hit, on_miss, (best_t, best_index))

    r = origins.shape[0]
    start_t = (
        jnp.full((r,), INF, jnp.float32) if init_t is None else init_t
    )
    init = (jnp.int32(0), start_t, jnp.zeros((r,), jnp.int32))
    _, best_t, best_index = jax.lax.while_loop(cond, body, init)
    return best_t, best_index


def intersect_mesh(bvh: MeshBVH, origins, directions, init_t=None):
    """Nearest mesh hit: Pallas packet kernel on TPU, XLA walk elsewhere."""
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        return pallas_kernels.intersect_bvh_pallas(
            bvh, origins, directions, init_t
        )
    return intersect_bvh_packet(bvh, origins, directions, init_t)


def occluded_bvh_packet(bvh: MeshBVH, origins, directions, already) -> jnp.ndarray:
    """Any-hit packet walk: True per ray once ANY triangle is hit.

    ``already`` marks rays occluded by earlier instances — they stop
    driving traversal (pruning whole subtrees), with no nearest-hit
    ordering or argmin bookkeeping. Deliberately NO data-dependent early
    exit of the walk itself: a per-step all() reduce costs more on TPU
    than the node visits it saves (measured -6% on the mesh bench).
    """
    n_nodes = bvh.skip.shape[0]
    inv_dir = 1.0 / jnp.where(
        jnp.abs(directions) < 1e-12, jnp.where(directions < 0, -1e-12, 1e-12),
        directions,
    )

    def cond(carry):
        node, _ = carry
        return node < n_nodes

    def body(carry):
        node, occluded = carry
        lo = (bvh.bounds_min[node][None, :] - origins) * inv_dir
        hi = (bvh.bounds_max[node][None, :] - origins) * inv_dir
        tmin = jnp.max(jnp.minimum(lo, hi), axis=-1)
        tmax = jnp.min(jnp.maximum(lo, hi), axis=-1)
        packet_hit = (tmax >= jnp.maximum(tmin, 0.0)) & ~occluded
        hit_any = jnp.any(packet_hit)
        is_leaf = bvh.count[node] > 0

        def on_leaf(occluded):
            start = bvh.first[node]
            v0 = jax.lax.dynamic_slice(bvh.v0, (start, 0), (LEAF_SIZE, 3))
            e1 = jax.lax.dynamic_slice(bvh.e1, (start, 0), (LEAF_SIZE, 3))
            e2 = jax.lax.dynamic_slice(bvh.e2, (start, 0), (LEAF_SIZE, 3))
            t = _moller_trumbore(origins, directions, v0, e1, e2)
            in_leaf = jnp.arange(LEAF_SIZE)[None, :] < bvh.count[node]
            return occluded | jnp.any(jnp.where(in_leaf, t, INF) < INF, axis=-1)

        def on_hit(occluded):
            occluded = jax.lax.cond(
                is_leaf, on_leaf, lambda occluded: occluded, occluded
            )
            return jnp.where(is_leaf, bvh.skip[node], node + 1), occluded

        def on_miss(occluded):
            return bvh.skip[node], occluded

        return jax.lax.cond(hit_any, on_hit, on_miss, occluded)

    _, occluded = jax.lax.while_loop(
        cond, body, (jnp.int32(0), already)
    )
    return occluded


def occluded_mesh(bvh: MeshBVH, origins, directions, already) -> jnp.ndarray:
    """Any-hit dispatch: Pallas kernel on TPU, XLA walk elsewhere."""
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        return pallas_kernels.occluded_bvh_pallas(
            bvh, origins, directions, already
        )
    return occluded_bvh_packet(bvh, origins, directions, already)


# ---------------------------------------------------------------------------
# Instances


class MeshInstances(NamedTuple):
    """K similarity-transformed instances of one object-space mesh.

    ``x_world = scale * rotation @ x_obj + translation``. Rays are pulled
    back with the inverse; dividing BOTH the local origin and direction by
    ``scale`` preserves the ray parameter t, so per-instance hits compare
    directly in world units and one static BVH serves every animated
    instance.
    """

    rotation: jnp.ndarray  # [K, 3, 3] pure rotations
    translation: jnp.ndarray  # [K, 3]
    albedo: jnp.ndarray  # [K, 3]
    scale: jnp.ndarray  # [K] uniform per-instance scale


def _rays_to_object_space(instances: MeshInstances, k, origins, directions):
    """World -> object: x' = R^T (x - t) / s; the direction is scaled by
    1/s too, which keeps the ray parameter t in world units.

    The rotation is applied elementwise (the 3-wide contraction unrolled):
    it stays on the VPU in full f32 — precision="highest" einsum forces a
    slow multi-pass MXU lowering, while the default bf16 matmul path puts
    ~0.4% relative error on ray origins (centimeters at scene scale).
    """
    rot = instances.rotation[k]
    inv_scale = 1.0 / instances.scale[k]
    shifted = origins - instances.translation[k][None, :]
    local_origins = (
        shifted[:, 0:1] * rot[0][None, :]
        + shifted[:, 1:2] * rot[1][None, :]
        + shifted[:, 2:3] * rot[2][None, :]
    ) * inv_scale
    local_directions = (
        directions[:, 0:1] * rot[0][None, :]
        + directions[:, 1:2] * rot[1][None, :]
        + directions[:, 2:3] * rot[2][None, :]
    ) * inv_scale
    return local_origins, local_directions


def _normals_to_world(rot, normal_obj):
    """World normal = R n_obj (rigid: inverse transpose == R).

    ``rot`` may be one [3, 3] rotation or a per-ray [R, 3, 3] batch.
    Unrolled elementwise so it stays on the VPU in full f32: the default
    matmul precision rounds through bf16 and visibly tilts shading normals
    (~0.2%).
    """
    return (
        rot[..., :, 0] * normal_obj[:, 0:1]
        + rot[..., :, 1] * normal_obj[:, 1:2]
        + rot[..., :, 2] * normal_obj[:, 2:3]
    )


def intersect_instances(
    bvh: MeshBVH, instances: MeshInstances, origins, directions, init_t=None
):
    """Nearest hit over all instances.

    Returns (t [R], normal [R, 3] world-space, albedo [R, 3]). Rigid
    transforms preserve ray parameter t, so per-instance results compare
    directly. ``init_t`` (optional, [R]) seeds the best-t with a hit the
    caller already knows (the same bounce's sphere/plane t): lanes whose
    seed beats an instance's AABB entry stop driving that instance's walk,
    and a mesh miss returns t == init_t (never closer, so callers using a
    strict ``<`` comparison see it as a miss).

    On TPU this is ONE instanced-kernel launch (grid = ray blocks x
    instances, world-AABB top-level cull per block) followed by XLA
    gathers for the winning triangle's normal and instance's
    rotation/albedo; elsewhere it is a lax.scan of per-instance walks.
    """
    from tpu_render_cluster.render import pallas_kernels

    if pallas_kernels.pallas_enabled():
        t, tri, inst = pallas_kernels.intersect_instances_pallas(
            bvh, instances, origins, directions, init_t
        )
        # A seeded miss comes back with t == init_t (< INF), so the hit
        # test must compare against the seed, not INF — otherwise the
        # tri=0/inst=0 gathers below leak garbage normals/albedo where the
        # scan branch returns zeros.
        seed = INF if init_t is None else init_t
        hit = (t < seed)[:, None]
        normal_obj = bvh.normal[tri]
        rot = instances.rotation[inst]  # [R, 3, 3]
        normal_world = _normals_to_world(rot, normal_obj)
        facing = jnp.sum(normal_world * directions, axis=-1) < 0.0
        normal_world = jnp.where(facing[:, None], normal_world, -normal_world)
        # Misses keep the scan path's zero normal/albedo contract.
        best_normal = jnp.where(hit, normal_world, 0.0)
        best_albedo = jnp.where(hit, instances.albedo[inst], 0.0)
        return t, best_normal, best_albedo

    def per_instance(carry, k):
        best_t, best_normal, best_albedo = carry
        rot = instances.rotation[k]
        local_origins, local_directions = _rays_to_object_space(
            instances, k, origins, directions
        )
        # Seed the walk with the best hit so far: t is in world units for
        # every instance, so earlier instances' hits prune this walk.
        t, tri = intersect_mesh(bvh, local_origins, local_directions, best_t)
        normal_obj = bvh.normal[tri]
        normal_world = _normals_to_world(rot, normal_obj)
        closer = t < best_t
        best_t = jnp.where(closer, t, best_t)
        best_normal = jnp.where(closer[:, None], normal_world, best_normal)
        best_albedo = jnp.where(
            closer[:, None], instances.albedo[k][None, :], best_albedo
        )
        return (best_t, best_normal, best_albedo), None

    r = origins.shape[0]
    init = (
        jnp.full((r,), INF, jnp.float32) if init_t is None else init_t,
        jnp.zeros((r, 3), jnp.float32),
        jnp.zeros((r, 3), jnp.float32),
    )
    k_count = instances.translation.shape[0]
    (best_t, best_normal, best_albedo), _ = jax.lax.scan(
        per_instance, init, jnp.arange(k_count)
    )
    # Flip normals to face the incoming ray.
    facing = jnp.sum(best_normal * directions, axis=-1) < 0.0
    best_normal = jnp.where(facing[:, None], best_normal, -best_normal)
    return best_t, best_normal, best_albedo


def occluded_instances(
    bvh: MeshBVH, instances: MeshInstances, origins, directions, already=None
):
    """Any-hit over all instances (shadow rays).

    Cheaper than ``intersect_instances``: shadow rays only need a boolean,
    so the per-instance scan skips the normal/albedo gathers and transform.
    ``already`` (optional, [R] bool) marks lanes the caller already knows
    are occluded (e.g. by the sphere any-hit): they stop driving the walks
    and come back True.
    """

    from tpu_render_cluster.render import pallas_kernels

    if already is None:
        already = jnp.zeros((origins.shape[0],), bool)
    if pallas_kernels.pallas_enabled():
        return pallas_kernels.occluded_instances_pallas(
            bvh, instances, origins, directions, already
        )

    def per_instance(occluded, k):
        local_origins, local_directions = _rays_to_object_space(
            instances, k, origins, directions
        )
        occluded = occluded_mesh(bvh, local_origins, local_directions, occluded)
        return occluded, None

    k_count = instances.translation.shape[0]
    occluded, _ = jax.lax.scan(
        per_instance,
        already,
        jnp.arange(k_count),
    )
    return occluded


def rotation_y(angle):
    """[..., 3, 3] rotation about +y for scalar or batched angles."""
    c, s = jnp.cos(angle), jnp.sin(angle)
    zero, one = jnp.zeros_like(c), jnp.ones_like(c)
    return jnp.stack(
        [
            jnp.stack([c, zero, s], axis=-1),
            jnp.stack([zero, one, zero], axis=-1),
            jnp.stack([-s, zero, c], axis=-1),
        ],
        axis=-2,
    )


# ---------------------------------------------------------------------------
# Two-level hierarchy: TLAS over instances (ISSUE 10)
#
# The flat in-kernel instance sweep visits every instance's world AABB per
# ray block; the TLAS replaces that with a threaded skip-link walk over a
# small tree of instance groups, so a block only descends into the
# subtrees its packet actually overlaps. Split of responsibilities under
# jit: instance transforms are TRACED (physics animation), so the tree
# TOPOLOGY must be frame-invariant — it is a median split over instance
# SLOTS (static numpy, memoized per (k_count, leaf_size)), while the
# slot -> instance assignment (a Morton sort of world-AABB centers) and
# the per-node bounds (segment unions over the sorted AABBs) are cheap
# XLA arithmetic recomputed per frame. A Morton-sorted median split is a
# spatial-median build — the SAH sweep of a classic host build needs
# data-dependent topology, which a jitted per-frame build cannot have.


class TlasTopology(NamedTuple):
    """Static (numpy) threaded TLAS topology over ``k_count`` instance
    slots: DFS preorder, skip links, leaves covering contiguous slot
    ranges. ``member`` is the [M, K] node->slot incidence mask the
    per-frame bounds reduction uses.

    ``octant_*`` are the eight near-first re-threadings (octant o at
    rows [o*M, (o+1)*M), LOCAL skip links, ``octant_perm`` mapping each
    row to its canonical node for the per-frame bounds gather): slots
    are Morton-ordered, so a median split at depth d cuts the curve's
    most-significant live axis — z, y, x cycling — and visiting the low
    half first is near-first for positive direction components along
    that axis. A heuristic order (any order is exact); the sah-build
    kernels walk the table matching each packet's direction octant.
    """

    skip: np.ndarray  # [M] int32 — next subtree root (M = done)
    first: np.ndarray  # [M] int32 — leaf slot start (0 for inner)
    count: np.ndarray  # [M] int32 — leaf slot count (0 for inner)
    member: np.ndarray  # [M, K] bool — node covers instance slot
    depth: int  # tree depth (root = 1)
    octant_skip: np.ndarray  # [8M] int32 — LOCAL skip links per octant
    octant_first: np.ndarray  # [8M] int32
    octant_count: np.ndarray  # [8M] int32
    octant_perm: np.ndarray  # [8M] int32 — row -> canonical node index


def build_tlas_topology(k_count: int, leaf_size: int) -> TlasTopology:
    """Median split over instance slot ranges, threaded like build_bvh."""
    if k_count < 1:
        raise ValueError("TLAS needs at least one instance")
    leaf_size = max(1, leaf_size)
    nodes: list[dict] = []

    def emit(lo: int, hi: int, level: int) -> tuple[int, int]:
        node_index = len(nodes)
        nodes.append(
            {"lo": lo, "hi": hi, "leaf": hi - lo <= leaf_size,
             "level": level, "children": None}
        )
        if nodes[node_index]["leaf"]:
            return node_index, level
        mid = (lo + hi) // 2
        left, left_depth = emit(lo, mid, level + 1)
        right, right_depth = emit(mid, hi, level + 1)
        nodes[node_index]["children"] = (left, right)
        return node_index, max(left_depth, right_depth)

    _, depth = emit(0, k_count, 1)
    m = len(nodes)
    # DFS preorder by construction; a node's subtree is the consecutive
    # run of nodes whose slot range nests inside its own.
    skip = np.zeros(m, np.int32)
    first = np.zeros(m, np.int32)
    count = np.zeros(m, np.int32)
    member = np.zeros((m, k_count), bool)
    for i, node in enumerate(nodes):
        j = i + 1
        while j < m and nodes[j]["lo"] >= node["lo"] and nodes[j]["hi"] <= node["hi"]:
            j += 1
        skip[i] = j
        member[i, node["lo"]:node["hi"]] = True
        if node["leaf"]:
            first[i] = node["lo"]
            count[i] = node["hi"] - node["lo"]
    subtree = skip - np.arange(m, dtype=np.int32)
    octant_skip = np.zeros(8 * m, np.int32)
    octant_first = np.zeros(8 * m, np.int32)
    octant_count = np.zeros(8 * m, np.int32)
    octant_perm = np.zeros(8 * m, np.int32)
    for octant in range(8):
        order: list[int] = []

        def emit_octant(i: int) -> None:
            order.append(i)
            children = nodes[i]["children"]
            if children is None:
                return
            # Morton MSB cycle: depth 1 splits z, then y, then x.
            axis = (2, 1, 0)[(nodes[i]["level"] - 1) % 3]
            low_first = bool(octant & (1 << axis))
            left, right = children
            emit_octant(left if low_first else right)
            emit_octant(right if low_first else left)

        emit_octant(0)
        base = octant * m
        for position, i in enumerate(order):
            octant_skip[base + position] = position + subtree[i]
            octant_first[base + position] = first[i]
            octant_count[base + position] = count[i]
            octant_perm[base + position] = i
    return TlasTopology(
        skip=skip, first=first, count=count, member=member, depth=depth,
        octant_skip=octant_skip, octant_first=octant_first,
        octant_count=octant_count, octant_perm=octant_perm,
    )


def tlas_build_counter(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.counter(
        "render_tlas_builds_total",
        "Host-side TLAS topology builds (cache misses of the process-wide "
        "geometry memo — bounded by distinct (instance count, leaf size) "
        "pairs, never frames)",
    )


def tlas_depth_gauge(registry=None):
    from tpu_render_cluster.obs import get_registry

    registry = registry if registry is not None else get_registry()
    return registry.gauge(
        "render_tlas_depth",
        "Depth of the most recently built TLAS topology (root = 1)",
    )


def cached_tlas_topology(k_count: int, leaf_size: int) -> TlasTopology:
    """Memoized ``build_tlas_topology`` (see ``_geometry_cache``)."""
    key = ("tlas", k_count, leaf_size)
    topology = _geometry_cache.get(key)
    if topology is None:
        topology = build_tlas_topology(k_count, leaf_size)
        _geometry_cache[key] = topology
        tlas_build_counter().inc()
        tlas_depth_gauge().set(topology.depth)
    return topology


def tlas_node_bounds(topology: TlasTopology, lo_sorted, hi_sorted):
    """Per-frame TLAS node AABBs from SORTED instance world AABBs.

    ``lo_sorted``/``hi_sorted`` are [K, 3] in slot order (the Morton
    permutation applied). Returns ([M, 3], [M, 3]) node unions — pure
    masked min/max off the static incidence mask, so it jits/vmaps.
    """
    mask = jnp.asarray(topology.member)[:, :, None]  # [M, K, 1]
    node_lo = jnp.min(jnp.where(mask, lo_sorted[None], INF), axis=1)
    node_hi = jnp.max(jnp.where(mask, hi_sorted[None], -INF), axis=1)
    return node_lo, node_hi


# ---------------------------------------------------------------------------
# Quantized node tables (ISSUE 15): fixed-point AABB slabs + packed meta
#
# The traversal kernels are memory-bound on node bytes (BVH_BENCH roofline);
# this compresses a node table from 36 B/node (6 f32 slabs + 3 int32 links)
# to 16 B (quant tier 1: 16-bit slabs packed two-per-int32 word) or 12 B
# (tier 2: 8-bit slabs packed six-per-two-words), with skip/first/count
# folded into ONE int32 meta word. Quantization is against the table's own
# union AABB with CONSERVATIVE outward rounding — a reconstructed box always
# CONTAINS its fp32 original (floor/ceil to the grid plus a pad absorbing
# f32 reconstruction rounding), so a quantized walk visits a superset of
# the exact walk's nodes and, because best-t updates compare exact triangle
# hits with a strict <, produces bit-identical results. One jnp
# implementation serves both the static BLAS (constant-folded under jit)
# and the per-frame traced TLAS bounds; tests/test_bvhq.py pins the
# containment property on randomized and degenerate inputs.

# Meta word layout (LSB->MSB): skip [0:16), first/first_unit [16:27),
# count [27:32). Ranges are shape-checkable, so the drivers degrade to the
# unquantized format when a table outgrows them (pallas_kernels.
# resolve_bvh_quant).
QUANT_MAX_NODES = 1 << 16
QUANT_MAX_FIRST_UNITS = 1 << 11
QUANT_MAX_COUNT = 31
# Outward pad in grid cells per tier: guarantees the f32 reconstruction
# (origin + q * cell, the kernels' exact arithmetic) stays outside the
# original bounds even under worst-case rounding of the quantize divide
# and the reconstruction multiply-add (the grid window is padded so one
# cell is never smaller than ~1 ulp of the coordinate scale).
_QUANT_PAD = {1: 4, 2: 1}
_QUANT_BITS = {1: 16, 2: 8}


def quantize_node_tables(lo, hi, skip, first, count, *, quant: int,
                         first_unit: int):
    """Pack a threaded node table into its quantized form.

    ``lo``/``hi`` [N, 3] node AABBs (traced or static), ``skip``/
    ``first``/``count`` [N] int32 links, ``first_unit`` the alignment of
    ``first`` (LEAF_SIZE for BLAS tables, 1 for TLAS slot ranges).
    Returns ``(bq [N, 3|2] int32, meta [N] int32, grid [6] f32)`` where
    ``grid`` = (origin[3], cell[3]) and a slab reconstructs as
    ``origin + q * cell`` (see ``dequantize_node_bounds``).
    """
    bits = _QUANT_BITS[quant]
    levels = (1 << bits) - 1
    pad = _QUANT_PAD[quant]
    lo = jnp.asarray(lo, jnp.float32)
    hi = jnp.asarray(hi, jnp.float32)
    glo = jnp.min(lo, axis=0)
    ghi = jnp.max(hi, axis=0)
    # Window pad: keeps one grid cell >= ~30 ulp of the coordinate scale
    # even for degenerate (flat / single-point) tables, so the per-node
    # cell pad above really is an outward margin after f32 rounding.
    eps = (jnp.abs(glo) + jnp.abs(ghi) + 1.0) * 2e-3
    origin = glo - eps
    cell = ((ghi + eps) - origin) / levels
    inv = 1.0 / cell
    qlo = jnp.clip(
        jnp.floor((lo - origin) * inv).astype(jnp.int32) - pad, 0, levels
    )
    qhi = jnp.clip(
        jnp.ceil((hi - origin) * inv).astype(jnp.int32) + pad, 0, levels
    )
    if quant == 1:
        bq = qlo | (qhi << 16)  # [N, 3]: per-axis (lo | hi << 16)
    else:
        w0 = (
            qlo[:, 0] | (qlo[:, 1] << 8) | (qlo[:, 2] << 16)
            | (qhi[:, 0] << 24)
        )
        w1 = qhi[:, 1] | (qhi[:, 2] << 8)
        bq = jnp.stack([w0, w1], axis=1)  # [N, 2]
    skip = jnp.asarray(skip, jnp.int32)
    first = jnp.asarray(first, jnp.int32)
    count = jnp.asarray(count, jnp.int32)
    meta = skip | ((first // first_unit) << 16) | (count << 27)
    grid = jnp.concatenate([origin, cell])
    return bq, meta, grid


def dequantize_node_bounds(bq, grid, quant: int):
    """XLA twin of the kernels' scalar slab reconstruction — THE one
    arithmetic (``origin + q * cell`` in f32) the containment property is
    asserted against. Returns ([N, 3] lo, [N, 3] hi)."""
    if quant == 1:
        qlo = bq & 0xFFFF
        qhi = (bq >> 16) & 0xFFFF
    else:
        qlo = jnp.stack(
            [bq[:, 0] & 0xFF, (bq[:, 0] >> 8) & 0xFF,
             (bq[:, 0] >> 16) & 0xFF],
            axis=1,
        )
        qhi = jnp.stack(
            [(bq[:, 0] >> 24) & 0xFF, bq[:, 1] & 0xFF,
             (bq[:, 1] >> 8) & 0xFF],
            axis=1,
        )
    origin, cell = grid[None, 0:3], grid[None, 3:6]
    return (
        origin + qlo.astype(jnp.float32) * cell,
        origin + qhi.astype(jnp.float32) * cell,
    )


def unpack_node_meta(meta, *, first_unit: int):
    """XLA twin of the kernels' meta-word unpack: (skip, first, count)."""
    skip = meta & 0xFFFF
    first = ((meta >> 16) & 0x7FF) * first_unit
    count = (meta >> 27) & 0x1F
    return skip, first, count


def morton_dilate5(v):
    """Spread the low 5 bits of a uint32 to every 3rd position (Morton
    dilation) — THE shared definition for the coherence-key quantization
    (instance slot assignment here, the kernels' fused sort-key epilogue
    and its XLA twin in pallas_kernels)."""
    v = (v | (v << 8)) & jnp.uint32(0x0300F)
    v = (v | (v << 4)) & jnp.uint32(0x030C3)
    v = (v | (v << 2)) & jnp.uint32(0x09249)
    return v


def instance_morton_order(lo_w, hi_w):
    """Morton order of instance world-AABB centers ([K] int32 permutation).

    The TLAS slot assignment: spatially-adjacent instances land in the
    same leaves, so subtree unions stay tight. Ray-INDEPENDENT by design
    (unlike the flat path's near-first anchor sort): a region launch and
    the whole-frame launch derive identical instance orders, keeping the
    tiled-equals-untiled contracts exact. Stable argsort, so equal codes
    (e.g. the degenerate all-overlapping field) keep their original
    relative order.
    """
    centers = 0.5 * (lo_w + hi_w)  # [K, 3]
    lo = jnp.min(centers, axis=0)
    span = jnp.maximum(jnp.max(centers, axis=0) - lo, 1e-6)
    cell = jnp.clip(
        (centers - lo) / span * 32.0, 0.0, 31.0
    ).astype(jnp.uint32)
    code = (
        morton_dilate5(cell[:, 0])
        | (morton_dilate5(cell[:, 1]) << 1)
        | (morton_dilate5(cell[:, 2]) << 2)
    )
    return jnp.argsort(code).astype(jnp.int32)


class MeshSet(NamedTuple):
    """A mesh-backed scene's geometry: one shared BVH + its instances."""

    bvh: MeshBVH
    instances: MeshInstances


def scene_mesh_set(
    scene_name: str, frame, builder: str | None = None,
    wide: int | None = None,
) -> "MeshSet | None":
    """The MeshSet for a scene (None for sphere-only scenes).

    The BVH is a cached constant (host-built once); only the instance
    transforms depend on the frame, so this composes into jit/vmap.
    ``builder``/``wide`` select the BLAS build (None = env tiers); the
    jitted renderer factories resolve them OUTSIDE the trace and pass
    explicit values, so the compiled program's tree matches its cache
    key.
    """
    from tpu_render_cluster.render.scene import (
        build_mesh_instances,
        mesh_kind_for_scene,
    )

    kind = mesh_kind_for_scene(scene_name)
    if kind is None:
        return None
    return MeshSet(
        bvh=cached_mesh_bvh(kind, builder, wide),
        instances=build_mesh_instances(scene_name, frame),
    )


# NOTE: an instance-flattened variant (one K*R-ray traversal call instead
# of a K-step lax.scan) was tried and measured SLOWER on TPU at render ray
# counts (8.9 vs 9.6 f/s): the per-instance grids already fill the device,
# and materializing [K*R, 3] local-ray buffers multiplies HBM traffic by
# K. The scan keeps live buffers at [R, 3] and additionally benefits from
# cross-instance best_t cull seeding.
